"""Per-module analysis context shared by all rules during one pass.

Owns everything rules need beyond the current node: the source lines,
import alias table, enclosing class/function stacks, the set of lock
expressions held by enclosing ``with`` blocks, and the suppression
comments (``# graftlint: disable=<rule>[,<rule>...]`` on the offending
line or on a standalone comment line directly above it;
``# graftlint: disable-file=<rule>`` anywhere disables for the whole
file; ``all`` matches every rule).
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from typing import TYPE_CHECKING

from ray_tpu.devtools.findings import Finding

if TYPE_CHECKING:
    from ray_tpu.devtools.registry import Rule

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable(?P<file>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)")


def qualname(node: ast.AST) -> str | None:
    """Dotted name of a Name/Attribute chain ('self._lock',
    'np.random.seed'), or None for anything dynamic."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def scan_suppressions(source: str, lines: list[str]
                      ) -> tuple[dict[int, set[str]], set[str]]:
    """(line -> suppressed rule names/codes, file-level set) from the
    real COMMENT tokens of ``source``. Shared by the per-module context
    and the semantic index (whose cached summaries must honor the same
    directives without re-holding the source)."""
    per_line: dict[int, set[str]] = {}
    file_level: set[str] = set()
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return per_line, file_level  # parse-error finding covers this
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        i = tok.start[0]
        names = {r.strip() for r in m.group("rules").split(",")}
        if m.group("file"):
            file_level |= names
        else:
            per_line.setdefault(i, set()).update(names)
            if lines[i - 1].lstrip().startswith("#"):
                # standalone comment line: also covers the next line
                per_line.setdefault(i + 1, set()).update(names)
    return per_line, file_level


class ModuleContext:
    def __init__(self, path: str, rel_path: str, source: str,
                 tree: ast.Module):
        self.path = path
        self.rel_path = rel_path.replace("\\", "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.findings: list[Finding] = []
        self.class_stack: list[ast.ClassDef] = []
        self.func_stack: list[ast.FunctionDef | ast.AsyncFunctionDef] = []
        self.lock_stack: list[str] = []  # qualnames of held with-contexts
        # local alias -> dotted origin ("np" -> "numpy",
        # "get" -> "ray_tpu.get")
        self.imports: dict[str, str] = {}
        self._suppress_line: dict[int, set[str]] = {}
        self._suppress_file: set[str] = set()
        self._scan_suppressions()

    # -------------------------------------------------------- suppressions

    def _scan_suppressions(self) -> None:
        # real COMMENT tokens only: a directive inside a string literal
        # (a lint test fixture, a doc example) must not suppress anything
        self._suppress_line, self._suppress_file = scan_suppressions(
            self.source, self.lines)

    def is_suppressed(self, rule: "Rule", line: int) -> bool:
        for names in (self._suppress_file,
                      self._suppress_line.get(line, ())):
            if names and ("all" in names or rule.name in names
                          or rule.code in names):
                return True
        return False

    # -------------------------------------------------------- reporting

    def report(self, rule: "Rule", node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        if self.is_suppressed(rule, line):
            return
        text = self.lines[line - 1] if 0 < line <= len(self.lines) else ""
        self.findings.append(Finding(
            path=self.rel_path, line=line, col=col, rule=rule.name,
            code=rule.code, message=message, line_text=text))

    # -------------------------------------------------------- imports

    def track_import(self, node: ast.Import | ast.ImportFrom) -> None:
        if isinstance(node, ast.Import):
            for a in node.names:
                self.imports[a.asname or a.name.split(".")[0]] = a.name
        else:
            mod = node.module or ""
            for a in node.names:
                self.imports[a.asname or a.name] = (
                    f"{mod}.{a.name}" if mod else a.name)

    def resolve(self, name: str) -> str:
        """Fully-qualified origin of a (possibly dotted) local name,
        following the import table one step: 'np.random.seed' ->
        'numpy.random.seed'."""
        head, _, rest = name.partition(".")
        origin = self.imports.get(head, head)
        return f"{origin}.{rest}" if rest else origin

    def resolve_call(self, node: ast.Call) -> str | None:
        qn = qualname(node.func)
        return self.resolve(qn) if qn else None

    # -------------------------------------------------------- stacks

    @property
    def current_function(self):
        return self.func_stack[-1] if self.func_stack else None

    @property
    def current_class(self):
        return self.class_stack[-1] if self.class_stack else None

    def in_async_function(self) -> bool:
        return isinstance(self.current_function, ast.AsyncFunctionDef)

    def holds_lock(self, lock: str) -> bool:
        return lock in self.lock_stack
