"""graftlint CLI.

Usage::

    python -m ray_tpu.devtools.lint ray_tpu/            # human output
    python -m ray_tpu.devtools.lint ray_tpu/ --json     # machine output
    python -m ray_tpu.devtools.lint --list-rules        # rule catalog
    python -m ray_tpu.devtools.lint ray_tpu/ --write-baseline

Exit codes: 0 clean (or everything baselined), 1 new findings,
2 usage/configuration error.

The baseline file (default ``graftlint.baseline.json`` next to the
package, i.e. the repo root) records fingerprints of known findings so
new code is held to a clean bar while legacy findings burn down
incrementally. This repo's committed baseline is empty — keep it that
way by fixing, not baselining.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from ray_tpu.devtools import baseline as baseline_mod
from ray_tpu.devtools.driver import lint_paths
from ray_tpu.devtools.registry import (all_index_rules, all_rules,
                                       index_rule_catalog, rule_catalog)


def repo_root() -> str:
    """The directory containing the ray_tpu package (the repo root in
    a source checkout)."""
    import ray_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(
        ray_tpu.__file__)))


def default_baseline_path() -> str:
    return os.path.join(repo_root(), baseline_mod.DEFAULT_BASELINE)


def run(paths: list[str], *, baseline_path: str | None = None,
        select: set[str] | None = None, root: str | None = None):
    """Programmatic entry point: returns (new, baselined) findings."""
    findings = lint_paths(paths, all_rules(select), root=root or repo_root(),
                          index_rules=all_index_rules(select))
    known = baseline_mod.load(baseline_path) if baseline_path else {}
    return baseline_mod.split(findings, known)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="AST-based concurrency & SPMD-correctness lint "
                    "for the ray_tpu runtime")
    ap.add_argument("paths", nargs="*", help="files or directories "
                    "(default: the ray_tpu package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="baseline file (default: graftlint.baseline."
                         "json at the repo root)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline file")
    ap.add_argument("--write-baseline", action="store_true",
                    help="freeze current findings into the baseline")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="drop baseline entries that no longer fire")
    ap.add_argument("--select", default=None, metavar="RULES",
                    help="comma-separated rule names/codes to run; "
                         "GL012 runs both layers of a promoted rule, "
                         "GL012.inter only the indexed one")
    ap.add_argument("--explain", action="store_true",
                    help="print call-chain evidence under indexed "
                         "findings (human output)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for cls in rule_catalog():
            print(f"{cls.code}  {cls.name}")
            print(f"       {cls.description}")
            print(f"       protects: {cls.invariant}")
        for cls in index_rule_catalog():
            print(f"{cls.selector()}  {cls.name} [indexed]")
            print(f"       {cls.description}")
            print(f"       protects: {cls.invariant}")
        return 0

    paths = args.paths or [os.path.join(repo_root(), "ray_tpu")]
    for p in paths:
        if not os.path.exists(p):
            print(f"graftlint: no such path: {p}", file=sys.stderr)
            return 2
    select = ({s.strip() for s in args.select.split(",")}
              if args.select else None)
    try:
        all_rules(select)  # fail fast on a typoed selector
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2
    baseline_path = None if args.no_baseline else (
        args.baseline or default_baseline_path())

    t0 = time.monotonic()
    findings = lint_paths(paths, all_rules(select), root=repo_root(),
                          index_rules=all_index_rules(select))
    elapsed = time.monotonic() - t0

    if args.write_baseline or args.prune_baseline:
        # a narrowed run (explicit paths / --select) sees only a subset
        # of findings; freezing or pruning from it would silently drop
        # every baseline entry outside the subset
        if args.paths or select:
            print("graftlint: --write-baseline/--prune-baseline need a "
                  "full run; drop the explicit paths and --select",
                  file=sys.stderr)
            return 2
        path = baseline_path or default_baseline_path()
        if args.write_baseline:
            baseline_mod.save(path, findings)
            print(f"graftlint: wrote {len(findings)} finding(s) to {path}")
        else:
            removed = baseline_mod.prune(path, findings)
            print(f"graftlint: pruned {removed} stale baseline entr"
                  f"{'y' if removed == 1 else 'ies'} from {path}")
        return 0

    try:
        known = baseline_mod.load(baseline_path) if baseline_path else {}
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2
    new, baselined = baseline_mod.split(findings, known)

    if args.as_json:
        print(json.dumps({
            "new": [f.to_dict() for f in new],
            "baselined": [f.to_dict() for f in baselined],
            "elapsed_s": round(elapsed, 3),
        }, indent=2))
    else:
        for f in new:
            print(f.render())
            if args.explain and f.chain:
                for hop in f.chain:
                    print(f"    | {hop}")
        summary = (f"graftlint: {len(new)} finding(s)"
                   + (f", {len(baselined)} baselined" if baselined else "")
                   + f" ({elapsed:.2f}s)")
        print(summary if new or baselined else
              f"graftlint: clean ({elapsed:.2f}s)")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
