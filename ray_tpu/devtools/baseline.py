"""Committed-baseline support for incremental burn-down.

A baseline is a JSON file mapping finding fingerprints to a small
descriptive record. Findings whose fingerprint appears in the baseline
are reported as "baselined" and do not fail the run; new findings do.
The workflow:

- ``python -m ray_tpu.devtools.lint ray_tpu/ --write-baseline`` freezes
  the current findings (ideally after fixing everything fixable — the
  committed baseline in this repo is empty and should stay that way).
- Fixing a baselined finding silently shrinks the effective baseline;
  ``--prune-baseline`` rewrites the file without the fixed entries.
"""

from __future__ import annotations

import json
import os

from ray_tpu.devtools.findings import Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE = "graftlint.baseline.json"


def load(path: str) -> dict[str, dict]:
    if not path or not os.path.isfile(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"unsupported baseline version {data.get('version')!r} "
            f"in {path}")
    return data.get("findings", {})


def save(path: str, findings: list[Finding]) -> None:
    entries = {
        f.fingerprint(): {"rule": f.rule, "code": f.code, "path": f.path,
                          "line": f.line, "message": f.message}
        for f in findings
    }
    data = {"version": BASELINE_VERSION, "findings": entries}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def split(findings: list[Finding], baseline: dict[str, dict]
          ) -> tuple[list[Finding], list[Finding]]:
    """(new, baselined) partition of a run's findings."""
    new, old = [], []
    for f in findings:
        (old if f.fingerprint() in baseline else new).append(f)
    return new, old


def prune(path: str, findings: list[Finding]) -> int:
    """Drop baseline entries no longer reported. Returns #removed."""
    baseline = load(path)
    live = {f.fingerprint() for f in findings}
    stale = [fp for fp in baseline if fp not in live]
    if stale:
        kept = [f for f in findings if f.fingerprint() in baseline]
        save(path, kept)
    return len(stale)
