"""The whole-package semantic index — graftlint's second analysis layer.

The single-pass driver sees one module at a time, which is exactly the
blind spot the runtime's worst bugs live in: a blocking call moved into
a helper, an RPC hop through a second service, a lock acquired in a
callee. This module builds, in one pre-pass over every file handed to
the linter:

- a **call graph** keyed by qualified name: ``self.meth()`` resolved
  through the class map (including bases, cross-module), ``self.attr.
  meth()`` through statically-evident attribute types (``self.attr =
  SomeClass(...)``), bare names through nested defs then module
  functions, dotted names through the import-alias table;
- a **class map**: methods, bases, attribute assignments (with the
  constructor type where evident), lock attributes, and the
  ``guarded_by(<lock>)`` annotations scoped to each class/module;
- the **RPC registry**: every ``<server>.register("name", handler[,
  oneway=][, slow=])`` site mapped to its handler function, per
  service class;
- an inferred **effect set** per function — ``blocking`` (with the
  originating label), ``acquires:<lock>`` — computed as a transitive
  closure over the call graph, each effect carrying a witness so the
  interprocedural rules can print the full call chain as evidence.

Dynamic dispatch is where static closure gives up; ``# effects:``
annotations take over there. On the ``def`` line (or the comment line
directly above the def / its first decorator)::

    # effects: none                      <- callee closure cut: inert
    # effects: blocking                  <- treat as blocking
    # effects: acquires:self._lock       <- treat as taking the lock
    # effects: blocking, acquires:_LOCK  <- combine freely

An annotated function's effect set is exactly what it declares —
inference neither adds to nor propagates through it.

Incrementality: per-file extraction results are cached in a JSON file
keyed by content hash (default: a per-root file under the system temp
dir), so a clean re-run re-parses nothing and an edit re-extracts only
the changed files. Linking and the effect closure always recompute —
they are whole-package by definition and cost milliseconds.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
import tempfile
from dataclasses import dataclass, field

from ray_tpu.devtools.context import qualname, scan_suppressions

CACHE_VERSION = 1

_EFFECTS_RE = re.compile(r"#\s*effects:\s*(?P<labels>[\w\s:,.\-]+)")
_ANNOT_RE = re.compile(r"#.*?guarded_by\(\s*(?:self\.)?([\w\.]+)\s*\)")

_RPC_METHODS = ("call", "call_frames", "call_gather")
_BLOCKING_RESOLVED = {"time.sleep", "ray_tpu.get", "ray_tpu.wait",
                      "open"}
_SELF_ADDRS = ("self.address", "self.server.address")
_LOCK_TYPE_TAILS = ("Lock", "RLock", "Condition", "Semaphore",
                    "BoundedSemaphore")


def _is_lock_name(qn: str) -> bool:
    return "lock" in qn.rsplit(".", 1)[-1].lower()


def blocking_call_label(node: ast.Call, resolve) -> str | None:
    """The label of a directly-blocking call, or None. ``resolve`` maps
    a local dotted name to its import-resolved origin. This is THE
    definition of "blocking" — GL012's per-file pass and the index's
    effect inference both use it, so the two layers can never disagree
    about what blocks."""
    f = node.func
    if isinstance(f, (ast.Name, ast.Attribute)):
        qn = qualname(f)
        if qn is not None and resolve(qn) in _BLOCKING_RESOLVED:
            return resolve(qn)
    if isinstance(f, ast.Attribute):
        if f.attr in _RPC_METHODS:
            recv = qualname(f.value)
            if recv is not None and "client" in recv.lower():
                return f"{recv}.{f.attr}"
            if isinstance(f.value, ast.Call):
                inner = qualname(f.value.func)
                if inner is not None and \
                        inner.endswith("RpcClient.shared"):
                    return f"RpcClient.shared().{f.attr}"
        if f.attr == "result" and not node.args and not node.keywords:
            return "Future.result() without timeout"
    return None


def module_name_of(rel_path: str) -> str:
    """Dotted module name for a repo-relative path."""
    p = rel_path.replace("\\", "/")
    if p.endswith(".py"):
        p = p[:-3]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


# --------------------------------------------------------------- extraction


class _Extractor(ast.NodeVisitor):
    """One walk over a module's AST producing the JSON-serializable
    per-file summary the index links from (and the cache stores)."""

    def __init__(self, source: str, rel_path: str):
        self.rel = rel_path.replace("\\", "/")
        self.module = module_name_of(self.rel)
        self.lines = source.splitlines()
        self.imports: dict[str, str] = {}
        self.functions: dict[str, dict] = {}
        self.classes: dict[str, dict] = {}
        self.module_assigns: set[str] = set()
        self.guarded: list[dict] = []   # {scope, lock, line, text}
        self.handlers: list[dict] = []  # {scope, method, handler,
        #                                  oneway, slow, line}
        self._class_stack: list[str] = []
        self._func_stack: list[str] = []
        self._with_stack: list[str] = []
        sup_line, sup_file = scan_suppressions(source, self.lines)
        self.suppress_line = {str(k): sorted(v)
                              for k, v in sup_line.items()}
        self.suppress_file = sorted(sup_file)

    # ------------------------------------------------------------ helpers

    def _resolve(self, name: str) -> str:
        head, _, rest = name.partition(".")
        origin = self.imports.get(head, head)
        return f"{origin}.{rest}" if rest else origin

    def _line_text(self, line: int) -> str:
        return self.lines[line - 1] if 0 < line <= len(self.lines) else ""

    def _fn(self) -> dict | None:
        if not self._func_stack:
            return None
        return self.functions[".".join(self._scope_parts())]

    def _scope_parts(self) -> list[str]:
        return self._class_stack[:1] + self._func_stack

    def _class_info(self) -> dict | None:
        if not self._class_stack:
            return None
        return self.classes[self._class_stack[0]]

    def _effects_annotation(self, node) -> list[str] | None:
        first = min([node.lineno]
                    + [d.lineno for d in node.decorator_list])
        for line in (node.lineno, first - 1):
            text = self._line_text(line)
            m = _EFFECTS_RE.search(text)
            if m:
                return [t.strip() for t in m.group("labels").split(",")
                        if t.strip()]
        return None

    # ------------------------------------------------------------- visits

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.imports[a.asname or a.name.split(".")[0]] = a.name

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        mod = node.module or ""
        for a in node.names:
            self.imports[a.asname or a.name] = (
                f"{mod}.{a.name}" if mod else a.name)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        if self._class_stack or self._func_stack:
            # nested classes are rare and out of static reach
            self.generic_visit(node)
            return
        self.classes[node.name] = {
            "line": node.lineno,
            "bases": [self._resolve(qn) for qn in
                      (qualname(b) for b in node.bases)
                      if qn is not None],
            "methods": [],
            "attrs": {},        # attr -> constructor type or ""
            "class_attrs": [],  # names assigned in the class body
        }
        self._class_stack.append(node.name)
        try:
            for child in node.body:
                if isinstance(child, ast.Assign):
                    for t in child.targets:
                        if isinstance(t, ast.Name):
                            self.classes[node.name]["class_attrs"].append(
                                t.id)
                elif isinstance(child, ast.AnnAssign) and \
                        isinstance(child.target, ast.Name):
                    self.classes[node.name]["class_attrs"].append(
                        child.target.id)
                self.visit(child)
        finally:
            self._class_stack.pop()

    def _visit_functiondef(self, node) -> None:
        cls = self._class_info()
        if cls is not None and not self._func_stack:
            cls["methods"].append(node.name)
        self._func_stack.append(node.name)
        key = ".".join(self._scope_parts())
        self.functions[key] = {
            "line": node.lineno,
            "cls": self._class_stack[0] if self._class_stack else "",
            "effects_annot": self._effects_annotation(node),
            "calls": [],      # {raw, kind, name, attr, line, held}
            "blocking": [],   # {label, line, held, local_guard}
            "acquires": [],   # {lock, line, held}
            "rpc": [],        # {kind, line, held, targets}
            "nested": [],
        }
        if len(self._func_stack) > 1:
            outer = ".".join(self._scope_parts()[:-1])
            self.functions[outer]["nested"].append(node.name)
        saved_with = self._with_stack
        self._with_stack = []  # a nested def runs on its caller's stack
        try:
            for dec in node.decorator_list:
                self.visit(dec)
            for child in node.body:
                self.visit(child)
        finally:
            self._with_stack = saved_with
            self._func_stack.pop()

    visit_FunctionDef = _visit_functiondef
    visit_AsyncFunctionDef = _visit_functiondef

    def _visit_with(self, node) -> None:
        held = []
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
            qn = qualname(item.context_expr)
            if qn is not None:
                held.append(qn)
                if _is_lock_name(qn):
                    fn = self._fn()
                    if fn is not None:
                        fn["acquires"].append({
                            "lock": qn, "line": item.context_expr.lineno,
                            "held": list(self._with_stack)})
        self._with_stack.extend(held)
        try:
            for child in node.body:
                self.visit(child)
        finally:
            if held:
                del self._with_stack[-len(held):]

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    def visit_Assign(self, node: ast.Assign) -> None:
        self._record_assign(node.targets, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._record_assign([node.target], node.value)
        self.generic_visit(node)

    def _record_assign(self, targets, value) -> None:
        ctor = ""
        if isinstance(value, ast.Call):
            qn = qualname(value.func)
            if qn is not None:
                ctor = self._resolve(qn)
        for t in targets:
            if isinstance(t, ast.Name) and not self._func_stack and \
                    not self._class_stack:
                self.module_assigns.add(t.id)
            cls = self._class_info()
            if cls is not None and isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self":
                cls["attrs"].setdefault(t.attr, ctor)

    def visit_Call(self, node: ast.Call) -> None:
        fn = self._fn()
        f = node.func
        # ---- RPC handler registration (class map feeding the registry)
        if isinstance(f, ast.Attribute) and f.attr == "register" and \
                len(node.args) >= 2 and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            handler = node.args[1]
            if isinstance(handler, ast.Call) and len(handler.args) == 1:
                # decorator-style wrapper at the register site, e.g.
                # register("c_get", alive(self._h_get), slow=True)
                handler = handler.args[0]
            hname = (handler.attr if isinstance(handler, ast.Attribute)
                     else handler.id if isinstance(handler, ast.Name)
                     else None)
            if hname is not None:
                flags = {k.arg: bool(getattr(k.value, "value", False))
                         for k in node.keywords if k.arg}
                oneway = flags.get("oneway", bool(
                    len(node.args) >= 3
                    and getattr(node.args[2], "value", False)))
                self.handlers.append({
                    "scope": self._class_stack[0]
                    if self._class_stack else "",
                    "method": node.args[0].value, "handler": hname,
                    "oneway": oneway, "slow": flags.get("slow", False),
                    "line": node.lineno})
        if fn is not None:
            self._record_call(node, fn)
        self.generic_visit(node)

    def _record_call(self, node: ast.Call, fn: dict) -> None:
        f = node.func
        held = list(self._with_stack)
        label = blocking_call_label(node, self._resolve)
        if label is not None:
            fn["blocking"].append({
                "label": label, "line": node.lineno, "held": held})
        # ---- synchronous RPC sites (for the handler-reentry graph)
        if isinstance(f, ast.Attribute) and f.attr in _RPC_METHODS:
            recv = qualname(f.value)
            is_client = (recv is not None and "client" in recv.lower()) \
                or (isinstance(f.value, ast.Call)
                    and (qualname(f.value.func) or "").endswith(
                        "RpcClient.shared"))
            if is_client:
                fn["rpc"].append({
                    "kind": f.attr, "line": node.lineno, "held": held,
                    "targets": self._rpc_targets(f.attr, node)})
        # ---- call-graph edge candidates
        qn = qualname(f)
        if qn is None:
            return
        rec = {"raw": qn, "line": node.lineno, "held": held}
        if qn.startswith("self."):
            parts = qn.split(".")[1:]
            if len(parts) == 1:
                rec.update(kind="self", name=parts[0])
            elif len(parts) == 2:
                rec.update(kind="attr", attr=parts[0], name=parts[1])
            else:
                return
        elif "." not in qn:
            rec.update(kind="local", name=qn)
        else:
            rec.update(kind="abs", name=self._resolve(qn))
        fn["calls"].append(rec)

    def _rpc_targets(self, kind: str, node: ast.Call) -> list[dict]:
        """[{self: bool, method: str|None}] for one RPC site. ``call``
        and ``call_frames`` take (addr, method, ...); ``call_gather``
        a literal list of (addr, method, msg) tuples when static."""
        out: list[dict] = []

        def one(addr, meth) -> dict:
            method = None
            if isinstance(meth, ast.Constant) and \
                    isinstance(meth.value, str):
                method = meth.value
            return {"self": qualname(addr) in _SELF_ADDRS,
                    "method": method}

        if kind in ("call", "call_frames") and len(node.args) >= 2:
            out.append(one(node.args[0], node.args[1]))
        elif kind == "call_gather" and node.args and \
                isinstance(node.args[0], (ast.List, ast.Tuple)):
            for elt in node.args[0].elts:
                if isinstance(elt, ast.Tuple) and len(elt.elts) >= 2:
                    out.append(one(elt.elts[0], elt.elts[1]))
        return out

    # ------------------------------------------------- guarded_by comments

    def scan_guarded(self, tree: ast.Module) -> None:
        spans = [(n.lineno, getattr(n, "end_lineno", n.lineno), n.name)
                 for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]
        for i, line in enumerate(self.lines, start=1):
            m = _ANNOT_RE.search(line)
            if not m:
                continue
            scope = ""
            best = None
            for lo, hi, name in spans:
                if lo <= i <= hi and (best is None or lo > best[0]):
                    best = (lo, name)
            if best is not None:
                scope = best[1]
            self.guarded.append({"scope": scope, "lock": m.group(1),
                                 "line": i, "text": line})

    def summary(self) -> dict:
        return {
            "module": self.module, "rel": self.rel,
            "imports": self.imports, "functions": self.functions,
            "classes": self.classes,
            "module_assigns": sorted(self.module_assigns),
            "guarded": self.guarded, "handlers": self.handlers,
            "suppress_line": self.suppress_line,
            "suppress_file": self.suppress_file,
            "lines": self.lines,
        }


def extract_summary(source: str, rel_path: str) -> dict:
    try:
        tree = ast.parse(source, filename=rel_path)
    except SyntaxError:
        # the per-file pass reports GL000; the index just skips it
        return {"module": module_name_of(rel_path), "rel": rel_path,
                "error": "syntax", "imports": {}, "functions": {},
                "classes": {}, "module_assigns": [], "guarded": [],
                "handlers": [], "suppress_line": {},
                "suppress_file": [], "lines": []}
    ex = _Extractor(source, rel_path)
    ex.visit(tree)
    ex.scan_guarded(tree)
    return ex.summary()


# ------------------------------------------------------------------ linking


@dataclass
class BuildStats:
    extracted: list[str] = field(default_factory=list)  # rel paths
    cached: list[str] = field(default_factory=list)


class SemanticIndex:
    """Linked whole-package view over the per-file summaries."""

    def __init__(self, summaries: dict[str, dict],
                 stats: BuildStats | None = None):
        self.files = summaries          # rel path -> summary
        self.stats = stats or BuildStats()
        self.modules: dict[str, dict] = {
            s["module"]: s for s in summaries.values()}
        # "module.Class" -> (summary, class info)
        self.classes: dict[str, tuple[dict, dict]] = {}
        for s in summaries.values():
            for cname, cinfo in s["classes"].items():
                self.classes[f"{s['module']}.{cname}"] = (s, cinfo)
        # function key "module::scope" -> (summary, fn info)
        self.functions: dict[str, tuple[dict, dict]] = {}
        for s in summaries.values():
            for scope, fn in s["functions"].items():
                self.functions[f"{s['module']}::{scope}"] = (s, fn)
        self._link()
        self._close_effects()

    # ---------------------------------------------------------- utilities

    def fn_display(self, key: str) -> str:
        mod, _, scope = key.partition("::")
        return f"{mod}.{scope}"

    def fn_site(self, key: str) -> tuple[str, int]:
        s, fn = self.functions[key]
        return s["rel"], fn["line"]

    def line_text(self, rel: str, line: int) -> str:
        lines = self.files.get(rel, {}).get("lines", [])
        return lines[line - 1] if 0 < line <= len(lines) else ""

    def is_suppressed(self, rel: str, line: int, names: set[str]) -> bool:
        s = self.files.get(rel)
        if s is None:
            return False
        if names & set(s["suppress_file"]):
            return True
        at = set(s["suppress_line"].get(str(line), ()))
        return bool(at and ("all" in at or at & names))

    def resolve_class(self, resolved: str) -> str | None:
        """'pkg.mod.Cls' (import-resolved) -> class key, if indexed."""
        if resolved in self.classes:
            return resolved
        return None

    def class_mro(self, ckey: str) -> list[str]:
        """ckey + resolvable bases (mapped from their import-resolved
        names back to class keys), BFS, cycles guarded."""
        out, todo = [], [ckey]
        while todo:
            k = todo.pop(0)
            if k in out or k not in self.classes:
                continue
            out.append(k)
            s, cinfo = self.classes[k]
            for b in cinfo["bases"]:
                bk = self._resolve_classref(s, b)
                if bk is not None:
                    todo.append(bk)
        return out

    def resolve_method(self, ckey: str, name: str) -> str | None:
        for k in self.class_mro(ckey):
            s, cinfo = self.classes[k]
            if name in cinfo["methods"]:
                return f"{s['module']}::{k.rsplit('.', 1)[1]}.{name}"
        return None

    def class_defines_attr(self, ckey: str, attr: str) -> bool | None:
        """True/False if decidable, None when a base class escapes the
        index (conservative: the attribute may live there)."""
        for k in self.class_mro(ckey):
            s, cinfo = self.classes[k]
            if attr in cinfo["attrs"] or attr in cinfo["class_attrs"]:
                return True
        for k in self.class_mro(ckey):
            s, cinfo = self.classes[k]
            for b in cinfo["bases"]:
                if self._resolve_classref(s, b) is None:
                    return None
        return False

    def _attr_type(self, s: dict, cls: str, attr: str) -> str | None:
        """Class key of ``self.<attr>`` in class ``cls``, if the
        constructor assignment made it statically evident."""
        for k in self.class_mro(f"{s['module']}.{cls}"):
            cs, cinfo = self.classes[k]
            ctor = cinfo["attrs"].get(attr, "")
            if ctor:
                ck = self._resolve_classref(cs, ctor)
                if ck is not None:
                    return ck
        return None

    def _resolve_classref(self, s: dict, resolved: str) -> str | None:
        """Import-resolved constructor string -> class key."""
        if resolved in s["classes"]:
            return f"{s['module']}.{resolved}"
        if resolved in self.classes:
            return resolved
        return None

    def _resolve_global(self, resolved: str) -> str | None:
        """Import-resolved dotted name -> function key, by longest
        module prefix."""
        parts = resolved.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:i])
            s = self.modules.get(mod)
            if s is None:
                continue
            scope = ".".join(parts[i:])
            if scope in s["functions"]:
                return f"{mod}::{scope}"
            if len(parts) - i == 2:
                cls, meth = parts[i], parts[i + 1]
                if cls in s["classes"]:
                    return self.resolve_method(f"{mod}.{cls}", meth)
            return None
        return None

    # ------------------------------------------------------------ linking

    def resolve_lock(self, s: dict, cls: str, raw: str) -> str:
        """Global identity for a lock expression seen in class ``cls``
        of summary ``s``. Statically-evident attribute types unify
        ``self._pool._lock`` with the pool class's own ``_lock``."""
        mod = s["module"]
        if raw.startswith("self."):
            parts = raw.split(".")[1:]
            if len(parts) == 1:
                return f"{mod}.{cls}.{parts[0]}" if cls else \
                    f"{mod}.{parts[0]}"
            if len(parts) == 2 and cls:
                ck = self._attr_type(s, cls, parts[0])
                if ck is not None:
                    return f"{ck}.{parts[1]}"
            return f"{mod}.{cls}.{'.'.join(parts)}"
        if "." not in raw:
            if raw in s["module_assigns"]:
                return f"{mod}.{raw}"
            return f"{mod}.{cls}~{raw}" if cls else f"{mod}~{raw}"
        return f"{mod}.{cls}~{raw}" if cls else f"{mod}~{raw}"

    def _link(self) -> None:
        # guarded lock ids, global: lock id -> (rel, line) of annotation
        self.guarded_ids: dict[str, tuple[str, int]] = {}
        for s in self.files.values():
            for g in s["guarded"]:
                lock = g["lock"]
                raw = lock if lock.startswith("self.") or \
                    not g["scope"] else f"self.{lock}"
                lid = self.resolve_lock(s, g["scope"], raw)
                self.guarded_ids.setdefault(lid, (s["rel"], g["line"]))
        # rpc registry: method name -> [(class key, handler fn key,
        #                                oneway, slow)]
        self.rpc_registry: dict[str, list[tuple]] = {}
        self.handler_fns: dict[str, list[tuple]] = {}  # fn key -> regs
        for s in self.files.values():
            for h in s["handlers"]:
                if not h["scope"]:
                    continue
                ckey = f"{s['module']}.{h['scope']}"
                fkey = self.resolve_method(ckey, h["handler"])
                if fkey is None:
                    continue
                entry = (ckey, fkey, h["method"], h["oneway"], h["slow"])
                self.rpc_registry.setdefault(h["method"], []).append(entry)
                self.handler_fns.setdefault(fkey, []).append(entry)
        # call edges: fn key -> [(callee key, site dict)]
        self.edges: dict[str, list[tuple[str, dict]]] = {}
        for key, (s, fn) in self.functions.items():
            scope = key.partition("::")[2]
            cls = fn["cls"]
            out = []
            for c in fn["calls"]:
                callee = self._resolve_callee(s, scope, cls, c)
                if callee is not None and callee in self.functions:
                    out.append((callee, c))
            self.edges[key] = out
        self.redges: dict[str, list[tuple[str, dict]]] = {}
        for caller, outs in self.edges.items():
            for callee, site in outs:
                self.redges.setdefault(callee, []).append((caller, site))

    def _resolve_callee(self, s: dict, scope: str, cls: str,
                        c: dict) -> str | None:
        mod = s["module"]
        kind = c.get("kind")
        if kind == "self" and cls:
            return self.resolve_method(f"{mod}.{cls}", c["name"])
        if kind == "attr" and cls:
            ck = self._attr_type(s, cls, c["attr"])
            if ck is not None:
                return self.resolve_method(ck, c["name"])
            return None
        if kind == "local":
            # nested def of the current function first, then module fn,
            # then an import-resolved origin
            fn = s["functions"].get(scope)
            if fn and c["name"] in fn["nested"]:
                return f"{mod}::{scope}.{c['name']}"
            if c["name"] in s["functions"]:
                return f"{mod}::{c['name']}"
            origin = s["imports"].get(c["name"])
            if origin is not None:
                return self._resolve_global(origin)
            return None
        if kind == "abs":
            return self._resolve_global(c["name"])
        return None

    # ----------------------------------------------------------- effects

    def _annotated(self, key: str) -> list[str] | None:
        return self.functions[key][1]["effects_annot"]

    def _close_effects(self) -> None:
        """Fixpoint over the call graph for ``blocking`` and
        ``acquires:<lock>``; each entry carries a witness for chain
        reconstruction: ("direct", rel, line, label) |
        ("call", callee_key, rel, line) | ("annot", rel, line)."""
        self.blocking: dict[str, tuple] = {}
        self.acquires: dict[str, dict[str, tuple]] = {}
        todo: list[str] = []

        def set_blocking(key: str, witness: tuple) -> None:
            if key not in self.blocking:
                self.blocking[key] = witness
                todo.append(key)

        def add_acquire(key: str, lock: str, witness: tuple) -> None:
            locks = self.acquires.setdefault(key, {})
            if lock not in locks:
                locks[lock] = witness
                todo.append(key)

        for key, (s, fn) in self.functions.items():
            annot = fn["effects_annot"]
            rel, line = s["rel"], fn["line"]
            if annot is not None:
                for label in annot:
                    if label == "blocking":
                        set_blocking(key, ("annot", rel, line))
                    elif label.startswith("acquires:"):
                        lock = self.resolve_lock(
                            s, fn["cls"], label.split(":", 1)[1])
                        add_acquire(key, lock, ("annot", rel, line))
                continue
            for b in fn["blocking"]:
                set_blocking(key, ("direct", rel, b["line"], b["label"]))
            for r in fn["rpc"]:
                set_blocking(key, ("direct", rel, r["line"],
                                   f"sync RPC .{r['kind']}()"))
            for a in fn["acquires"]:
                lock = self.resolve_lock(s, fn["cls"], a["lock"])
                add_acquire(key, lock, ("direct", rel, a["line"],
                                        f"with {a['lock']}"))

        while todo:
            key = todo.pop()
            for caller, site in self.redges.get(key, ()):
                if self._annotated(caller) is not None:
                    continue  # annotation freezes the caller's effects
                rel = self.functions[caller][0]["rel"]
                if key in self.blocking and caller not in self.blocking:
                    set_blocking(caller,
                                 ("call", key, rel, site["line"]))
                for lock in self.acquires.get(key, {}):
                    if lock not in self.acquires.get(caller, {}):
                        add_acquire(caller, lock,
                                    ("call", key, rel, site["line"]))

    # ------------------------------------------------------------- chains

    def blocking_chain(self, key: str) -> list[str]:
        """Human-readable witness path from ``key`` to the blocking
        primitive."""
        out: list[str] = []
        seen = set()
        while key not in seen:
            seen.add(key)
            w = self.blocking.get(key)
            if w is None:
                break
            if w[0] == "direct":
                out.append(f"{w[1]}:{w[2]}: {self.fn_display(key)} "
                           f"blocks: {w[3]}")
                break
            if w[0] == "annot":
                out.append(f"{w[1]}:{w[2]}: {self.fn_display(key)} "
                           f"declared '# effects: blocking'")
                break
            _, callee, rel, line = w
            out.append(f"{rel}:{line}: {self.fn_display(key)} calls "
                       f"{self.fn_display(callee)}")
            key = callee
        return out

    def acquire_chain(self, key: str, lock: str) -> list[str]:
        out: list[str] = []
        seen = set()
        while key not in seen:
            seen.add(key)
            w = self.acquires.get(key, {}).get(lock)
            if w is None:
                break
            if w[0] == "direct":
                out.append(f"{w[1]}:{w[2]}: {self.fn_display(key)} "
                           f"acquires {lock} ({w[3]})")
                break
            if w[0] == "annot":
                out.append(f"{w[1]}:{w[2]}: {self.fn_display(key)} "
                           f"declared '# effects: acquires:{lock}'")
                break
            _, callee, rel, line = w
            out.append(f"{rel}:{line}: {self.fn_display(key)} calls "
                       f"{self.fn_display(callee)}")
            key = callee
        return out


# -------------------------------------------------------------------- cache


def default_cache_path(root: str) -> str:
    tag = hashlib.sha1(os.path.abspath(root).encode()).hexdigest()[:12]
    uid = getattr(os, "getuid", lambda: 0)()
    return os.path.join(tempfile.gettempdir(),
                        f"graftlint-index-{uid}-{tag}.json")


def _load_cache(path: str) -> dict:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        if data.get("version") != CACHE_VERSION:
            return {}
        return data.get("files", {})
    except (OSError, ValueError):
        return {}


def _save_cache(path: str, files: dict) -> None:
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"version": CACHE_VERSION, "files": files}, f)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def build_index(paths: list[str], root: str,
                cache_path: str | None = None) -> SemanticIndex:
    """Build the index over ``paths`` (absolute file paths), caching
    per-file extraction by content hash. ``cache_path=''`` disables
    the cache entirely."""
    root = os.path.abspath(root).rstrip(os.sep)
    if cache_path is None:
        cache_path = default_cache_path(root)
    cached = _load_cache(cache_path) if cache_path else {}
    out: dict[str, dict] = {}
    fresh: dict[str, dict] = {}
    stats = BuildStats()
    for path in paths:
        rel = path[len(root) + 1:] if path.startswith(root + os.sep) \
            else path
        rel = rel.replace("\\", "/")
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            continue
        digest = hashlib.sha1(raw).hexdigest()
        entry = cached.get(rel)
        if entry is not None and entry.get("hash") == digest:
            out[rel] = entry["summary"]
            fresh[rel] = entry
            stats.cached.append(rel)
            continue
        summary = extract_summary(
            raw.decode("utf-8", errors="replace"), rel)
        out[rel] = summary
        fresh[rel] = {"hash": digest, "summary": summary}
        stats.extracted.append(rel)
    if cache_path:
        _save_cache(cache_path, fresh)
    return SemanticIndex(out, stats)
