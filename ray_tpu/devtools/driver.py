"""The single-pass AST driver, plus the indexed second pass.

One recursive walk per module; every registered rule observes every
node in pre-order while the context keeps the class/function/lock
stacks honest. ``with`` blocks get special treatment: the context
expressions are visited OUTSIDE the held-lock scope, the body inside —
that is what lets the guarded-by rule see exactly which lock
expressions protect a mutation.

``lint_paths`` then builds the whole-package semantic index over the
same file set (incremental, content-hash cached) and runs the
registered index rules once, merging their findings into the per-file
stream. ``lint_source`` stays per-file only — it is the
single-module entry point and has no package to index.
"""

from __future__ import annotations

import ast
import os

from ray_tpu.devtools.context import ModuleContext, qualname
from ray_tpu.devtools.findings import Finding, assign_occurrences
from ray_tpu.devtools.registry import Rule


def _dispatch_table(rules: list[Rule]) -> tuple[dict, list[Rule]]:
    """(node-type -> interested rules, rules interested in everything)."""
    by_type: dict[type, list[Rule]] = {}
    catch_all: list[Rule] = []
    for r in rules:
        if not r.interests:
            catch_all.append(r)
            continue
        for name in r.interests:
            by_type.setdefault(getattr(ast, name), []).append(r)
    return by_type, catch_all


def lint_source(source: str, rel_path: str, rules: list[Rule],
                path: str | None = None) -> list[Finding]:
    try:
        tree = ast.parse(source, filename=rel_path)
    except SyntaxError as e:
        return [Finding(path=rel_path, line=e.lineno or 1, col=0,
                        rule="parse-error", code="GL000",
                        message=f"syntax error: {e.msg}")]
    ctx = ModuleContext(path or rel_path, rel_path, source, tree)
    for r in rules:
        r.begin_module(ctx)
    _walk(tree, ctx, *_dispatch_table(rules))
    for r in rules:
        r.end_module(ctx)
    return assign_occurrences(ctx.findings)


def lint_file(path: str, root: str, rules: list[Rule]) -> list[Finding]:
    root = root.rstrip(os.sep)
    rel = path[len(root) + 1:] if path.startswith(root + os.sep) else path
    with open(path, encoding="utf-8", errors="replace") as f:
        source = f.read()
    return lint_source(source, rel, rules, path=path)


def iter_python_files(paths: list[str]) -> list[str]:
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            out.extend(os.path.join(dirpath, n) for n in sorted(filenames)
                       if n.endswith(".py"))
    return out


def lint_paths(paths: list[str], rules: list[Rule],
               root: str | None = None, *,
               index_rules: list | None = None,
               index_cache: str | None = None) -> list[Finding]:
    """Per-file pass over every file under ``paths``, then the index
    rules over the whole set. ``index_rules=None`` runs all registered
    index rules; pass ``[]`` to skip the indexed layer (that is the
    pre-v2 single-pass engine, which the interprocedural fixture tests
    rely on). ``index_cache`` overrides the per-root cache file; ``""``
    disables caching."""
    root = os.path.abspath(root or os.getcwd())
    files = [os.path.abspath(p) for p in iter_python_files(paths)]
    findings: list[Finding] = []
    for path in files:
        findings.extend(lint_file(path, root, rules))
    if index_rules is None:
        from ray_tpu.devtools.registry import all_index_rules

        index_rules = all_index_rules()
    if index_rules:
        from ray_tpu.devtools.semindex import build_index

        index = build_index(files, root, cache_path=index_cache)
        for r in index_rules:
            findings.extend(r.check(index))
        assign_occurrences(findings)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings


def _walk(node: ast.AST, ctx: ModuleContext, by_type: dict,
          catch_all: list[Rule]) -> None:
    for r in by_type.get(type(node), ()):
        r.visit(node, ctx)
    for r in catch_all:
        r.visit(node, ctx)

    if isinstance(node, (ast.Import, ast.ImportFrom)):
        ctx.track_import(node)
        return
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        for dec in node.decorator_list:
            _walk(dec, ctx, by_type, catch_all)
        ctx.func_stack.append(node)
        try:
            for child in node.body:
                _walk(child, ctx, by_type, catch_all)
        finally:
            ctx.func_stack.pop()
        return
    if isinstance(node, ast.ClassDef):
        for dec in node.decorator_list:
            _walk(dec, ctx, by_type, catch_all)
        ctx.class_stack.append(node)
        try:
            for child in node.body:
                _walk(child, ctx, by_type, catch_all)
        finally:
            ctx.class_stack.pop()
        return
    if isinstance(node, (ast.With, ast.AsyncWith)):
        held = []
        for item in node.items:
            _walk(item.context_expr, ctx, by_type, catch_all)
            if item.optional_vars is not None:
                _walk(item.optional_vars, ctx, by_type, catch_all)
            qn = qualname(item.context_expr)
            if qn is not None:
                held.append(qn)
        ctx.lock_stack.extend(held)
        try:
            for child in node.body:
                _walk(child, ctx, by_type, catch_all)
        finally:
            if held:
                del ctx.lock_stack[-len(held):]
        return
    for child in ast.iter_child_nodes(node):
        _walk(child, ctx, by_type, catch_all)
