"""Grafana dashboard generator — one panel per catalog metric.

``python -m ray_tpu.devtools.grafana [-o dashboards/ray_tpu.json]``
regenerates the committed dashboard from `ray_tpu.util.metrics_catalog`
(the machine-readable metric registry). Deterministic output: same
catalog, byte-identical JSON — which is what lets the CI drift gate
assert the committed file matches a regeneration, so dashboard, docs,
and code cannot diverge silently.

Panel expression by type (the cluster /metrics page is the datasource,
every series tagged node=/proc= by the aggregation layer):

- counter   -> ``rate(name[5m])``, legended by node
- gauge     -> ``name``
- histogram -> p50/p99 via ``histogram_quantile`` over bucket rates

Rows group panels by metric prefix (train/serve_llm/object_store/...).
"""

from __future__ import annotations

import json

from ray_tpu.util.metrics_catalog import CATALOG

DATASOURCE = {"type": "prometheus", "uid": "${DS_PROMETHEUS}"}

_GROUPS = (
    ("train", "Train"),
    ("collective", "Collectives"),
    ("object_store", "Object store"),
    ("serve_llm", "serve.llm engine"),
    ("serve_slo", "Serving SLO attribution"),
    ("serve", "Serve proxy"),
    ("rl", "RL flywheel"),
    ("profile", "Profiler plane"),
    ("log", "Logs"),
    ("spans", "Span plane"),
    ("watchtower", "Alerts"),
)


def _group_of(name: str) -> str:
    for prefix, title in _GROUPS:
        if name == prefix or name.startswith(prefix + "_"):
            return title
    return "Other"


def _targets(metric: dict) -> list[dict]:
    name, mtype = metric["name"], metric["type"]
    if mtype == "counter":
        return [{"expr": f"rate({name}[5m])",
                 "legendFormat": "{{node}}/{{proc}}", "refId": "A"}]
    if mtype == "gauge":
        return [{"expr": name,
                 "legendFormat": "{{node}}/{{proc}}", "refId": "A"}]
    return [
        {"expr": ("histogram_quantile(0.5, sum by (le) "
                  f"(rate({name}_bucket[5m])))"),
         "legendFormat": "p50", "refId": "A"},
        {"expr": ("histogram_quantile(0.99, sum by (le) "
                  f"(rate({name}_bucket[5m])))"),
         "legendFormat": "p99", "refId": "B"},
    ]


def build_dashboard() -> dict:
    """The dashboard dict, grouped into collapsible rows by prefix.
    Grid: 2 panels per row of 12x8 units; ids assigned in catalog
    order (stable across regenerations by construction)."""
    panels: list[dict] = []
    panel_id = 1
    y = 0
    current_group = None
    x = 0
    for m in CATALOG:
        group = _group_of(m["name"])
        if group != current_group:
            if current_group is not None and x > 0:
                y += 8
            panels.append({
                "id": panel_id, "type": "row", "title": group,
                "collapsed": False,
                "gridPos": {"h": 1, "w": 24, "x": 0, "y": y},
            })
            panel_id += 1
            y += 1
            x = 0
            current_group = group
        panels.append({
            "id": panel_id,
            "type": "timeseries",
            "title": m["name"],
            "description": f"{m['what']} ({m['where']})",
            "datasource": DATASOURCE,
            "targets": _targets(m),
            "fieldConfig": {"defaults": {"custom": {"fillOpacity": 8}},
                            "overrides": []},
            "gridPos": {"h": 8, "w": 12, "x": x, "y": y},
        })
        panel_id += 1
        if x == 0:
            x = 12
        else:
            x = 0
            y += 8
    return {
        "__inputs": [{"name": "DS_PROMETHEUS", "label": "Prometheus",
                      "type": "datasource",
                      "pluginId": "prometheus"}],
        "title": "ray_tpu cluster",
        "uid": "ray-tpu-cluster",
        "tags": ["ray_tpu", "generated"],
        "timezone": "browser",
        "schemaVersion": 39,
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "templating": {"list": [{
            "name": "node", "type": "query",
            "datasource": DATASOURCE,
            "query": "label_values(node)", "refresh": 2,
            "includeAll": True, "multi": True,
        }]},
        "panels": panels,
    }


def dashboard_json() -> str:
    return json.dumps(build_dashboard(), indent=1, sort_keys=True) + "\n"


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(prog="python -m ray_tpu.devtools.grafana")
    ap.add_argument("-o", "--output", default="dashboards/ray_tpu.json")
    args = ap.parse_args(argv)
    import os

    os.makedirs(os.path.dirname(args.output) or ".", exist_ok=True)
    with open(args.output, "w") as f:
        f.write(dashboard_json())
    print(f"wrote {args.output} ({len(CATALOG)} metrics)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
