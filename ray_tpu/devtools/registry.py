"""Rule plugin registry.

A rule is a class with a ``name`` (used in suppression comments and
baselines), a ``code`` (stable short id, GLnnn), and three hooks the
single-pass driver calls per module: ``begin_module``, ``visit`` (once
per AST node, pre-order), and ``end_module``. Rules that need whole-
module knowledge (call graphs, annotation tables) collect during
``visit`` and emit findings in ``end_module`` — the driver still walks
the tree exactly once.

Registering is one decorator::

    @register
    class MyRule(Rule):
        name = "my-rule"
        code = "GL099"
        description = "..."
        invariant = "..."

A second registry holds **index rules** — whole-package checks that
run once over the semantic index (``semindex.SemanticIndex``) after
every file's single pass. An index rule has the same identity fields
plus a ``subcode``: the interprocedural layer of an existing rule
keeps that rule's name/code and sets ``subcode = "inter"``, so
``--select GL012`` runs both layers while ``--select GL012.inter``
runs only the indexed one. Suppression comments match by name or code
and therefore cover both layers of a promoted rule.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:
    import ast

    from ray_tpu.devtools.context import ModuleContext
    from ray_tpu.devtools.findings import Finding
    from ray_tpu.devtools.semindex import SemanticIndex

_RULES: dict[str, type["Rule"]] = {}
_INDEX_RULES: dict[str, type["IndexRule"]] = {}


class Rule:
    name: str = ""
    code: str = ""
    description: str = ""
    invariant: str = ""  # the runtime property the rule protects
    # AST node class names this rule's visit() wants; () means every
    # node. The driver builds a per-type dispatch table from these so a
    # rule only pays for nodes it can act on.
    interests: tuple[str, ...] = ()

    def begin_module(self, ctx: "ModuleContext") -> None:
        pass

    def visit(self, node: "ast.AST", ctx: "ModuleContext") -> None:
        pass

    def end_module(self, ctx: "ModuleContext") -> None:
        pass


class IndexRule:
    """A whole-package check over the semantic index. ``check`` runs
    once per lint invocation and returns findings; call-chain evidence
    goes in each finding's ``chain``."""

    name: str = ""
    code: str = ""
    subcode: str = ""  # "inter" for the indexed layer of a GLnnn rule
    description: str = ""
    invariant: str = ""

    @classmethod
    def selector(cls) -> str:
        return f"{cls.code}.{cls.subcode}" if cls.subcode else cls.code

    def check(self, index: "SemanticIndex") -> list["Finding"]:
        raise NotImplementedError

    def report(self, index: "SemanticIndex", findings: list["Finding"],
               rel: str, line: int, message: str,
               chain: tuple | list = ()) -> None:
        from ray_tpu.devtools.findings import Finding

        if index.is_suppressed(rel, line, {self.name, self.code}):
            return
        findings.append(Finding(
            path=rel, line=line, col=0, rule=self.name, code=self.code,
            message=message, line_text=index.line_text(rel, line),
            chain=tuple(chain)))


def register(cls: type[Rule]) -> type[Rule]:
    if not cls.name or not cls.code:
        raise ValueError(f"rule {cls.__name__} needs name and code")
    if cls.name in _RULES and _RULES[cls.name] is not cls:
        raise ValueError(f"duplicate rule name {cls.name!r}")
    _RULES[cls.name] = cls
    return cls


def register_index(cls: type[IndexRule]) -> type[IndexRule]:
    if not cls.name or not cls.code:
        raise ValueError(f"index rule {cls.__name__} needs name and code")
    key = cls.selector()
    if key in _INDEX_RULES and _INDEX_RULES[key] is not cls:
        raise ValueError(f"duplicate index rule selector {key!r}")
    _INDEX_RULES[key] = cls
    return cls


def _load_bundled() -> None:
    from ray_tpu.devtools import interproc as _inter  # noqa: F401
    from ray_tpu.devtools import rules as _bundled  # noqa: F401


def _validate_select(select: set[str] | None) -> None:
    """Unknown selectors raise — a typo silently selecting zero rules
    would turn the lint gate into a no-op that reports clean. The known
    set spans both registries so ``GL017`` or ``GL012.inter`` validate
    when filtering per-file rules (and vice versa)."""
    if not select:
        return
    known: set[str] = set()
    for c in _RULES.values():
        known |= {c.name, c.code}
    for c in _INDEX_RULES.values():
        known |= {c.name, c.code, c.selector()}
    unknown = set(select) - known
    if unknown:
        raise ValueError(
            f"unknown rule selector(s): {', '.join(sorted(unknown))}")


def all_rules(select: set[str] | None = None) -> list[Rule]:
    """Instantiate registered per-file rules (loading the bundled rule
    package on first use). ``select`` filters by name or code."""
    _load_bundled()
    _validate_select(select)
    out = []
    for cls in sorted(_RULES.values(), key=lambda c: c.code):
        if select and cls.name not in select and cls.code not in select:
            continue
        out.append(cls())
    return out


def all_index_rules(select: set[str] | None = None) -> list[IndexRule]:
    """Instantiate registered index rules. ``select`` filters by name,
    code, or ``code.subcode`` (``GL012`` runs both layers of a promoted
    rule; ``GL012.inter`` only the indexed one)."""
    _load_bundled()
    _validate_select(select)
    out = []
    for _, cls in sorted(_INDEX_RULES.items()):
        if select and cls.name not in select and cls.code not in select \
                and cls.selector() not in select:
            continue
        out.append(cls())
    return out


def rule_catalog() -> list[type[Rule]]:
    _load_bundled()
    return sorted(_RULES.values(), key=lambda c: c.code)


def index_rule_catalog() -> list[type[IndexRule]]:
    _load_bundled()
    return [_INDEX_RULES[k] for k in sorted(_INDEX_RULES)]
