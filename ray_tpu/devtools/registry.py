"""Rule plugin registry.

A rule is a class with a ``name`` (used in suppression comments and
baselines), a ``code`` (stable short id, GLnnn), and three hooks the
single-pass driver calls per module: ``begin_module``, ``visit`` (once
per AST node, pre-order), and ``end_module``. Rules that need whole-
module knowledge (call graphs, annotation tables) collect during
``visit`` and emit findings in ``end_module`` — the driver still walks
the tree exactly once.

Registering is one decorator::

    @register
    class MyRule(Rule):
        name = "my-rule"
        code = "GL099"
        description = "..."
        invariant = "..."
"""

from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:
    import ast

    from ray_tpu.devtools.context import ModuleContext

_RULES: dict[str, type["Rule"]] = {}


class Rule:
    name: str = ""
    code: str = ""
    description: str = ""
    invariant: str = ""  # the runtime property the rule protects
    # AST node class names this rule's visit() wants; () means every
    # node. The driver builds a per-type dispatch table from these so a
    # rule only pays for nodes it can act on.
    interests: tuple[str, ...] = ()

    def begin_module(self, ctx: "ModuleContext") -> None:
        pass

    def visit(self, node: "ast.AST", ctx: "ModuleContext") -> None:
        pass

    def end_module(self, ctx: "ModuleContext") -> None:
        pass


def register(cls: type[Rule]) -> type[Rule]:
    if not cls.name or not cls.code:
        raise ValueError(f"rule {cls.__name__} needs name and code")
    if cls.name in _RULES and _RULES[cls.name] is not cls:
        raise ValueError(f"duplicate rule name {cls.name!r}")
    _RULES[cls.name] = cls
    return cls


def all_rules(select: set[str] | None = None) -> list[Rule]:
    """Instantiate registered rules (loading the bundled rule package
    on first use). ``select`` filters by name or code; unknown entries
    raise — a typo silently selecting zero rules would turn the lint
    gate into a no-op that reports clean."""
    from ray_tpu.devtools import rules as _bundled  # noqa: F401

    if select:
        known = {c.name for c in _RULES.values()} | {
            c.code for c in _RULES.values()}
        unknown = set(select) - known
        if unknown:
            raise ValueError(
                f"unknown rule selector(s): {', '.join(sorted(unknown))}")
    out = []
    for cls in sorted(_RULES.values(), key=lambda c: c.code):
        if select and cls.name not in select and cls.code not in select:
            continue
        out.append(cls())
    return out


def rule_catalog() -> list[type[Rule]]:
    from ray_tpu.devtools import rules as _bundled  # noqa: F401

    return sorted(_RULES.values(), key=lambda c: c.code)
