"""graftlint — project-specific static analysis for the ray_tpu runtime.

The distributed runtime's correctness rests on invariants that unit
tests cannot cheaply cover: actor event loops must never block on their
own futures (the classic get-in-async-actor deadlock), SPMD-traced code
must stay replica-deterministic and free of hidden host transfers, and
shared nodelet/runtime state must only mutate under its lock. graftlint
turns those invariants into lint rules that run on every PR.

Layout:
- ``findings.py``  — the Finding record + stable fingerprints
- ``registry.py``  — Rule base class + plugin registry
- ``context.py``   — per-module analysis context (imports, stacks,
  suppression comments)
- ``driver.py``    — the single-pass AST walker that feeds every rule
- ``baseline.py``  — committed-baseline load/save/diff for burn-down
- ``lint.py``      — CLI: ``python -m ray_tpu.devtools.lint ray_tpu/``
- ``rules/``       — one module per rule; importing the package
  registers them

See DEVTOOLS.md at the repo root for the rule catalog and the
suppression/baseline workflow.
"""

from ray_tpu.devtools.findings import Finding
from ray_tpu.devtools.registry import Rule, all_rules, register

__all__ = ["Finding", "Rule", "all_rules", "register"]
