"""GL016: bare ``print()`` / raw stderr writes in package code.

The structured log plane (``ray_tpu/utils/logging.py``) gives every
process bounded JSONL records with node/proc/task/trace attribution —
queryable via ``ray_tpu logs``, counted into ``log_records_total``,
watched by the error-rate rule. A bare ``print()`` in library code
bypasses all of it: on a worker the line lands attributed only because
the stream CAPTURE rescues it (and then with no level or logger name);
on the head/nodelet/driver it goes straight to a console nobody tails.
Package code logs through ``logging.getLogger(...)``.

Scope: fires on ``print(...)`` calls and on ``sys.stdout.write`` /
``sys.stderr.write`` calls. CLI/devtools entry points are exempt by
path (``ray_tpu/scripts/``, ``ray_tpu/devtools/`` — their stdout IS
the user interface), as are bench drivers (outside the package).
Deliberate raw-console sites — protocol handshakes parsed from stdout,
the driver-side mirror endpoint whose purpose is the console — carry
justified suppressions."""

from __future__ import annotations

import ast

from ray_tpu.devtools.context import ModuleContext, qualname
from ray_tpu.devtools.registry import Rule, register

_EXEMPT_PARTS = ("scripts/", "devtools/")
_STREAM_WRITES = {"sys.stdout.write", "sys.stderr.write"}


@register
class BarePrintRule(Rule):
    name = "bare-print"
    code = "GL016"
    description = ("bare print()/sys.std{out,err}.write in package "
                   "code bypasses the structured log plane — use "
                   "logging.getLogger(...)")
    invariant = ("library code emits through the structured logger "
                 "(attributed, counted, queryable); raw console "
                 "writes belong to CLI entry points and sanctioned "
                 "protocol/mirror sites only")
    interests = ("Call",)

    def begin_module(self, ctx: ModuleContext) -> None:
        rel = ctx.rel_path
        self._exempt = any(
            rel.startswith(part) or f"/{part}" in rel
            for part in _EXEMPT_PARTS)

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if self._exempt:
            return
        func = node.func
        if isinstance(func, ast.Name) and func.id == "print":
            ctx.report(self, node,
                       "bare print() in package code — raw console "
                       "output bypasses the structured log plane "
                       "(no level, no task/trace attribution, not "
                       "queryable via `ray_tpu logs`); use "
                       "logging.getLogger(...)")
            return
        qn = qualname(func)
        if qn is None:
            return
        if ctx.resolve(qn) in _STREAM_WRITES:
            ctx.report(self, node,
                       f"raw {ctx.resolve(qn)}() in package code — "
                       "bypasses the structured log plane; use "
                       "logging.getLogger(...) (or a sanctioned "
                       "suppression for protocol/console sites)")
