"""GL005: mutation of ``# guarded_by(<lock>)`` state outside its lock.

The nodelet, cluster runtime, and object store share mutable maps and
counters across their RPC-handler pool and background threads. An
attribute whose initializing assignment carries a
``# guarded_by(<lock>)`` comment may only be MUTATED (assigned,
aug-assigned, deleted, or called with a mutating method like
``append``/``pop``/``update``) while an enclosing ``with self.<lock>:``
holds the named lock. Reads are not checked — callers that read a
stale snapshot are a (documented) design choice here; unlocked writes
are races.

Two caller-holds-the-lock conventions are honored, matching existing
code: a ``*_locked`` function-name suffix, and a docstring containing
"caller holds self._lock".
"""

from __future__ import annotations

import ast
import re

from ray_tpu.devtools.context import ModuleContext
from ray_tpu.devtools.registry import Rule, register

# anywhere in a trailing comment, so it composes with existing notes
# (the next line is a doc EXAMPLE, not an annotation of this module):
#   self._queue = deque()  # guarded_by(_lock)  # graftlint: disable=stale-guarded-by
_ANNOT_RE = re.compile(r"#.*?guarded_by\(\s*(?:self\.)?([\w\.]+)\s*\)")

_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "pop", "popleft", "popitem", "clear", "add", "discard", "update",
    "setdefault", "rotate", "sort", "reverse",
}
_INIT_FUNCS = {"__init__", "__new__", "__init_subclass__"}


def _self_attr_of(node: ast.expr) -> str | None:
    """The 'X' in a self.X / self.X[k] / self.X[k].y chain, or None."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        node = node.value
    return None


@register
class GuardedByRule(Rule):
    name = "guarded-by"
    code = "GL005"
    description = ("guarded_by(<lock>)-annotated attribute mutated "
                   "outside a matching `with <lock>:` block")
    invariant = ("annotated shared state only mutates while its lock "
                 "is held")
    interests = ("Assign", "AnnAssign", "AugAssign", "Delete", "Call")

    def begin_module(self, ctx: ModuleContext) -> None:
        # (class name, attr) -> lock qualname ("self._lock")
        self._annotations: dict[tuple[str, str], str] = {}
        # deferred mutation events, judged in end_module once the whole
        # annotation table exists
        self._events: list[tuple] = []
        self._enabled = "guarded_by(" in ctx.source

    # ---------------------------------------------------------------- visit

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if not self._enabled:
            return
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._maybe_annotation(node, ctx)
            for target in self._targets(node):
                attr = _self_attr_of(target)
                if attr is not None:
                    self._record(attr, node, ctx)
        elif isinstance(node, ast.Delete):
            for target in node.targets:
                attr = _self_attr_of(target)
                if attr is not None:
                    self._record(attr, node, ctx)
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr in _MUTATORS):
            attr = _self_attr_of(node.func.value)
            if attr is not None:
                self._record(attr, node, ctx)

    @staticmethod
    def _targets(node) -> list[ast.expr]:
        if isinstance(node, ast.Assign):
            out = []
            for t in node.targets:
                out.extend(t.elts if isinstance(t, ast.Tuple) else [t])
            return out
        return [node.target]

    def _maybe_annotation(self, node, ctx: ModuleContext) -> None:
        line = ctx.lines[node.lineno - 1] if node.lineno <= len(ctx.lines) \
            else ""
        m = _ANNOT_RE.search(line)
        if not m and node.lineno >= 2:
            # annotation on a standalone comment line directly above
            prev = ctx.lines[node.lineno - 2]
            if prev.strip().startswith("#"):
                m = _ANNOT_RE.search(prev)
        if not m or ctx.current_class is None:
            return
        lock = m.group(1)
        if not lock.startswith("self."):
            lock = f"self.{lock}"
        for target in self._targets(node):
            attr = _self_attr_of(target)
            if attr is not None:
                self._annotations[(ctx.current_class.name, attr)] = lock

    def _record(self, attr: str, node: ast.AST, ctx: ModuleContext) -> None:
        if ctx.current_class is None or ctx.current_function is None:
            return
        fn = ctx.current_function
        if fn.name in _INIT_FUNCS:
            return  # construction happens-before sharing
        docs = [(f.name, (ast.get_docstring(f, clean=False) or "").lower())
                for f in ctx.func_stack]
        self._events.append((ctx.current_class.name, attr, node,
                             tuple(ctx.lock_stack), docs))

    # ------------------------------------------------------------ end pass

    def end_module(self, ctx: ModuleContext) -> None:
        for cls, attr, node, held, docs in self._events:
            lock = self._annotations.get((cls, attr))
            if lock is None:
                continue
            if lock in held:
                continue
            if any(name.endswith("_locked") or f"holds {lock}" in doc
                   for name, doc in docs):
                continue
            fn_name = docs[-1][0] if docs else "?"
            ctx.report(self, node,
                       f"self.{attr} is guarded_by({lock}) but "
                       f"{cls}.{fn_name} mutates it without holding "
                       f"the lock")
