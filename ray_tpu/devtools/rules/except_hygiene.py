"""GL006: bare ``except:`` and swallowed cancellation.

Runtime code that catches everything — bare ``except:``, or
``BaseException`` / ``KeyboardInterrupt`` / ``CancelledError`` — and
neither re-raises nor records the exception turns worker cancellation
and operator Ctrl-C into silent no-ops: the task "succeeds", the soak
test hangs, the node never drains. The handler passes when it contains
a ``raise`` or actually uses the bound exception name (storing it for
a supervisor to re-raise is this repo's sanctioned pattern, e.g. the
train/tune thread runners).

One carve-out: ``except KeyboardInterrupt`` in a ``main()`` function
or at module level is the standard clean-^C CLI exit and is allowed.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.context import ModuleContext, qualname
from ray_tpu.devtools.registry import Rule, register

_FATAL = {"BaseException", "KeyboardInterrupt", "CancelledError",
          "GeneratorExit"}


def _caught_names(type_node: ast.expr | None) -> set[str]:
    if type_node is None:
        return set()
    nodes = type_node.elts if isinstance(type_node, ast.Tuple) \
        else [type_node]
    out = set()
    for n in nodes:
        qn = qualname(n)
        if qn:
            out.add(qn.rsplit(".", 1)[-1])
    return out


def _handler_reraises_or_uses(handler: ast.ExceptHandler) -> bool:
    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Raise):
                return True
            if (handler.name and isinstance(node, ast.Name)
                    and node.id == handler.name
                    and isinstance(node.ctx, ast.Load)):
                return True
    return False


@register
class ExceptHygieneRule(Rule):
    name = "except-hygiene"
    code = "GL006"
    description = ("bare except / swallowed BaseException, "
                   "KeyboardInterrupt or CancelledError")
    invariant = ("cancellation and operator interrupts always "
                 "propagate or get recorded, never vanish")
    interests = ("ExceptHandler",)

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if not isinstance(node, ast.ExceptHandler):
            return
        if node.type is None:
            ctx.report(self, node,
                       "bare `except:` also swallows KeyboardInterrupt/"
                       "SystemExit and cancellation; catch Exception "
                       "(or narrower)")
            return
        fatal = _caught_names(node.type) & _FATAL
        if not fatal or _handler_reraises_or_uses(node):
            return
        fn = ctx.current_function
        at_cli_top = fn is None or fn.name == "main"
        if fatal == {"KeyboardInterrupt"} and at_cli_top:
            return  # standard clean-^C exit in a CLI entry point
        ctx.report(self, node,
                   f"except {'/'.join(sorted(fatal))} neither re-raises "
                   f"nor uses the exception: cancellation/interrupts "
                   f"are silently swallowed")
