"""GL004: implicit host transfers inside traced/training-step code.

``.item()``, ``.tolist()``, ``np.asarray(...)`` and
``jax.device_get(...)`` on a traced value force a device→host copy and
a blocking synchronization — inside a jit/pmap/shard_map trace they
either fail at trace time (TracerArrayConversionError) or, worse,
silently fence the accelerator pipeline on every step when applied to
the function's inputs. Training-step code should keep values on device
and transfer explicitly at the logging boundary.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.context import ModuleContext
from ray_tpu.devtools.registry import register
from ray_tpu.devtools.rules._traced import TracedCodeRule

_TRANSFER_METHODS = {"item", "tolist"}
_TRANSFER_CALLS = {"numpy.asarray", "numpy.array", "jax.device_get"}


@register
class HostTransferRule(TracedCodeRule):
    name = "host-transfer"
    code = "GL004"
    description = (".item()/np.asarray/jax.device_get inside "
                   "traced/training-step code")
    invariant = ("traced code never forces a device->host copy; "
                 "transfers happen explicitly at the host boundary")

    def check_call(self, node: ast.Call, ctx: ModuleContext) -> str | None:
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _TRANSFER_METHODS
                and not node.args and not node.keywords):
            return (f".{node.func.attr}() forces a device->host "
                    f"transfer and pipeline sync")
        resolved = ctx.resolve_call(node)
        if resolved in _TRANSFER_CALLS:
            return (f"{resolved}() materializes the value on host "
                    f"inside traced code")
        return None
