"""GL003: nondeterminism reachable from jax-traced code.

SPMD correctness (veScale-style replica determinism) requires every
replica to trace the SAME computation. Wall-clock reads and host-side
RNG (``time.time``, stdlib ``random``, ``np.random``) inside a
``jax.jit`` / ``pmap`` / ``shard_map`` root — or any module-local
helper it calls — bake a per-process value into the trace: replicas
diverge, caches miss, and cross-replica collectives deadlock on
mismatched programs. Key-passing ``jax.random`` is the deterministic
alternative and is never flagged.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.context import ModuleContext
from ray_tpu.devtools.registry import register
from ray_tpu.devtools.rules._traced import TracedCodeRule

_CLOCK_CALLS = {
    "time.time", "time.time_ns", "time.monotonic", "time.perf_counter",
    "time.perf_counter_ns", "datetime.datetime.now",
}
_RNG_PREFIXES = ("random.", "numpy.random.", "secrets.", "uuid.")


@register
class SpmdNondeterminismRule(TracedCodeRule):
    name = "spmd-nondeterminism"
    code = "GL003"
    description = ("wall clock / host RNG reachable from "
                   "jit/pmap/shard_map-traced code")
    invariant = ("traced programs are replica-deterministic: every "
                 "replica traces the same computation")

    def check_call(self, node: ast.Call, ctx: ModuleContext) -> str | None:
        resolved = ctx.resolve_call(node)
        if resolved is None or resolved.startswith("jax."):
            return None  # jax.random is the deterministic path
        if resolved in _CLOCK_CALLS:
            return (f"wall-clock read {resolved}() bakes a per-process "
                    f"value into the trace")
        head = resolved.split(".", 1)[0]
        if resolved.startswith(_RNG_PREFIXES) and (
                head in ("numpy", "random", "secrets", "uuid")):
            return (f"host RNG {resolved}() diverges across replicas; "
                    f"thread a jax.random key instead")
        if resolved == "os.urandom":
            return ("os.urandom() diverges across replicas; thread a "
                    "jax.random key instead")
        return None
