"""GL010: mutation of a ``# guarded_by(<lock>)`` MODULE GLOBAL outside
its lock.

GL005 covers instance attributes; this rule covers the other shape the
codebase actually uses: module-level registries/counters shared across
threads (reward registries, handle caches, process-wide singletons). A
module-level name whose defining assignment carries a
``# guarded_by(<lock>)`` comment may only be mutated while an
enclosing ``with <lock>:`` holds the named lock — the classic bug this
catches is a global locked at most call sites but mutated bare in one
(the inconsistency makes the locked sites useless).

What counts as a mutation, from inside any function:

- rebinding (``NAME = ...``, ``NAME += ...``, ``del NAME``) — only
  when the function declares ``global NAME`` (otherwise the target is
  a local that merely shadows the global);
- item writes (``NAME[k] = ...``, ``del NAME[k]``) and mutating method
  calls (``NAME.append(...)``, ``.pop``, ``.update`` …) — unless the
  function binds ``NAME`` as a parameter or a plain local first.

Module-level (import-time) mutations are exempt: imports happen-before
sharing, same as ``__init__`` for GL005. The two caller-holds-the-lock
conventions GL005 honors apply here too: a ``*_locked`` function-name
suffix, and a docstring containing "holds <lock>".
"""

from __future__ import annotations

import ast
import re

from ray_tpu.devtools.context import ModuleContext
from ray_tpu.devtools.registry import Rule, register

_ANNOT_RE = re.compile(r"#.*?guarded_by\(\s*([\w\.]+)\s*\)")

_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "pop", "popleft", "popitem", "clear", "add", "discard", "update",
    "setdefault", "rotate", "sort", "reverse",
}


def _root_name(node: ast.expr) -> str | None:
    """The NAME in a NAME / NAME[k] / NAME.attr[k] chain, or None."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


@register
class GlobalGuardedByRule(Rule):
    name = "global-guarded-by"
    code = "GL010"
    description = ("guarded_by(<lock>)-annotated module global mutated "
                   "outside a matching `with <lock>:` block")
    invariant = ("annotated module-level shared state only mutates "
                 "while its lock is held — locked at SOME sites and "
                 "bare at others is the bug this exists for")
    interests = ("Assign", "AnnAssign", "AugAssign", "Delete", "Call",
                 "Global")

    def begin_module(self, ctx: ModuleContext) -> None:
        self._annotations: dict[str, str] = {}  # global -> lock qualname
        self._global_decls: dict[int, set[str]] = {}  # fn id -> names
        self._local_binds: dict[int, set[str]] = {}  # fn id -> names
        self._events: list[tuple] = []
        self._enabled = "guarded_by(" in ctx.source

    # ---------------------------------------------------------------- visit

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if not self._enabled:
            return
        fn = ctx.current_function
        if isinstance(node, ast.Global):
            if fn is not None:
                self._global_decls.setdefault(id(fn), set()).update(
                    node.names)
            return
        if fn is None:
            # module level: annotations are declared here, and
            # import-time mutations happen-before sharing
            if isinstance(node, (ast.Assign, ast.AnnAssign)) \
                    and ctx.current_class is None:
                self._maybe_annotation(node, ctx)
            return
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            for target in self._targets(node):
                if isinstance(target, ast.Name):
                    # plain local bind unless `global` declared — track
                    # both; end_module sorts out which it was
                    self._local_binds.setdefault(id(fn), set()).add(
                        target.id)
                    self._record(target.id, "rebind", node, ctx)
                else:
                    name = _root_name(target)
                    if name is not None:
                        self._record(name, "item", node, ctx)
        elif isinstance(node, ast.Delete):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    self._record(target.id, "rebind", node, ctx)
                else:
                    name = _root_name(target)
                    if name is not None:
                        self._record(name, "item", node, ctx)
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr in _MUTATORS):
            name = _root_name(node.func.value)
            if name is not None:
                self._record(name, "item", node, ctx)

    @staticmethod
    def _targets(node) -> list[ast.expr]:
        if isinstance(node, ast.Assign):
            out = []
            for t in node.targets:
                out.extend(t.elts if isinstance(t, ast.Tuple) else [t])
            return out
        return [node.target]

    def _maybe_annotation(self, node, ctx: ModuleContext) -> None:
        line = ctx.lines[node.lineno - 1] if node.lineno <= len(ctx.lines) \
            else ""
        m = _ANNOT_RE.search(line)
        if not m and node.lineno >= 2:
            prev = ctx.lines[node.lineno - 2]
            if prev.strip().startswith("#"):
                m = _ANNOT_RE.search(prev)
        if not m:
            return
        for target in self._targets(node):
            if isinstance(target, ast.Name):
                self._annotations[target.id] = m.group(1)

    def _record(self, name: str, kind: str, node: ast.AST,
                ctx: ModuleContext) -> None:
        fns = tuple(ctx.func_stack)
        docs = [(f.name, (ast.get_docstring(f, clean=False) or "").lower())
                for f in fns]
        self._events.append(
            (name, kind, node, tuple(ctx.lock_stack), docs,
             tuple(id(f) for f in fns),
             tuple(a.arg for f in fns for a in
                   f.args.args + f.args.posonlyargs + f.args.kwonlyargs)))

    # ------------------------------------------------------------ end pass

    def end_module(self, ctx: ModuleContext) -> None:
        for name, kind, node, held, docs, fn_ids, params in self._events:
            lock = self._annotations.get(name)
            if lock is None:
                continue
            declared_global = any(
                name in self._global_decls.get(i, ()) for i in fn_ids)
            if kind == "rebind" and not declared_global:
                continue  # a local that shadows the global
            if kind == "item" and not declared_global and (
                    name in params
                    or any(name in self._local_binds.get(i, ())
                           for i in fn_ids)):
                continue  # parameter / plain local shadows the global
            if lock in held:
                continue
            if any(fn_name.endswith("_locked")
                   or f"holds {lock.lower()}" in doc
                   for fn_name, doc in docs):
                continue
            fn_name = docs[-1][0] if docs else "?"
            ctx.report(self, node,
                       f"{name} is guarded_by({lock}) but {fn_name} "
                       f"mutates it without holding the lock")
