"""Shared infrastructure for rules about jax-traced (SPMD) code.

Builds, during the driver's single pass, a per-module view of:
- which functions are trace roots — decorated with ``jax.jit`` /
  ``pmap`` / ``shard_map`` (including ``partial(jax.jit, ...)`` forms)
  or wrapped by a ``jax.jit(fn)`` / ``shard_map(fn, ...)`` call
  anywhere in the module (the dominant idiom in this repo:
  ``self._update = jax.jit(update)``);
- the module-local call graph (flat, by function name — precise enough
  for the single-file helper functions traced code is built from);
- per-function violation sites collected by the concrete rule.

``end_module`` then walks reachability from the trace roots and reports
only violations inside traced code, naming the root they are reachable
from.
"""

from __future__ import annotations

import ast
from collections import deque

from ray_tpu.devtools.context import ModuleContext, qualname
from ray_tpu.devtools.registry import Rule

_TRACE_TAILS = ("jit", "pmap", "shard_map")


def _is_trace_ref(node: ast.AST, ctx: ModuleContext) -> bool:
    """Does this expression refer to jax.jit / pmap / shard_map?"""
    qn = qualname(node)
    if qn is None:
        return False
    resolved = ctx.resolve(qn)
    return resolved.rsplit(".", 1)[-1] in _TRACE_TAILS and (
        resolved.startswith(("jax", "shard_map"))
        or resolved in _TRACE_TAILS)


def _trace_decorated(fn: ast.FunctionDef | ast.AsyncFunctionDef,
                     ctx: ModuleContext) -> bool:
    for dec in fn.decorator_list:
        if _is_trace_ref(dec, ctx):
            return True
        # @partial(jax.jit, static_argnums=...) and friends
        if isinstance(dec, ast.Call):
            if _is_trace_ref(dec.func, ctx):
                return True
            if any(_is_trace_ref(a, ctx) for a in dec.args):
                return True
    return False


class TracedCodeRule(Rule):
    """Base for rules that flag constructs reachable from traced code.

    Subclasses implement ``check_call(node, ctx) -> str | None`` (a
    violation message, or None) and may extend ``check_node`` for
    non-Call sites.
    """

    interests = ("FunctionDef", "AsyncFunctionDef", "Call")

    def begin_module(self, ctx: ModuleContext) -> None:
        self._roots: set[str] = set()
        self._calls: dict[str, set[str]] = {}
        self._violations: dict[str, list[tuple[ast.AST, str]]] = {}
        self._local_funcs: set[str] = set()
        # no trace machinery in the module -> nothing can be a root
        self._enabled = ("jit" in ctx.source or "pmap" in ctx.source
                         or "shard_map" in ctx.source)

    # ------------------------------------------------------------ pass 1

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if not self._enabled:
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._local_funcs.add(node.name)
            if _trace_decorated(node, ctx):
                self._roots.add(node.name)
            return
        if not isinstance(node, ast.Call):
            return
        fn = ctx.current_function
        scope = fn.name if fn is not None else "<module>"
        # jax.jit(update) / shard_map(step, mesh=...): every Name
        # argument is a traced entry point
        if _is_trace_ref(node.func, ctx):
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    self._roots.add(arg.id)
        callee = qualname(node.func)
        if callee is not None and "." not in callee:
            self._calls.setdefault(scope, set()).add(callee)
        elif callee is not None and callee.startswith("self."):
            # method calls within one class: flat name is close enough
            self._calls.setdefault(scope, set()).add(
                callee.split(".", 1)[1])
        msg = self.check_call(node, ctx)
        if msg is not None:
            self._violations.setdefault(scope, []).append((node, msg))

    # ------------------------------------------------------------ pass 2

    def end_module(self, ctx: ModuleContext) -> None:
        if not self._roots:
            return
        reachable: set[str] = set()
        todo = deque(self._roots)
        while todo:
            name = todo.popleft()
            if name in reachable:
                continue
            reachable.add(name)
            todo.extend(self._calls.get(name, set()) & self._local_funcs)
        for scope, sites in self._violations.items():
            if scope not in reachable:
                continue
            for node, msg in sites:
                ctx.report(self, node,
                           f"{msg} (in {scope!r}, reachable from a "
                           f"jit/pmap/shard_map trace root)")

    # ------------------------------------------------------------ hooks

    def check_call(self, node: ast.Call, ctx: ModuleContext) -> str | None:
        raise NotImplementedError
