"""GL014: a per-item blocking RPC round trip inside a hot loop that
should ride a batch API.

The motivating shape came out of the ISSUE-11 fast-path review: the
submit path paid one SYNCHRONOUS ``schedule_task`` call per task inside
the submit loop — N round trips, N thread-pool dispatches, N socket
writes — when the transport offers batch frames (``schedule_tasks`` /
``actor_calls`` / ``execute_leased`` with a spec list), the submit-side
``Batcher``, and ``call_gather`` (one shared deadline across a fan-out).
A loop like::

    for oid in oids:
        self.client.call(holder, "free_object", {"oid": oid})

serializes N network round trips where one batched frame (or one
``call_gather``) pays a single wait. ``send_oneway`` in a loop is NOT
flagged: the oneway batcher already coalesces those per peer.

Heuristic: inside a ``for`` loop body (own scope — nested function
bodies belong to their own scope, like GL011), flag a blocking
``.call(...)`` / ``.call_frames(...)`` on a client receiver (path
mentions ``client``, or ``RpcClient.shared()``) whose ADDRESS argument
is loop-invariant — it references no name bound by the loop (loop
targets or names assigned anywhere in the body). Loop-variant addresses
(one peer per item) are a fan-out, where ``call_gather`` may still be
better but each call is necessary; ``range(...)`` loops stay quiet —
they are retry/backoff loops, where sequential calls are the point.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.context import ModuleContext, qualname
from ray_tpu.devtools.registry import Rule, register

_RPC_METHODS = {"call", "call_frames"}


def _is_range_loop(node: ast.For) -> bool:
    it = node.iter
    return (isinstance(it, ast.Call)
            and isinstance(it.func, ast.Name)
            and it.func.id == "range")


def _bound_names(node: ast.For) -> set[str]:
    """Names the loop binds: its targets plus anything stored in the
    body (so an address derived per item — ``loc = ...`` then
    ``client.call(loc, ...)`` — counts as loop-variant)."""
    out: set[str] = set()
    for t in ast.walk(node.target):
        if isinstance(t, ast.Name):
            out.add(t.id)
    for child in node.body:
        for sub in ast.walk(child):
            if isinstance(sub, ast.Name) and \
                    isinstance(sub.ctx, ast.Store):
                out.add(sub.id)
    return out


def _client_recv(call: ast.Call) -> str | None:
    f = call.func
    if not isinstance(f, ast.Attribute) or f.attr not in _RPC_METHODS:
        return None
    recv = qualname(f.value)
    if recv is not None and "client" in recv.lower():
        return recv
    if isinstance(f.value, ast.Call):
        inner = qualname(f.value.func)
        if inner is not None and inner.endswith("RpcClient.shared"):
            return "RpcClient.shared()"
    return None


@register
class SequentialRpcInLoopRule(Rule):
    name = "sequential-rpc-in-loop"
    code = "GL014"
    description = ("per-item blocking RPC round trip in a for loop with "
                   "a loop-invariant peer — should ride a batch frame "
                   "or call_gather")
    invariant = ("hot loops never serialize N network round trips the "
                 "transport can coalesce into one frame / one shared "
                 "deadline")
    interests = ("For",)

    def begin_module(self, ctx: ModuleContext) -> None:
        # id(call) -> [call, union of enclosing loops' bound names]
        self._events: dict[int, list] = {}

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if not isinstance(node, ast.For) or _is_range_loop(node):
            return
        bound = _bound_names(node)
        for child in node.body + node.orelse:
            for sub in self._walk_same_scope(child):
                if not isinstance(sub, ast.Call):
                    continue
                if _client_recv(sub) is None:
                    continue
                ent = self._events.setdefault(id(sub), [sub, set()])
                ent[1] |= bound

    @staticmethod
    def _walk_same_scope(node: ast.AST):
        """ast.walk, but never descend into nested function/class
        bodies — a call there belongs to that scope (GL011's rule)."""
        stack = [node]
        while stack:
            n = stack.pop()
            yield n
            for child in ast.iter_child_nodes(n):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda,
                                      ast.ClassDef)):
                    continue
                stack.append(child)

    def end_module(self, ctx: ModuleContext) -> None:
        for call, bound in self._events.values():
            if not call.args:
                continue
            addr_names = {n.id for n in ast.walk(call.args[0])
                          if isinstance(n, ast.Name)}
            if addr_names & bound:
                continue  # loop-variant peer: a genuine fan-out
            method = call.func.attr
            ctx.report(self, call,
                       f"blocking .{method}() to a loop-invariant peer "
                       "inside a for loop — N round trips the transport "
                       "can coalesce; use a batch frame (schedule_tasks/"
                       "actor_calls-style), the submit Batcher, or "
                       "call_gather (one shared deadline)")
