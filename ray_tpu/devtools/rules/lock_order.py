"""GL009: lock-order inversion between nested ``with <lock>:`` blocks.

The engine/scheduler/cache stack (and the nodelet before it) layers
locks: an outer coordination lock (``self._lock``) wrapping calls into
a store/pool whose own lock is a LEAF. That layering only stays
deadlock-free while every code path acquires the locks in one global
order — the moment one function nests ``A -> B`` and another nests
``B -> A``, two threads can each hold one lock and wait forever on the
other.

This rule records every ordered pair of lock acquisitions that appear
lexically nested (``with A: ... with B:``), scoped per class (plain
``self._lock`` names in different classes are different locks), and
fires when both orders of the same pair show up. The later-seen
direction is reported at each of its acquisition sites, naming the
function holding the first direction — both sides of an inversion are
equally "wrong"; the report just needs a deterministic anchor.

Only attribute chains whose last component mentions ``lock`` (e.g.
``self._lock``, ``self.store._store_lock``, ``pool._lock``) are
considered: `with` is also files/meshes/spans, and a lint that
second-guesses every context manager would drown the real signal.
Cross-function and cross-class inversions (A held while *calling* a
method that transitively takes B) belong to the indexed layer
(``interproc.py``, selector ``GL009.inter``), which merges every
acquisition — lexical and via the call graph — into one global
lock-order graph; this per-file layer keeps owning inversions whose
both directions are lexical within one file and class.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.context import ModuleContext, qualname
from ray_tpu.devtools.registry import Rule, register


def _is_lock_name(qn: str) -> bool:
    return "lock" in qn.rsplit(".", 1)[-1].lower()


@register
class LockOrderRule(Rule):
    name = "lock-order"
    code = "GL009"
    description = ("nested with-lock acquisitions in inverted orders "
                   "(A->B in one function, B->A in another)")
    invariant = ("every code path acquires any pair of locks in one "
                 "global order, so no two threads can deadlock "
                 "holding one each")
    interests = ("With", "AsyncWith")

    def begin_module(self, ctx: ModuleContext) -> None:
        # (scope, outer, inner) -> [(node, function name), ...]
        self._orders: dict[tuple[str, str, str], list] = {}

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        locks = [qn for qn in (qualname(i.context_expr)
                               for i in node.items)
                 if qn is not None and _is_lock_name(qn)]
        if not locks:
            return
        held = [qn for qn in ctx.lock_stack if _is_lock_name(qn)]
        if not held:
            return
        scope = ctx.current_class.name if ctx.current_class else ""
        fn = ctx.current_function.name if ctx.current_function else "?"
        for outer in held:
            for inner in locks:
                if inner == outer:
                    continue
                self._orders.setdefault(
                    (scope, outer, inner), []).append((node, fn))

    def end_module(self, ctx: ModuleContext) -> None:
        reported: set[int] = set()
        for (scope, outer, inner), sites in sorted(
                self._orders.items(),
                key=lambda kv: min(s[0].lineno for s in kv[1])):
            rev = self._orders.get((scope, inner, outer))
            if not rev:
                continue
            # report the direction whose first acquisition appears
            # later in the file; the earlier one defines "the" order
            first = min(s[0].lineno for s in sites)
            rev_first = min(s[0].lineno for s in rev)
            if first < rev_first:
                continue  # the reverse entry will report
            holder = rev[0][1]
            for site, fn in sites:
                if id(site) in reported:
                    continue
                reported.add(id(site))
                ctx.report(
                    self, site,
                    f"lock-order inversion: {fn} acquires {inner} "
                    f"while holding {outer}, but {holder} acquires "
                    f"them as {inner} -> {outer}")
