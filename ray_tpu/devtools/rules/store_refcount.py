"""GL007: object-store ``get()`` without a matching ``release()``.

``ObjectStore.get`` returns a zero-copy view that HOLDS A REFCOUNT —
the store cannot evict the object until ``release(oid)`` drops it
(``ray_tpu/core/object_store.py``). A function that calls
``<store>.get(...)`` and never calls ``<store>.release(...)`` leaks
that pin: under memory pressure the allocator sees phantom live
objects, eviction stalls, and puts start failing with
ObjectStoreFullError long before the store is actually full.

Heuristic scope is the enclosing function and the exact receiver
expression: a ``self.store.get(oid)`` needs a ``self.store.release(...)``
somewhere in the same function. Receivers are considered store-like
when the attribute/name path ends in ``store`` (``store``,
``self.store``, ``self._store``, ``node.obj_store``); plain dict/queue
``.get`` calls never match — additionally the call must take exactly
one non-string-literal argument (an oid), so ``store.get("key", {})``
on a dict that merely happens to be NAMED store stays quiet. Two sanctioned hand-off conventions are
honored (mirroring GL005's caller-holds-the-lock conventions):

- a docstring (of any enclosing function) containing
  ``caller releases`` — the view is returned and ownership moves up;
- a function name ending in ``_unreleased``.

Anything else intentional gets a justified
``# graftlint: disable=unreleased-store-ref`` at the call site.
"""

from __future__ import annotations

import ast
import re

from ray_tpu.devtools.context import ModuleContext, qualname
from ray_tpu.devtools.registry import Rule, register

_STORE_RE = re.compile(r"(^|[._])store$")


def _store_receiver(call: ast.Call) -> str | None:
    """The dotted receiver of a `<recv>.get(...)`/`<recv>.release(...)`
    call when `<recv>` looks like an object store, else None."""
    if not isinstance(call.func, ast.Attribute):
        return None
    recv = qualname(call.func.value)
    if recv is None or not _STORE_RE.search(recv):
        return None
    return recv


@register
class StoreRefcountRule(Rule):
    name = "unreleased-store-ref"
    code = "GL007"
    description = ("object-store get() whose refcount pin has no "
                   "matching release() in the function")
    invariant = ("every store.get() view is released, so eviction is "
                 "never stalled by phantom pins")
    interests = ("Call",)

    def begin_module(self, ctx: ModuleContext) -> None:
        # (func node) -> set of receivers released in that function
        self._released: dict[ast.AST, set[str]] = {}
        # deferred get() events: (recv, node, func, docstring stack)
        self._gets: list[tuple] = []

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            return
        fn = ctx.current_function
        if fn is None:
            return
        if node.func.attr == "release":
            recv = _store_receiver(node)
            if recv is not None:
                for f in ctx.func_stack:
                    self._released.setdefault(f, set()).add(recv)
        elif (node.func.attr == "get" and len(node.args) == 1
              and not (isinstance(node.args[0], ast.Constant)
                       and isinstance(node.args[0].value, str))):
            recv = _store_receiver(node)
            if recv is not None:
                docs = [(f.name,
                         (ast.get_docstring(f, clean=False) or "").lower())
                        for f in ctx.func_stack]
                self._gets.append((recv, node, fn, docs))

    def end_module(self, ctx: ModuleContext) -> None:
        for recv, node, fn, docs in self._gets:
            if recv in self._released.get(fn, ()):
                continue
            if any(name.endswith("_unreleased") or "caller releases" in doc
                   for name, doc in docs):
                continue
            fn_name = docs[-1][0] if docs else "?"
            ctx.report(self, node,
                       f"{recv}.get() holds a refcount but {fn_name} "
                       f"never calls {recv}.release(); the pin leaks "
                       f"and stalls eviction (hand off with a 'caller "
                       f"releases' docstring if ownership moves up)")
