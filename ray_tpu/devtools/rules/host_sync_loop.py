"""GL019: device->host synchronization inside step/daemon loop bodies.

The serve decode path exists to keep the accelerator busy: one program
dispatch per step, results committed in (multi-token) batches. A
``.item()`` / ``float()`` / ``np.asarray()`` / ``jax.device_get()`` on
a device value *inside* a ``*_loop`` method body is the anti-pattern
that un-does it — every iteration blocks the host on the device
pipeline to materialize one scalar, serializing dispatch against
compute (the per-token host round-trip speculative decoding was built
to avoid; see SERVING.md "Speculative decoding").

What counts as a device value (flow-insensitive taint, per function):

- the result of a jit-program dispatch — a call whose callee ends with
  ``_jit`` (the house idiom ``self._decode_jit = jax.jit(...)``);
- the result of a ``jnp.*`` / ``jax.lax.*`` / ``jax.nn.*`` call;
- anything derived from one: tuple-unpacked, subscripted, method
  results on a tainted receiver, arithmetic on tainted operands.

Sinks that fire on a tainted value: ``.item()`` / ``.tolist()``,
``float()`` / ``int()`` / ``bool()`` casts, ``np.asarray()`` /
``np.array()``. ``jax.device_get()`` fires unconditionally — it is a
host sync by definition, whatever the linter can prove about its
argument. Host-value uses (``float(cfg.get(...))``, ``np.asarray``
of a python list) stay quiet, as do syncs in non-loop methods: the
discipline is *batch the transfer at the loop/commit boundary*, not
*never transfer*. GL004 covers the same calls inside traced code;
this rule covers the host-side dispatch loop around it.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.context import ModuleContext, qualname
from ray_tpu.devtools.registry import Rule, register

_SYNC_METHODS = frozenset(("item", "tolist"))
_NP_SINKS = frozenset(("numpy.asarray", "numpy.array"))
_CASTS = frozenset(("float", "int", "bool"))
_DEVICE_PREFIXES = ("jax.numpy.", "jax.lax.", "jax.nn.")


def _is_device_call(node: ast.AST, ctx: ModuleContext) -> bool:
    """A call that returns device arrays: a ``*_jit`` program dispatch
    or a jnp/jax.lax/jax.nn op."""
    if not isinstance(node, ast.Call):
        return False
    qn = qualname(node.func)
    if qn is None:
        return False
    if qn.rsplit(".", 1)[-1].endswith("_jit"):
        return True
    return ctx.resolve(qn).startswith(_DEVICE_PREFIXES)


@register
class HostSyncLoopRule(Rule):
    name = "host-sync-in-step-loop"
    code = "GL019"
    description = (".item()/float()/np.asarray/jax.device_get on a "
                   "device value inside a *_loop body — a per-"
                   "iteration device->host pipeline sync")
    invariant = ("step/daemon loops keep values on device and batch "
                 "the host transfer at the loop or commit boundary, "
                 "never once per iteration")
    interests = ("FunctionDef", "AsyncFunctionDef")

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if not node.name.endswith("_loop"):
            return
        tainted = self._tainted_names(node, ctx)

        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            msg = self._sink(sub, tainted, ctx)
            if msg is not None:
                ctx.report(self, sub,
                           f"{msg} in loop {node.name}() blocks the "
                           "host on the device pipeline every "
                           "iteration — keep it on device and batch "
                           "the transfer at the loop/commit boundary")

    # ---------------------------------------------------------- taint

    def _tainted_names(self, fn: ast.AST,
                       ctx: ModuleContext) -> set[str]:
        """Names ever bound to a device value in this function —
        flow-insensitive, iterated to a fixpoint so derivation chains
        (``x = jit(...); y = x[0]``) and loop-carried values land."""
        assigns: list[tuple[list[str], ast.AST]] = []
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign):
                targets, value = sub.targets, sub.value
            elif isinstance(sub, (ast.AnnAssign, ast.AugAssign)):
                targets, value = [sub.target], sub.value
            else:
                continue
            if value is None:
                continue
            names: list[str] = []
            for tgt in targets:
                elts = (tgt.elts if isinstance(tgt, (ast.Tuple, ast.List))
                        else [tgt])
                names.extend(e.id for e in elts
                             if isinstance(e, ast.Name))
            if names:
                assigns.append((names, value))

        tainted: set[str] = set()
        changed = True
        while changed:
            changed = False
            for names, value in assigns:
                if self._expr_tainted(value, tainted, ctx):
                    for name in names:
                        if name not in tainted:
                            tainted.add(name)
                            changed = True
        return tainted

    def _expr_tainted(self, node: ast.AST, tainted: set[str],
                      ctx: ModuleContext) -> bool:
        if isinstance(node, ast.Name):
            return node.id in tainted
        if isinstance(node, (ast.Subscript, ast.Starred)):
            return self._expr_tainted(node.value, tainted, ctx)
        if isinstance(node, ast.Call):
            if _is_device_call(node, ctx):
                return True
            # method result on a tainted receiver: logits.max()
            return (isinstance(node.func, ast.Attribute)
                    and self._expr_tainted(node.func.value, tainted,
                                           ctx))
        if isinstance(node, ast.BinOp):
            return (self._expr_tainted(node.left, tainted, ctx)
                    or self._expr_tainted(node.right, tainted, ctx))
        if isinstance(node, ast.UnaryOp):
            return self._expr_tainted(node.operand, tainted, ctx)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._expr_tainted(e, tainted, ctx)
                       for e in node.elts)
        if isinstance(node, ast.IfExp):
            return (self._expr_tainted(node.body, tainted, ctx)
                    or self._expr_tainted(node.orelse, tainted, ctx))
        return False

    # ---------------------------------------------------------- sinks

    def _sink(self, node: ast.Call, tainted: set[str],
              ctx: ModuleContext) -> str | None:
        f = node.func
        if (isinstance(f, ast.Attribute) and f.attr in _SYNC_METHODS
                and not node.args and not node.keywords
                and self._expr_tainted(f.value, tainted, ctx)):
            return f".{f.attr}() on a device value"
        if (isinstance(f, ast.Name) and f.id in _CASTS
                and len(node.args) == 1 and not node.keywords
                and self._expr_tainted(node.args[0], tainted, ctx)):
            return f"{f.id}() cast of a device value"
        resolved = ctx.resolve_call(node)
        if resolved == "jax.device_get":
            return "jax.device_get()"
        if (resolved in _NP_SINKS and node.args
                and self._expr_tainted(node.args[0], tainted, ctx)):
            return f"{resolved}() on a device value"
        return None
