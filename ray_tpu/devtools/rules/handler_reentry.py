"""GL013: an RPC handler that synchronously calls back into its own
server's handler pool.

An ``RpcServer`` dispatches handlers on a bounded thread pool. A handler
that does a synchronous ``.call(...)`` against its OWN server's address
needs a second pool thread to answer it — fine under light load, a
deterministic self-deadlock the moment the pool is saturated: every
pool thread is parked inside the outer handler waiting for an inner
dispatch that can never be scheduled, and the server wedges until the
client timeout cascades. The bug ships green (tests rarely saturate the
pool) and surfaces as a cluster-wide stall under exactly the load spike
the handler was built for.

Heuristic (lexical, same scoping as GL008/GL011): collect handler
functions registered via ``<server>.register("method", self._h_x, ...)``
(first argument a string literal — so ``atexit.register(fn)`` and
one-argument registries never match), then flag, in those functions'
own bodies, any ``.call`` / ``.call_frames`` / ``.call_gather`` whose
target resolves to the server's own address — a first argument of
``self.address`` or ``self.server.address``, including inside a literal
``call_gather`` target list. The sanctioned shapes: do the fan-out on a
NON-handler thread and have the handler read the gathered state (the
head's watchtower/metrics_history split), or ``send_oneway`` (no reply
to park on), or move the work to a different process/server.

This per-file layer owns self-addressed RPC directly in the handler
body. The indexed layer (``interproc.py``, selector ``GL013.inter``)
owns the reentry the single pass cannot see: a self-targeted RPC
reached through helper calls, and multi-hop cycles across service
classes (A's handler synchronously calls a method of B whose handler
calls back into A).
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.context import ModuleContext, qualname
from ray_tpu.devtools.registry import Rule, register

_RPC_METHODS = {"call", "call_frames", "call_gather"}
_SELF_ADDRS = {"self.address", "self.server.address"}


def _handler_name(expr: ast.expr) -> str | None:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _targets_self(arg: ast.expr) -> bool:
    """Does this call target (an address expression, or a call_gather
    [(addr, method, msg), ...] literal list) name the server's own
    address?"""
    qn = qualname(arg)
    if qn in _SELF_ADDRS:
        return True
    if isinstance(arg, (ast.List, ast.Tuple)):
        for elt in arg.elts:
            if isinstance(elt, ast.Tuple) and elt.elts and \
                    qualname(elt.elts[0]) in _SELF_ADDRS:
                return True
    return False


@register
class HandlerReentryRule(Rule):
    name = "handler-reentry"
    code = "GL013"
    description = ("RPC handler synchronously calls back into its own "
                   "server's handler pool (self-deadlock when the pool "
                   "is saturated)")
    invariant = ("handler-pool threads never park waiting on a dispatch "
                 "that needs one of those same threads")
    interests = ("Call",)

    def begin_module(self, ctx: ModuleContext) -> None:
        # (class scope, handler fn name) registered on an RPC server
        self._handlers: set[tuple[str, str]] = set()
        # (class scope, enclosing fn name, call node) self-targeted RPCs
        self._events: list[tuple[str, str, ast.Call]] = []
        self._enabled = ".register(" in ctx.source

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if not self._enabled or not isinstance(node.func, ast.Attribute):
            return
        scope = ctx.current_class.name if ctx.current_class else ""
        f = node.func
        if f.attr == "register" and len(node.args) >= 2 and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            name = _handler_name(node.args[1])
            if name is not None:
                self._handlers.add((scope, name))
            return
        if f.attr in _RPC_METHODS and node.args and \
                _targets_self(node.args[0]):
            fn = ctx.current_function
            if fn is not None:
                self._events.append((scope, fn.name, node))

    def end_module(self, ctx: ModuleContext) -> None:
        for scope, fn_name, node in self._events:
            if (scope, fn_name) not in self._handlers:
                continue
            method = node.func.attr  # type: ignore[union-attr]
            ctx.report(self, node,
                       f"{fn_name} is a registered RPC handler doing a "
                       f"synchronous .{method}() against its own "
                       "server's address — with the pool saturated "
                       "every thread parks waiting for a dispatch that "
                       "needs one of them (self-deadlock); gather on a "
                       "non-handler thread and let the handler read "
                       "the result, or send_oneway")
