"""GL018: unbounded container growth in RPC handlers / daemon loops.

Long-lived processes (head, nodelet, worker, driver runtime) accumulate
state in RPC handlers (``_h_*`` methods) and daemon loops (``*_loop``
methods) that run for the lifetime of the cluster. A ``self.X.append``
in such a method with NO bounding discipline anywhere in the class is a
slow leak: it grows monotonically with traffic until the process OOMs —
the classic shape behind "the head died after three days".

Bounding discipline, recognized anywhere in the same class:

- the attribute is constructed with a ``maxlen=`` keyword (a bounded
  ``deque``);
- something consumes it: ``.pop/.popleft/.popitem/.clear/.discard/
  .remove`` on the attribute, or ``del self.X[...]``;
- the attribute is REASSIGNED outside ``__init__`` (the drain-by-
  reassignment idiom: ``batch, self.X = self.X, []``) or its contents
  replaced via slice assignment (``self.X[:] = ...``).

Caps enforced by a length check before the append count as discipline
only when paired with one of the above on the overflow path (drop or
drain) — a bare length check without a consumer still never shrinks.
Scope is deliberately narrow: only ``self``-attribute containers, only
``append/appendleft/add/insert/extend`` calls, only inside handler or
loop methods. Dict subscript writes are out of scope (GL013's keyed-
state territory)."""

from __future__ import annotations

import ast

from ray_tpu.devtools.context import ModuleContext, qualname
from ray_tpu.devtools.registry import Rule, register

_GROW = frozenset(("append", "appendleft", "add", "insert", "extend"))
_SHRINK = frozenset(("pop", "popleft", "popitem", "clear", "discard",
                     "remove"))


def _self_attr(node: ast.AST) -> str | None:
    """'X' when node is exactly ``self.X``, else None."""
    qn = qualname(node)
    if qn and qn.startswith("self.") and qn.count(".") == 1:
        return qn[len("self."):]
    return None


def _is_hot_method(name: str) -> bool:
    return name.startswith("_h_") or name.endswith("_loop")


@register
class UnboundedAccumulatorRule(Rule):
    name = "unbounded-accumulator"
    code = "GL018"
    description = ("container attribute grown in an RPC handler or "
                   "daemon loop with no cap/trim/drain discipline "
                   "anywhere in the class — a slow leak")
    invariant = ("every container a long-lived process appends to on "
                 "a traffic-driven path is bounded: maxlen, a "
                 "consumer that pops/clears, or drain-by-reassignment")
    interests = ("ClassDef",)

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        methods = [n for n in node.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        if not methods:
            return
        disciplined: set[str] = set()
        # (attr, call node, method name) growth sites on hot paths
        growth: list[tuple[str, ast.Call, str]] = []

        for meth in methods:
            hot = _is_hot_method(meth.name)
            for sub in ast.walk(meth):
                if isinstance(sub, ast.Call) and isinstance(
                        sub.func, ast.Attribute):
                    attr = _self_attr(sub.func.value)
                    if attr is None:
                        continue
                    if sub.func.attr in _SHRINK:
                        disciplined.add(attr)
                    elif hot and sub.func.attr in _GROW:
                        growth.append((attr, sub, meth.name))
                elif isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    targets = (sub.targets
                               if isinstance(sub, ast.Assign)
                               else [sub.target])
                    for tgt in targets:
                        # tuple unpack: batch, self.X = self.X, []
                        elts = (tgt.elts if isinstance(
                            tgt, (ast.Tuple, ast.List)) else [tgt])
                        for e in elts:
                            attr = _self_attr(e)
                            if attr is not None:
                                if meth.name != "__init__":
                                    disciplined.add(attr)
                                elif self._bounded_ctor(sub.value):
                                    disciplined.add(attr)
                            elif isinstance(e, ast.Subscript):
                                # self.X[:] = ... / self.X[i] = ...
                                attr = _self_attr(e.value)
                                if attr is not None:
                                    disciplined.add(attr)
                elif isinstance(sub, ast.Delete):
                    for tgt in sub.targets:
                        base = (tgt.value if isinstance(
                            tgt, ast.Subscript) else tgt)
                        attr = _self_attr(base)
                        if attr is not None:
                            disciplined.add(attr)

        for attr, call, meth_name in growth:
            if attr in disciplined:
                continue
            kind = ("RPC handler" if meth_name.startswith("_h_")
                    else "daemon loop")
            ctx.report(self, call,
                       f"self.{attr}.{call.func.attr}() in {kind} "
                       f"{meth_name}() with no bounding discipline in "
                       f"class {node.name} — grows with traffic until "
                       "OOM; bound it (deque(maxlen=...), a consumer "
                       "that pops/clears, or drain-by-reassignment)")

    @staticmethod
    def _bounded_ctor(value: ast.AST | None) -> bool:
        return isinstance(value, ast.Call) and any(
            kw.arg == "maxlen" for kw in value.keywords)
