"""GL011: exceptions escaping a oneway RPC handler are swallowed.

A handler registered with ``register(<method>, fn, oneway=True)`` has
no reply path, and the dispatch loop in ``ray_tpu/core/rpc.py``
deliberately sends nothing back on error — an exception that escapes a
oneway handler simply vanishes. A ``raise`` (or ``assert``) in one is
therefore a silent no-op masquerading as validation: the author
believed *someone* observes the failure, but neither the caller (fired
and forgot) nor the server (dispatch drops it) ever does. The bug
class GL008 catches for return values, this rule catches for errors.

Heuristic: reuse GL008's oneway-registration detection (``<anything>
.register(<name>, <handler>, oneway=True)``, keyword or third
positional), then flag every ``raise``/``assert`` in the same-module
function of that name that can ESCAPE the handler — i.e. one not
enclosed in a ``try`` with at least one ``except`` clause inside the
handler itself (any handler counts; matching exception types is out of
AST reach and a deliberately-narrow except around a raise is already a
considered choice). Statements inside functions NESTED in the handler
belong to the nested function and are ignored, as are re-raises inside
``except`` bodies only when a further enclosing try covers them —
an uncovered bare ``raise`` in an except clause escapes too.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.context import ModuleContext
from ray_tpu.devtools.registry import Rule, register
from ray_tpu.devtools.rules.oneway_return import _handler_name, _is_true


def _escaping_raises(fn: ast.AST) -> list[ast.AST]:
    """Raise/Assert nodes in `fn`'s OWN body that no enclosing
    try/except (within `fn`) can catch."""
    out: list[ast.AST] = []

    def scan(node: ast.AST, caught: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return  # nested scope: its raises are its own business
        if isinstance(node, (ast.Raise, ast.Assert)):
            if not caught:
                out.append(node)
            return
        if isinstance(node, ast.Try):
            covered = caught or bool(node.handlers)
            for st in node.body:
                scan(st, covered)
            for h in node.handlers:
                for st in h.body:
                    scan(st, caught)  # raising out of except escapes
            for st in node.orelse + node.finalbody:
                scan(st, caught)
            return
        for child in ast.iter_child_nodes(node):
            scan(child, caught)

    for st in getattr(fn, "body", ()):
        scan(st, False)
    return out


@register
class OnewayRaiseRule(Rule):
    name = "oneway-exception"
    code = "GL011"
    description = ("raise/assert escaping a oneway=True handler is "
                   "silently swallowed by the RPC dispatch")
    invariant = ("oneway handlers never signal errors by raising: no "
                 "caller and no log ever observes them")
    interests = ("Call", "FunctionDef", "AsyncFunctionDef")

    def begin_module(self, ctx: ModuleContext) -> None:
        self._oneway_handlers: set[str] = set()
        # name -> first same-module function def of that name
        self._functions: dict[str, ast.AST] = {}

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._functions.setdefault(node.name, node)
            return
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "register"
                and len(node.args) >= 2):
            return
        oneway = any(kw.arg == "oneway" and _is_true(kw.value)
                     for kw in node.keywords)
        if not oneway and len(node.args) >= 3:
            oneway = _is_true(node.args[2])
        if not oneway:
            return
        name = _handler_name(node.args[1])
        if name is not None:
            self._oneway_handlers.add(name)

    def end_module(self, ctx: ModuleContext) -> None:
        for name in sorted(self._oneway_handlers):
            fn = self._functions.get(name)
            if fn is None:
                continue
            for node in _escaping_raises(fn):
                kind = ("assert" if isinstance(node, ast.Assert)
                        else "raise")
                ctx.report(self, node,
                           f"{name} is registered oneway=True: this "
                           f"{kind} is silently swallowed by the RPC "
                           "dispatch (oneway handlers have no reply "
                           "path and errors are dropped) — handle it "
                           "locally or register the method two-way")
