"""GL015: wall-clock deltas used as durations.

``time.time()`` readings subtract to intervals that jump under NTP
slew, leap smearing, and operator clock steps — the PR 3
epoch-anchoring bug class: a span plane stamped with raw wall deltas
mis-ordered cross-process events by each machine's clock adjustment.
The repo's clock discipline (OBSERVABILITY.md) is: **durations come
from the monotonic clock** (`time.monotonic()` / `monotonic_ns` /
`perf_counter` / `thread_time`), **timestamps come from the wall
clock**, and the only sanctioned mix is the epoch anchor
``time.time() - time.monotonic()`` recorded once and added to
monotonic readings.

Heuristic: flag a ``-`` subtraction where BOTH operands are wall-clock
readings — a direct ``time.time()`` call, or a name/attribute ASSIGNED
from ``time.time()`` anywhere in the module (module-wide tracking
matches how ``t0``-style locals and ``self._start``-style attributes
are actually used; a name also assigned from a monotonic source
anywhere is treated as NOT wall, keeping the rule conservative).
Quiet by construction:

- timestamps stored without subtraction (record fields, session names);
- the anchoring idiom ``time.time() - time.monotonic()`` (one operand
  is monotonic);
- ``deadline - time.time()`` where ``deadline``'s provenance is
  unknown (only *known-wall* operands fire).
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.context import ModuleContext, qualname
from ray_tpu.devtools.registry import Rule, register

_MONO_FNS = {"monotonic", "monotonic_ns", "perf_counter",
             "perf_counter_ns", "thread_time", "thread_time_ns",
             "process_time", "process_time_ns"}


def _call_kind(node: ast.AST) -> str | None:
    """'wall' / 'mono' for a time-module call expression, else None."""
    if not isinstance(node, ast.Call):
        return None
    q = qualname(node.func)
    if q == "time.time":
        return "wall"
    if q is not None and "." in q and q.split(".")[-1] in _MONO_FNS:
        return "mono"
    return None


@register
class WallclockDurationRule(Rule):
    name = "wallclock-duration"
    code = "GL015"
    description = ("time.time() delta used as a duration — wall-clock "
                   "subtraction jumps under NTP slew/clock steps; "
                   "durations must come from time.monotonic()")
    invariant = ("durations are monotonic-clock differences; the wall "
                 "clock only stamps timestamps (and the once-per-process "
                 "epoch anchor)")
    interests = ("Assign", "BinOp")

    def begin_module(self, ctx: ModuleContext) -> None:
        self._wall_names: set[str] = set()
        self._mono_names: set[str] = set()
        self._subs: list[ast.BinOp] = []

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if isinstance(node, ast.Assign):
            kind = _call_kind(node.value)
            if kind is None:
                return
            names = self._wall_names if kind == "wall" else \
                self._mono_names
            for target in node.targets:
                q = qualname(target)
                if q is not None:
                    names.add(q)
        elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
            self._subs.append(node)

    def _wallness(self, node: ast.AST) -> str | None:
        kind = _call_kind(node)
        if kind is not None:
            return kind
        q = qualname(node)
        if q is None:
            return None
        # a name fed from BOTH clocks anywhere in the module is
        # ambiguous: treat as monotonic (no finding) — conservative
        if q in self._mono_names:
            return "mono"
        if q in self._wall_names:
            return "wall"
        return None

    def end_module(self, ctx: ModuleContext) -> None:
        for sub in self._subs:
            if self._wallness(sub.left) == "wall" and \
                    self._wallness(sub.right) == "wall":
                ctx.report(self, sub,
                           "wall-clock delta used as a duration: both "
                           "operands of this subtraction are "
                           "time.time() readings, which jump under NTP "
                           "slew/clock steps — time the interval with "
                           "time.monotonic() (keep time.time() for "
                           "timestamps)")
