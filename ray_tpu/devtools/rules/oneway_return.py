"""GL008: oneway RPC handlers that return a value.

A handler registered with ``register(<method>, fn, oneway=True)`` gets
NO reply path — the RPC server drops whatever it returns
(``ray_tpu/core/rpc.py`` dispatch: oneway handlers send nothing back).
A ``return <value>`` in one is a silent contract violation: the author
believed the caller sees an ack/result, but every caller fired and
forgot. The bug ships green (nothing crashes) and surfaces as a
mysteriously-ignored reply months later.

Heuristic: collect ``<anything>.register(<name>, <handler>,
oneway=True)`` calls (keyword or third positional argument) whose
handler is a ``self._h_x`` / bare-name reference or an inline lambda,
then flag every ``return`` WITH a non-None value in the same-module
function of that name (lambdas: flag at the register site when the body
is not the ``None`` constant). Bare ``return`` / ``return None`` —
early exits — are the sanctioned oneway idiom and never flagged.
Returns inside functions NESTED in the handler belong to the nested
function and are ignored.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.context import ModuleContext
from ray_tpu.devtools.registry import Rule, register


def _is_true(node: ast.AST | None) -> bool:
    return isinstance(node, ast.Constant) and node.value is True


def _is_none(node: ast.AST | None) -> bool:
    return node is None or (isinstance(node, ast.Constant)
                            and node.value is None)


def _handler_name(expr: ast.expr) -> str | None:
    """Bare name of the handler reference: `self._h_x` -> `_h_x`,
    `_h_x` -> `_h_x`; dynamic expressions -> None."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


@register
class OnewayReturnRule(Rule):
    name = "oneway-return"
    code = "GL008"
    description = ("handler registered oneway=True returns a value the "
                   "RPC layer silently drops")
    invariant = ("oneway handlers never compute replies: no caller can "
                 "ever observe them")
    interests = ("Call", "Return")

    def begin_module(self, ctx: ModuleContext) -> None:
        self._oneway_handlers: dict[str, ast.Call] = {}  # name -> site
        # function name -> value-returning Return nodes in its OWN body
        self._value_returns: dict[str, list[ast.Return]] = {}

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if isinstance(node, ast.Return):
            fn = ctx.current_function
            if fn is not None and not _is_none(node.value):
                self._value_returns.setdefault(fn.name, []).append(node)
            return
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "register"
                and len(node.args) >= 2):
            return
        oneway = any(kw.arg == "oneway" and _is_true(kw.value)
                     for kw in node.keywords)
        if not oneway and len(node.args) >= 3:
            oneway = _is_true(node.args[2])
        if not oneway:
            return
        handler = node.args[1]
        if isinstance(handler, ast.Lambda):
            if not _is_none(handler.body):
                ctx.report(self, handler,
                           "lambda registered oneway=True returns a "
                           "value; the RPC layer drops it — no caller "
                           "ever sees a reply from a oneway handler")
            return
        name = _handler_name(handler)
        if name is not None:
            self._oneway_handlers.setdefault(name, node)

    def end_module(self, ctx: ModuleContext) -> None:
        for name in self._oneway_handlers:
            for ret in self._value_returns.get(name, ()):
                ctx.report(self, ret,
                           f"{name} is registered oneway=True: this "
                           "return value is silently dropped (no reply "
                           "is ever sent) — drop the value or register "
                           "the method two-way")
