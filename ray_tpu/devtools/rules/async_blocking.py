"""GL001: blocking calls inside async actor methods and RPC handlers.

The runtime's classic deadlock: an ``async def`` actor method calls the
blocking ``ray_tpu.get()`` / ``wait()`` on a future produced by its own
event loop — the loop thread parks forever. The same applies to the
control plane's RPC handler callbacks (``_h_*`` methods on the
nodelet/head/runtime/worker): they run on a bounded server thread pool,
so an indefinite block (``time.sleep``, a timeout-less ``Event.wait()``
or ``Queue.get()``) can starve every other handler, including the one
that would have unblocked it.

Allowed: awaiting, executor offload (``run_in_executor``), and bounded
waits — the indefinite-block methods pass once they carry any argument
(a timeout). Blocking ray get()/wait() and ``time.sleep`` are flagged
regardless of timeouts: even bounded, they park a pool/loop thread for
the duration — route them to the RPC slow lane or an executor, or
suppress with a justification (see ``ray_tpu/client.py``).
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.context import ModuleContext
from ray_tpu.devtools.registry import Rule, register

_RAY_BLOCKING = {
    "ray_tpu.get", "ray_tpu.wait",
    "ray_tpu.core.api.get", "ray_tpu.core.api.wait",
}
# zero-arg methods that block indefinitely on the usual suspects
_INDEFINITE_METHODS = {"wait", "get", "acquire", "join", "result"}


@register
class AsyncBlockingRule(Rule):
    name = "async-blocking"
    code = "GL001"
    description = ("blocking get()/wait()/sleep inside async actor "
                   "methods or _h_* RPC handler callbacks")
    invariant = ("event-loop and handler-pool threads never block on "
                 "results that need those same threads to progress")
    interests = ("Await", "Call")

    def begin_module(self, ctx: ModuleContext) -> None:
        self._awaited: set[int] = set()

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if isinstance(node, ast.Await):
            # `await x.wait()` is the NON-blocking asyncio form
            if isinstance(node.value, ast.Call):
                self._awaited.add(id(node.value))
            return
        if not isinstance(node, ast.Call) or id(node) in self._awaited:
            return
        fn = ctx.current_function
        if fn is None:
            return
        in_async = isinstance(fn, ast.AsyncFunctionDef)
        in_handler = (fn.name.startswith("_h_")
                      and ctx.current_class is not None)
        if not (in_async or in_handler):
            return
        where = ("async function" if in_async else
                 f"RPC handler {ctx.current_class.name}.{fn.name}")

        resolved = ctx.resolve_call(node)
        if resolved in _RAY_BLOCKING:
            ctx.report(self, node,
                       f"blocking {resolved}() inside {where}: deadlocks "
                       f"when the result needs this thread; restructure "
                       f"or offload to an executor")
            return
        if resolved == "time.sleep":
            ctx.report(self, node,
                       f"time.sleep() inside {where} parks a shared "
                       f"thread; use asyncio.sleep or an Event timeout")
            return
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _INDEFINITE_METHODS
                and not node.args and not node.keywords):
            ctx.report(self, node,
                       f".{node.func.attr}() with no timeout inside "
                       f"{where} can block forever; pass a timeout")
