"""GL012: blocking I/O or RPC while holding a ``guarded_by`` lock.

The locks named in ``# guarded_by(<lock>)`` annotations are, by
declaration, the locks every thread in the process contends on to
touch shared state. Sleeping, waiting on a remote result, or doing an
RPC round-trip while holding one turns a microsecond critical section
into a seconds-long convoy: every handler thread that needs the lock
parks behind one slow network peer, and the component's event loop
reads as "stalled" (the serve controller's health loop is the
motivating shape — probe RPCs must happen on a SNAPSHOT taken under
the lock, never under it).

Fires on, lexically inside a ``with <lock>:`` where ``<lock>`` is
named by any guarded_by annotation in the same class (or module scope,
for GL010-style module locks):

- ``time.sleep(...)``
- ``ray_tpu.get(...)`` / ``ray_tpu.wait(...)`` (remote results)
- ``.call(...)`` / ``.call_frames(...)`` / ``.call_gather(...)`` on a
  receiver whose path mentions ``client``, or on ``RpcClient.shared()``
  (the RPC round-trip idiom)
- timeout-less ``.result()`` (future join)
- builtin ``open(...)`` (file I/O; spill paths stage under the lock and
  write outside it)

The snapshot-then-act pattern (copy under the lock, call outside) is
the sanctioned fix. ``Condition.wait`` is NOT flagged — it releases
the lock while parked, which is the whole point of conditions.
Justified exceptions use ``# graftlint: disable=blocking-under-lock``.

This is the PER-FILE layer: it owns blocking primitives lexically
under the lock. The indexed layer (``interproc.py``, selector
``GL012.inter``) owns blocking that hides behind a function call —
both share ``semindex.blocking_call_label`` as the single definition
of "blocking", so the two layers can never disagree about what blocks.
"""

from __future__ import annotations

import ast
import re

from ray_tpu.devtools.context import ModuleContext
from ray_tpu.devtools.registry import Rule, register
from ray_tpu.devtools.semindex import blocking_call_label

_ANNOT_RE = re.compile(r"#.*?guarded_by\(\s*(?:self\.)?([\w\.]+)\s*\)")


@register
class BlockingUnderLockRule(Rule):
    name = "blocking-under-lock"
    code = "GL012"
    description = ("blocking I/O / RPC / sleep while holding a lock "
                   "named by a guarded_by annotation")
    invariant = ("guarded_by critical sections stay short: no thread "
                 "holding shared-state locks parks on the network, the "
                 "disk, or a timer")
    interests = ("Call",)

    def begin_module(self, ctx: ModuleContext) -> None:
        # (scope, lock qualname) seen in guarded_by annotations; scope
        # is the class name ("" at module level). Collected up front
        # from the raw lines — annotations are comments, invisible to
        # the AST walk.
        self._locks: set[tuple[str, str]] = set()
        self._events: list[tuple] = []
        self._enabled = "guarded_by(" in ctx.source
        if not self._enabled:
            return
        self._collect_annotations(ctx)

    def _collect_annotations(self, ctx: ModuleContext) -> None:
        """Map each guarded_by comment line to its enclosing class by
        AST position (module scope for top-level annotations)."""
        spans: list[tuple[int, int, str]] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                spans.append((node.lineno,
                              getattr(node, "end_lineno", node.lineno),
                              node.name))
        for i, line in enumerate(ctx.lines, start=1):
            m = _ANNOT_RE.search(line)
            if not m:
                continue
            lock = m.group(1)
            scope = ""
            best = None
            for lo, hi, name in spans:
                if lo <= i <= hi and (best is None or lo > best[0]):
                    best = (lo, name)
            if best is not None:
                scope = best[1]
            if "." not in lock or lock.startswith("self."):
                # class-scope locks are self attributes
                qual = lock if lock.startswith("self.") else (
                    f"self.{lock}" if scope else lock)
                self._locks.add((scope, qual))
            else:
                self._locks.add((scope, lock))

    # ---------------------------------------------------------------- visit

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if not self._enabled or not ctx.lock_stack:
            return
        label = blocking_call_label(node, ctx.resolve)
        if label is None:
            return
        scope = ctx.current_class.name if ctx.current_class else ""
        self._events.append((scope, tuple(ctx.lock_stack), node, label))

    # ------------------------------------------------------------ end pass

    def end_module(self, ctx: ModuleContext) -> None:
        if not self._enabled:
            return
        for scope, held, node, label in self._events:
            guarded = [lock for s, lock in self._locks
                       if s == scope and lock in held]
            if not guarded:
                # module-scope guarded locks apply everywhere in the
                # module (GL010 globals are shared process-wide)
                guarded = [lock for s, lock in self._locks
                           if s == "" and lock in held]
            if not guarded:
                continue
            ctx.report(self, node,
                       f"{label} while holding {guarded[0]} (a "
                       f"guarded_by lock) — snapshot under the lock, "
                       f"block outside it")
