"""Bundled graftlint rules. Importing this package registers them all.

Adding a rule: create a module here, subclass ``Rule``, decorate with
``@register``, and import it below. See DEVTOOLS.md for the catalog.
"""

from ray_tpu.devtools.rules import (  # noqa: F401
    async_blocking,
    bare_print,
    blocking_lock,
    discarded_future,
    except_hygiene,
    global_guard,
    guarded_by,
    handler_reentry,
    host_sync_loop,
    host_transfer,
    lock_order,
    oneway_raise,
    oneway_return,
    sequential_rpc,
    spmd_nondeterminism,
    store_refcount,
    unbounded_accumulator,
    wallclock_duration,
)
