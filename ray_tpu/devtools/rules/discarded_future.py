"""GL002: discarded ``.remote()`` futures.

A ``.remote()`` call whose ObjectRef is thrown away as a bare
expression statement leaks the submitted work: its errors can never be
observed (``get`` is what re-raises them), retries/backpressure never
apply, and the owner-side bookkeeping keeps the ref alive until
process exit. Fire-and-forget is occasionally intentional — say so
with ``# graftlint: disable=discarded-future`` at the call site, or
bind the ref.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools.context import ModuleContext
from ray_tpu.devtools.registry import Rule, register


def _is_remote_call(value: ast.expr) -> bool:
    return (isinstance(value, ast.Call)
            and isinstance(value.func, ast.Attribute)
            and value.func.attr == "remote")


@register
class DiscardedFutureRule(Rule):
    name = "discarded-future"
    code = "GL002"
    description = ".remote() result discarded as a bare statement"
    invariant = ("every submitted task/actor-call has an owner that can "
                 "observe its error")
    interests = ("Expr",)

    def visit(self, node: ast.AST, ctx: ModuleContext) -> None:
        if isinstance(node, ast.Expr) and _is_remote_call(node.value):
            ctx.report(self, node,
                       "ObjectRef from .remote() is discarded: errors "
                       "become unobservable and the ref leaks; bind it "
                       "(or suppress if fire-and-forget is intended)")
