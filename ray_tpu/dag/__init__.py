"""Compiled DAGs — channel-backed repeated execution of actor graphs.

Reference parity: ray.dag accelerated DAGs
(python/ray/dag/compiled_dag_node.py:711 — `experimental_compile` turns
a bound actor-method graph into a resident pipeline: each actor runs a
loop reading input CHANNELS, invoking its method directly, writing its
output channel; `execute()` then costs one channel write + read instead
of per-call task submission). Here the channels are the native shm SPSC
rings (ray_tpu.experimental.channel) and the per-actor loops are
installed by the worker runtime (dag_start).

Usage:
    with InputNode() as inp:
        x = a.step.bind(inp)
        y = b.step.bind(x)
    dag = y.experimental_compile()
    out = dag.execute(5).get()
    dag.teardown()
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any

_CHANNEL_CAP = 1 << 20


class _DagError:
    """Slot-consuming error marker in the result sequence."""

    def __init__(self, message: str):
        self.message = message


class DAGNode:
    """Base: a node producing one value per execution."""

    def __init__(self, upstream: list["DAGNode"]):
        self.upstream = upstream

    def experimental_compile(self) -> "CompiledDAG":
        return CompiledDAG(self)

    def _walk(self, seen, order):
        if id(self) in seen:
            return
        seen.add(id(self))
        for u in self.upstream:
            u._walk(seen, order)
        order.append(self)


class InputNode(DAGNode):
    """The driver-fed input (reference: ray.dag.InputNode)."""

    def __init__(self):
        super().__init__([])

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class ClassMethodNode(DAGNode):
    """One bound actor method (reference: dag/class_node.py)."""

    def __init__(self, actor_handle, method_name: str,
                 args: tuple["DAGNode", ...]):
        for a in args:
            if not isinstance(a, DAGNode):
                raise TypeError(
                    "compiled-DAG args must be DAG nodes (InputNode or "
                    "other bound methods); constants go in actor state")
        super().__init__(list(args))
        self.actor_handle = actor_handle
        self.method_name = method_name


class MultiOutputNode(DAGNode):
    """Fan-in terminal: execute() returns a list (reference:
    ray.dag.MultiOutputNode)."""

    def __init__(self, outputs: list[DAGNode]):
        super().__init__(list(outputs))


class CompiledDAGRef:
    """Result handle for one execute() (reference: CompiledDAGRef)."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq

    def get(self, timeout: float | None = 60.0) -> Any:
        return self._dag._fetch(self._seq, timeout)


class CompiledDAG:
    def __init__(self, output_node: DAGNode):
        from ray_tpu.core.api import _global_runtime
        from ray_tpu.experimental.channel import Channel

        self._rt = _global_runtime()
        order: list[DAGNode] = []
        output_node._walk(set(), order)
        self._order = order
        inputs = [n for n in order if isinstance(n, InputNode)]
        if len(inputs) != 1:
            raise ValueError("a compiled DAG needs exactly one InputNode")
        self._multi = isinstance(output_node, MultiOutputNode)
        self._loop_prefix = f"dag_{os.urandom(4).hex()}"
        # one channel per EDGE (SPSC): producer node -> consumer slot
        self._channels: list[Channel] = []
        edge_chan: dict[tuple[int, int], Channel] = {}

        def make_chan():
            c = Channel(capacity=_CHANNEL_CAP, create=True)
            self._channels.append(c)
            return c

        compute_nodes = [n for n in order
                         if isinstance(n, ClassMethodNode)]
        terminals = (output_node.upstream if self._multi
                     else [output_node])
        for t in terminals:
            if not isinstance(t, ClassMethodNode):
                raise ValueError("DAG outputs must be bound actor methods")
        # input edges the driver writes directly
        self._input_edges: list[Channel] = []
        # per-node in/out channel wiring
        node_out: dict[int, list[Channel]] = {}
        node_ins: dict[int, list[Channel]] = {}
        for n in compute_nodes:
            node_ins[id(n)] = []
            for u in n.upstream:
                c = make_chan()
                node_ins[id(n)].append(c)
                if isinstance(u, InputNode):
                    self._input_edges.append(c)
                else:
                    node_out.setdefault(id(u), []).append(c)
        # terminal outputs flow to the driver through one channel each;
        # a node feeding BOTH another node and the driver fans out below
        self._output_chans: list[Channel] = []
        term_ids = []
        for t in terminals:
            c = make_chan()
            node_out.setdefault(id(t), []).append(c)
            self._output_chans.append(c)
            term_ids.append(id(t))
        # install per-actor loops. Fan-out (one producer, many consumer
        # channels) rides a driver-side pump when needed; the common
        # chain/tree case is pure actor-to-actor.
        self._pumps: list[threading.Thread] = []
        self._stop = threading.Event()
        self._loop_ids: list[tuple[str, str]] = []  # (actor addr, loop_id)
        for i, n in enumerate(compute_nodes):
            outs = node_out.get(id(n), [])
            if len(outs) > 1:
                mid = make_chan()
                self._start_pump(mid, outs)
                primary = mid
            else:
                primary = outs[0]
            addr = self._rt._resolve_actor(n.actor_handle._actor_id.binary())
            loop_id = f"{self._loop_prefix}_{i}"
            self._rt.client.call(addr, "dag_start", {
                "loop_id": loop_id,
                "method": n.method_name,
                "in_channels": [c.name for c in node_ins[id(n)]],
                "out_channel": primary.name,
            }, timeout=30)
            self._loop_ids.append((addr, loop_id))
        if len(self._input_edges) > 1:
            # one driver write fans out to every input consumer
            first = make_chan()
            self._start_pump(first, self._input_edges)
            self._write_chan = first
        else:
            self._write_chan = self._input_edges[0]
        self._seq = 0
        self._fetched = 0  # results drained from the output channels
        self._results: dict[int, Any] = {}
        # values already drained from SOME output channels of the row
        # currently being assembled — survives a get() timeout so a
        # partially-drained multi-output row is resumed, never lost
        self._partial: list = []
        self._fetch_lock = threading.Lock()

    def _start_pump(self, src, dsts):
        def pump():
            while not self._stop.is_set():
                try:
                    v = src.get(timeout=0.5)
                except TimeoutError:
                    continue
                except Exception:  # noqa: BLE001
                    return
                for d in dsts:
                    try:
                        d.put(v, timeout=60)
                    except Exception:  # noqa: BLE001
                        return

        t = threading.Thread(target=pump, daemon=True, name="dag-pump")
        t.start()
        self._pumps.append(t)

    # ------------------------------------------------------------ public

    def execute(self, value: Any) -> CompiledDAGRef:
        """One pipelined execution: a channel write; results stream back
        in order (reference: CompiledDAG.execute)."""
        self._write_chan.put(value, timeout=60)
        ref = CompiledDAGRef(self, self._seq)
        self._seq += 1
        return ref

    def _fetch(self, seq: int, timeout):
        """Results arrive strictly in execution order (SPSC channels):
        drain until `seq` has landed. Errors CONSUME their slot like any
        result — raising without recording would desynchronize every
        later execution's sequence number."""
        with self._fetch_lock:
            while seq not in self._results:
                # drain channel-by-channel into the resumable partial row:
                # a timeout mid-row must not discard already-popped values
                # (SPSC pops are destructive)
                while len(self._partial) < len(self._output_chans):
                    c = self._output_chans[len(self._partial)]
                    self._partial.append(c.get(timeout=timeout))
                outs, self._partial = self._partial, []
                err = next((o["__dag_error__"] for o in outs
                            if isinstance(o, dict) and "__dag_error__" in o),
                           None)
                self._results[self._fetched] = (
                    _DagError(err) if err is not None
                    else (outs if self._multi else outs[0]))
                self._fetched += 1
            out = self._results.pop(seq)
            if isinstance(out, _DagError):
                raise RuntimeError(out.message)
            return out

    def teardown(self):
        self._stop.set()
        for addr, loop_id in self._loop_ids:
            try:
                self._rt.client.call(addr, "dag_stop",
                                     {"loop_id": loop_id}, timeout=10)
            except Exception:  # noqa: BLE001
                pass
        # driver-side pump threads poll at 0.5s: JOIN them before
        # unmapping the segments (destroying under a reader is a UAF on
        # the mmap'd base — segfault, not an exception)
        for t in self._pumps:
            t.join(timeout=2.0)
        # closing marks the ring closed so any still-blocked worker
        # reader exits cleanly before we unlink the names (their own
        # mappings stay valid until their process detaches)
        for c in self._channels:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        time.sleep(0.1)
        for c in self._channels:
            try:
                c.destroy()
            except Exception:  # noqa: BLE001
                pass


__all__ = ["ClassMethodNode", "CompiledDAG", "CompiledDAGRef", "DAGNode",
           "InputNode", "MultiOutputNode"]
