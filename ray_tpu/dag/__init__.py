"""Compiled DAGs — channel-backed repeated execution of actor graphs.

Reference parity: ray.dag accelerated DAGs
(python/ray/dag/compiled_dag_node.py:711 — `experimental_compile` turns
a bound actor-method graph into a resident pipeline: each actor runs a
loop reading input CHANNELS, invoking its method directly, writing its
output channel; `execute()` then costs one channel write + read instead
of per-call task submission). Here the channels are the native shm SPSC
rings (ray_tpu.experimental.channel) and the per-actor loops are
installed by the worker runtime (dag_start).

Compile once, execute many:

    with InputNode() as inp:
        x = a.step.bind(inp)
        y = b.step.bind(x)
    dag = y.compile()              # experimental_compile() also works
    out = dag.execute(5).get()
    dag.teardown()

The fast-path contract (test-gated in tests/test_compiled_dag.py):

- compile() resolves every actor address ONCE and pre-allocates one
  reusable channel slot (an object-ID-named shm ring) per graph edge;
  steady-state execute() is one channel write, intermediate results
  flow worker→worker through their edge channels, and NO head, nodelet
  or per-call RPC is involved.
- Backpressure is structural: every channel is a bounded ring (a
  producer blocks when its consumer's slots are full) and the driver
  additionally caps in-flight executions at `max_inflight`, so a fast
  producer can never overrun a slow consumer — memory stays bounded
  end to end.
- Errors propagate exactly like the eager `.remote()` chain: a stage's
  exception rides the pipeline as a slot-consuming marker and `get()`
  re-raises the same TaskError the eager path would raise.
- On actor death the DAG falls back to the EAGER path: pending and
  subsequent executions replay through ordinary actor calls (the heal
  plane republishes routing for restartable actors; non-restartable
  actors surface ActorDiedError), and teardown() releases every
  channel slot either way.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any

_CHANNEL_CAP = 1 << 20

# dag_executions_total (lazy: keep import-time free of the metrics
# registry; the counter appears on first execute)
_exec_counter = None
_exec_counter_lock = threading.Lock()


def _count_execution(fallback: bool):
    global _exec_counter
    if _exec_counter is None:
        with _exec_counter_lock:
            if _exec_counter is None:
                try:
                    from ray_tpu.util.metrics import Counter

                    _exec_counter = Counter(
                        "dag_executions_total",
                        "compiled-DAG executions, by path "
                        "(compiled|eager_fallback)",
                        tag_keys=("path",))
                except Exception:  # noqa: BLE001
                    return
    try:
        _exec_counter.inc(
            1, {"path": "eager_fallback" if fallback else "compiled"})
    except Exception:  # noqa: BLE001
        pass


class _DagError:
    """Slot-consuming error marker in the result sequence. Carries the
    actual remote exception when it pickled, else a message string."""

    def __init__(self, err):
        self.err = err

    def raise_(self):
        if isinstance(self.err, BaseException):
            raise self.err
        raise RuntimeError(str(self.err))


class DAGNode:
    """Base: a node producing one value per execution."""

    def __init__(self, upstream: list["DAGNode"]):
        self.upstream = upstream

    def compile(self, **kwargs) -> "CompiledDAG":
        """Compile this graph into a resident channel pipeline (see the
        module docstring for the fast-path contract)."""
        return CompiledDAG(self, **kwargs)

    def experimental_compile(self, **kwargs) -> "CompiledDAG":
        return self.compile(**kwargs)

    def _walk(self, seen, order):
        if id(self) in seen:
            return
        seen.add(id(self))
        for u in self.upstream:
            u._walk(seen, order)
        order.append(self)


class InputNode(DAGNode):
    """The driver-fed input (reference: ray.dag.InputNode)."""

    def __init__(self):
        super().__init__([])

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class ClassMethodNode(DAGNode):
    """One bound actor method (reference: dag/class_node.py)."""

    def __init__(self, actor_handle, method_name: str,
                 args: tuple["DAGNode", ...]):
        for a in args:
            if not isinstance(a, DAGNode):
                raise TypeError(
                    "compiled-DAG args must be DAG nodes (InputNode or "
                    "other bound methods); constants go in actor state")
        super().__init__(list(args))
        self.actor_handle = actor_handle
        self.method_name = method_name


class MultiOutputNode(DAGNode):
    """Fan-in terminal: execute() returns a list (reference:
    ray.dag.MultiOutputNode)."""

    def __init__(self, outputs: list[DAGNode]):
        super().__init__(list(outputs))


class CompiledDAGRef:
    """Result handle for one execute() (reference: CompiledDAGRef)."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq

    def get(self, timeout: float | None = 60.0) -> Any:
        return self._dag._fetch(self._seq, timeout)


class CompiledDAG:
    def __init__(self, output_node: DAGNode, max_inflight: int = 1024,
                 channel_capacity: int = _CHANNEL_CAP,
                 enable_fallback: bool = True):
        from ray_tpu.core.api import _global_runtime
        from ray_tpu.core.ids import ObjectID
        from ray_tpu.experimental.channel import Channel

        self._rt = _global_runtime()
        order: list[DAGNode] = []
        output_node._walk(set(), order)
        self._order = order
        inputs = [n for n in order if isinstance(n, InputNode)]
        if len(inputs) != 1:
            raise ValueError("a compiled DAG needs exactly one InputNode")
        self._multi = isinstance(output_node, MultiOutputNode)
        self._loop_prefix = f"dag_{os.urandom(4).hex()}"
        self._max_inflight = max(1, int(max_inflight))
        self._enable_fallback = enable_fallback
        self._broken = False  # actor died: every path goes eager
        # one channel per EDGE — a reusable SLOT named by a pre-allocated
        # object id, so the steady state re-uses N rings instead of
        # minting per-call object ids (reference: shared-memory mutable
        # objects, experimental/channel/shared_memory_channel.py)
        self._channels: list[Channel] = []

        def make_chan():
            c = Channel(name=f"dagc_{ObjectID.random().hex()[:20]}",
                        capacity=channel_capacity, create=True)
            self._channels.append(c)
            return c

        compute_nodes = [n for n in order
                         if isinstance(n, ClassMethodNode)]
        self._compute_nodes = compute_nodes
        terminals = (output_node.upstream if self._multi
                     else [output_node])
        for t in terminals:
            if not isinstance(t, ClassMethodNode):
                raise ValueError("DAG outputs must be bound actor methods")
        self._terminals = terminals
        # input edges the driver writes directly
        self._input_edges: list[Channel] = []
        # per-node in/out channel wiring
        node_out: dict[int, list[Channel]] = {}
        node_ins: dict[int, list[Channel]] = {}
        for n in compute_nodes:
            node_ins[id(n)] = []
            for u in n.upstream:
                c = make_chan()
                node_ins[id(n)].append(c)
                if isinstance(u, InputNode):
                    self._input_edges.append(c)
                else:
                    node_out.setdefault(id(u), []).append(c)
        # terminal outputs flow to the driver through one channel each;
        # a node feeding BOTH another node and the driver fans out below
        self._output_chans: list[Channel] = []
        for t in terminals:
            c = make_chan()
            node_out.setdefault(id(t), []).append(c)
            self._output_chans.append(c)
        # install per-actor loops. Fan-out (one producer, many consumer
        # channels) rides a driver-side pump when needed; the common
        # chain/tree case is pure actor-to-actor.
        self._pumps: list[threading.Thread] = []
        self._stop = threading.Event()
        self._loop_ids: list[tuple[str, str]] = []  # (actor addr, loop_id)
        for i, n in enumerate(compute_nodes):
            outs = node_out.get(id(n), [])
            if len(outs) > 1:
                mid = make_chan()
                self._start_pump(mid, outs)
                primary = mid
            else:
                primary = outs[0]
            addr = self._rt._resolve_actor(n.actor_handle._actor_id.binary())
            loop_id = f"{self._loop_prefix}_{i}"
            self._rt.client.call(addr, "dag_start", {
                "loop_id": loop_id,
                "method": n.method_name,
                "in_channels": [c.name for c in node_ins[id(n)]],
                "out_channel": primary.name,
            }, timeout=30)
            self._loop_ids.append((addr, loop_id))
        if len(self._input_edges) > 1:
            # one driver write fans out to every input consumer
            first = make_chan()
            self._start_pump(first, self._input_edges)
            self._write_chan = first
        else:
            self._write_chan = self._input_edges[0]
        self._seq = 0
        self._fetched = 0  # results drained from the output channels
        self._results: dict[int, Any] = {}
        # inputs of not-yet-fetched executions, retained so an actor
        # death can REPLAY them through the eager path (bounded by
        # max_inflight; popped as their row is assembled)
        self._pending_inputs: dict[int, Any] = {}
        # values already drained from SOME output channels of the row
        # currently being assembled — survives a get() timeout so a
        # partially-drained multi-output row is resumed, never lost
        self._partial: list = []
        self._fetch_lock = threading.Lock()
        # driver-side backpressure: execute() blocks here once
        # max_inflight executions are unfetched
        self._flow = threading.Condition()
        # channel writes leave in seq order (concurrent execute())
        self._write_cond = threading.Condition()
        self._next_write = 0

    def _start_pump(self, src, dsts):
        def pump():
            while not self._stop.is_set():
                try:
                    v = src.get(timeout=0.5)
                except TimeoutError:
                    continue
                except Exception:  # noqa: BLE001
                    return
                for d in dsts:
                    try:
                        d.put(v, timeout=60)
                    except Exception:  # noqa: BLE001
                        return

        t = threading.Thread(target=pump, daemon=True, name="dag-pump")
        t.start()
        self._pumps.append(t)

    # ------------------------------------------------------------ public

    def execute(self, value: Any) -> CompiledDAGRef:
        """One pipelined execution: a channel write; results stream back
        in order (reference: CompiledDAG.execute). Blocks once
        max_inflight executions are in the pipe (backpressure: a fast
        submitter cannot overrun the slowest stage's channel slots)."""
        t0 = time.monotonic_ns()
        with self._flow:
            # the cap applies on the eager-fallback path too: retained
            # inputs are the fallback's replay state and must stay as
            # bounded as the channel-resident work they replace
            while self._seq - self._fetched >= self._max_inflight:
                if not self._flow.wait(timeout=60.0) and \
                        self._seq - self._fetched >= self._max_inflight:
                    raise TimeoutError(
                        "compiled DAG backpressured for 60s: "
                        "max_inflight results unfetched")
            seq = self._seq
            self._seq += 1
            self._pending_inputs[seq] = value
        # channel writes are serialized IN SEQ ORDER: two concurrent
        # execute() calls must not land their inputs swapped, or the
        # in-order result rows would resolve against the wrong refs
        with self._write_cond:
            while self._next_write != seq and not self._broken:
                self._write_cond.wait(timeout=1.0)
            if not self._broken:
                try:
                    self._write_chan.put(value, timeout=60)
                except Exception:  # noqa: BLE001
                    # pipeline wedged (channel closed / full forever):
                    # flip to the eager path — the value is retained,
                    # the row gets filled at fetch time
                    self._broken = True
            self._next_write = max(self._next_write, seq + 1)
            self._write_cond.notify_all()
        ref = CompiledDAGRef(self, seq)
        _count_execution(fallback=self._broken)
        self._rt._events.record(
            f"dag.execute:{seq}", "dag", t0,
            trace={"trace_id": f"dag:{self._loop_prefix}:{seq}"})
        return ref

    def _fetch(self, seq: int, timeout):
        """Results arrive strictly in execution order (SPSC channels):
        drain until `seq` has landed. Errors CONSUME their slot like any
        result — raising without recording would desynchronize every
        later execution's sequence number. A drain that stalls past its
        poll slice probes the DAG's actors; a dead actor flips the DAG
        to the eager path and pending executions replay there."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._fetch_lock:
            while seq not in self._results:
                if self._broken:
                    self._fallback_fill()
                    continue
                try:
                    self._drain_row(deadline)
                except _PipelineStalled:
                    self._broken = True  # probe said an actor is dead
                    continue
            out = self._results.pop(seq)
            self._pending_inputs.pop(seq, None)
            if isinstance(out, _DagError):
                out.raise_()
            return out

    def _drain_row(self, deadline):
        """Assemble the next result row from the output channels (called
        under _fetch_lock). Channel pops are destructive, so partially
        drained rows persist in self._partial across timeouts."""
        from ray_tpu.experimental.channel import ChannelClosed

        stalls = 0
        next_probe = 1  # probe backoff: 1, 2, 4, ... slices (cap 16)
        while len(self._partial) < len(self._output_chans):
            c = self._output_chans[len(self._partial)]
            rem = (None if deadline is None
                   else deadline - time.monotonic())
            if rem is not None and rem <= 0:
                raise TimeoutError("compiled DAG result timed out")
            try:
                # short poll slices so a dead mid-chain actor is
                # detected in ~1s instead of blocking the full window
                self._partial.append(
                    c.get(timeout=min(1.0, rem) if rem is not None
                          else 1.0))
                stalls = 0
                next_probe = 1
            except TimeoutError:
                # probe with exponential backoff: a legitimately SLOW
                # stage (30s inference step) must not turn every
                # blocked get into 1 head RPC per second
                stalls += 1
                if self._enable_fallback and stalls >= next_probe:
                    if self._any_actor_dead():
                        raise _PipelineStalled from None
                    next_probe = min(next_probe * 2, 16)
                    stalls = 0
                continue
            except ChannelClosed:
                if self._enable_fallback:
                    raise _PipelineStalled from None
                raise
        outs, self._partial = self._partial, []
        err = next((o["__dag_error__"] for o in outs
                    if isinstance(o, dict) and "__dag_error__" in o),
                   None)
        row = self._fetched
        self._results[row] = (
            _DagError(err) if err is not None
            else (outs if self._multi else outs[0]))
        self._pending_inputs.pop(row, None)
        self._fetched += 1
        with self._flow:
            self._flow.notify_all()

    # ------------------------------------------------------ eager fallback

    def _any_actor_dead(self) -> bool:
        """Pipeline-liveness probe (only runs when a drain stalls —
        never on the steady-state path). An actor that is DEAD is lost;
        so is one that restarted to a NEW address: the replacement
        process has no dag loop, so the compiled pipeline can never
        make progress even though the actor is ALIVE — both flip the
        DAG to the eager path."""
        replies = self._rt.client.call_gather(
            [(self._rt.head_address, "get_actor",
              {"actor_id": n.actor_handle._actor_id.binary(),
               "wait": False}) for n in self._compute_nodes],
            timeout=5)
        for r, (compiled_addr, _) in zip(replies, self._loop_ids):
            if r is None:
                return True  # head unreachable: treat as lost
            state = r.get("state")
            if state in ("DEAD", "UNKNOWN"):
                return True
            if state == "ALIVE" and r.get("address") != compiled_addr:
                return True  # restarted: loop gone with the process
        return False

    def _fallback_fill(self):
        """Replay every unfetched execution through the EAGER actor-call
        path, in order (called under _fetch_lock once _broken). The
        partially drained compiled row is discarded — the replay
        recomputes it whole; routing re-resolves through the heal
        plane, so restartable actors serve the replay and dead ones
        surface ActorDiedError exactly like an eager chain would."""
        self._partial = []
        # SNAPSHOT the sequence watermark under _flow: execute() racing
        # this fill advances _seq concurrently, and advancing _fetched
        # past a seq whose row was never filled would hang its fetch
        # forever (the raced execution is covered by the next fill —
        # _fetch re-enters here while its seq has no result)
        with self._flow:
            seq_snap = self._seq
        for s in range(self._fetched, seq_snap):
            if s in self._results:
                continue
            try:
                row = self._eager_once(self._pending_inputs.get(s))
            except BaseException as e:  # noqa: BLE001
                # strip the traceback: its frames hold _eager_once's
                # intermediate ObjectRefs, and an exception retained in
                # _results would pin their refcounts — stranding every
                # oid of the failed replay (TaskError already carries
                # the remote traceback as a string)
                e.__traceback__ = None
                row = _DagError(e)
            self._results[s] = row
            _count_execution(fallback=True)
        self._fetched = max(self._fetched, seq_snap)
        with self._flow:
            self._flow.notify_all()

    def _eager_once(self, value):
        """One execution through ordinary `.remote()` calls — the
        bit-parity reference for the compiled path (and its fallback)."""
        refs: dict[int, Any] = {}
        for n in self._order:
            if isinstance(n, InputNode):
                refs[id(n)] = value
            elif isinstance(n, ClassMethodNode):
                args = [refs[id(u)] for u in n.upstream]
                refs[id(n)] = getattr(
                    n.actor_handle, n.method_name).remote(*args)
        outs = self._rt.get([refs[id(t)] for t in self._terminals],
                            timeout=60)
        return outs if self._multi else outs[0]

    def teardown(self):
        self._stop.set()
        for addr, loop_id in self._loop_ids:
            try:
                self._rt.client.call(addr, "dag_stop",
                                     {"loop_id": loop_id}, timeout=10)
            except Exception:  # noqa: BLE001
                pass
        # driver-side pump threads poll at 0.5s: JOIN them before
        # unmapping the segments (destroying under a reader is a UAF on
        # the mmap'd base — segfault, not an exception)
        for t in self._pumps:
            t.join(timeout=2.0)
        # closing marks the ring closed so any still-blocked worker
        # reader exits cleanly before we unlink the names (their own
        # mappings stay valid until their process detaches)
        for c in self._channels:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        time.sleep(0.1)
        for c in self._channels:
            try:
                c.destroy()
            except Exception:  # noqa: BLE001
                pass
        self._pending_inputs.clear()


class _PipelineStalled(Exception):
    """Internal: the compiled pipeline cannot make progress (dead actor
    or closed channel); the fetch loop flips to the eager path."""


__all__ = ["ClassMethodNode", "CompiledDAG", "CompiledDAGRef", "DAGNode",
           "InputNode", "MultiOutputNode"]
