"""Mixture-of-Experts layer with expert parallelism.

SURVEY.md §7.8: EP is a first-class capability the reference lacks
natively (it schedules frameworks that do it). TPU-native design:

- top-k softmax gating with capacity-based token dropping (Switch/GShard
  style): dispatch/combine are one-hot einsums — MXU-friendly, static
  shapes, no sorting;
- the expert dimension of expert weights carries the `expert` mesh axis
  in its partition rule; with tokens sharded on (data, fsdp) and experts
  sharded on `expert`, GSPMD lowers the dispatch einsum to the
  all-to-all over ICI that a hand-written NCCL MoE would issue;
- f32 gate statistics, bf16 expert compute; auxiliary load-balancing
  loss (Switch §2.2 form) returned alongside.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel.sharding import constrain


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    d_model: int = 128
    d_ff: int = 512
    dtype: object = jnp.bfloat16


def init_moe(key: jax.Array, cfg: MoEConfig) -> dict:
    kg, k1, k2 = jax.random.split(key, 3)
    E, Dm, Df = cfg.num_experts, cfg.d_model, cfg.d_ff
    s1 = (2.0 / Dm) ** 0.5
    s2 = (2.0 / Df) ** 0.5
    return {
        "gate": {"kernel": jax.random.normal(kg, (Dm, E)) * 0.02},
        "wi": jax.random.normal(k1, (E, Dm, Df)) * s1,  # expert-sharded
        "wo": jax.random.normal(k2, (E, Df, Dm)) * s2,
    }


def moe_partition_rules() -> list[tuple[str, P]]:
    """Merge into a model's PartitionRules: expert weights shard their
    leading (expert) dim on the `expert` axis, ff dim on `tensor`."""
    return [
        (r"moe/wi$", P("expert", "fsdp", "tensor")),
        (r"moe/wo$", P("expert", "tensor", "fsdp")),
        (r"moe/gate/kernel$", P(None, None)),
    ]


def moe_layer(params: dict, x: jax.Array, cfg: MoEConfig,
              ) -> tuple[jax.Array, jax.Array]:
    """x: (B, T, Dm) -> (out (B, T, Dm), aux_loss scalar)."""
    B, T, Dm = x.shape
    E = cfg.num_experts
    N = B * T
    cap = max(1, int(cfg.capacity_factor * N * cfg.top_k / E))
    xt = x.reshape(N, Dm)

    gate_logits = (xt.astype(jnp.float32)
                   @ params["gate"]["kernel"].astype(jnp.float32))  # (N, E)
    probs = jax.nn.softmax(gate_logits, axis=-1)

    # top-k expert choice per token
    gate_vals, gate_idx = jax.lax.top_k(probs, cfg.top_k)  # (N, k)
    gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)

    # capacity assignment: position of each token within its expert's
    # queue, computed per (k)-choice with a running cumsum (GShard-style)
    combine = jnp.zeros((N, E, cap), jnp.float32)
    used = jnp.zeros((N, E), jnp.float32)  # one-hot accumulation for aux
    position_in_expert = jnp.zeros((E,), jnp.int32)
    for choice in range(cfg.top_k):
        idx = gate_idx[:, choice]  # (N,)
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # (N, E)
        # rank of each token within this expert across the batch
        pos = (jnp.cumsum(onehot, axis=0) - onehot) + \
            position_in_expert[None, :].astype(jnp.float32)
        position_in_expert = position_in_expert + \
            jnp.sum(onehot, axis=0).astype(jnp.int32)
        pos_tok = jnp.sum(pos * onehot, axis=-1)  # (N,)
        keep = pos_tok < cap
        w = gate_vals[:, choice] * keep.astype(jnp.float32)
        pos_oh = jax.nn.one_hot(pos_tok.astype(jnp.int32), cap,
                                dtype=jnp.float32)  # (N, cap)
        combine = combine + w[:, None, None] * onehot[:, :, None] \
            * pos_oh[:, None, :]
        used = used + onehot

    dispatch = (combine > 0.0).astype(cfg.dtype)  # (N, E, cap)

    # dispatch: (N,E,cap) x (N,Dm) -> (E,cap,Dm); sharded over `expert`
    xe = jnp.einsum("nec,nd->ecd", dispatch, xt.astype(cfg.dtype))
    xe = constrain(xe, "expert", None, None)
    h = jnp.einsum("ecd,edf->ecf", xe, params["wi"].astype(cfg.dtype))
    h = jax.nn.gelu(h)
    ye = jnp.einsum("ecf,efd->ecd", h, params["wo"].astype(cfg.dtype))
    ye = constrain(ye, "expert", None, None)
    # combine back: weighted sum over experts/capacity slots
    out = jnp.einsum("nec,ecd->nd", combine.astype(cfg.dtype), ye)

    # Switch-style load balancing aux loss: E * sum_e f_e * p_e
    frac_tokens = jnp.mean(used, axis=0) / cfg.top_k  # (E,)
    frac_probs = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac_tokens * frac_probs)
    return out.reshape(B, T, Dm).astype(x.dtype), aux
