"""Llama-family transformer — RMSNorm, RoPE, SwiGLU, grouped-query attn.

Second flagship model family beside GPT-2 (SURVEY.md §2.4 model breadth;
the reference trains Llama-class models through TorchTrainer — here the
architecture is built TPU-first like models/gpt2.py): scan-stacked
blocks, Megatron-sharded partition rules over the canonical mesh axes,
bf16 activations with f32 norms, flash attention via ops.attention, and
GQA (n_kv_heads < n_heads) with K/V head replication at attention time.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import causal_attention
from ray_tpu.parallel.sharding import PartitionRules, constrain

Params = Any


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    n_layer: int = 8
    n_head: int = 8
    n_kv_head: int = 4  # grouped-query attention
    n_embd: int = 512
    intermediate: int = 1408  # SwiGLU hidden (~8/3 * n_embd, 128-aligned)
    block_size: int = 1024
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    @property
    def padded_vocab(self) -> int:
        return ((self.vocab_size + 127) // 128) * 128

    @staticmethod
    def tiny() -> "LlamaConfig":
        return LlamaConfig(vocab_size=512, n_layer=2, n_head=4, n_kv_head=2,
                           n_embd=128, intermediate=384, block_size=128,
                           dtype=jnp.float32, remat=False)

    @staticmethod
    def small() -> "LlamaConfig":
        """~110M-param config comparable to GPT-2-small for benching."""
        return LlamaConfig(vocab_size=32000, n_layer=12, n_head=12,
                           n_kv_head=4, n_embd=768, intermediate=2048,
                           block_size=1024)


def llama_partition_rules() -> PartitionRules:
    """Megatron layout over the canonical axes: attention/MLP input
    projections sharded on the output dim over 'tensor', output
    projections on the input dim; embeddings vocab-sharded; everything
    fsdp-sharded on the other dim."""
    from jax.sharding import PartitionSpec as P

    # block params are scan-STACKED: leading dim is the layer axis and
    # must stay unsharded (None), like gpt2_partition_rules
    return PartitionRules([
        (r"blocks/(wq|wk|wv)$", P(None, "fsdp", "tensor")),
        (r"blocks/wo$", P(None, "tensor", "fsdp")),
        (r"blocks/(w_gate|w_up)$", P(None, "fsdp", "tensor")),
        (r"blocks/w_down$", P(None, "tensor", "fsdp")),
        (r"blocks/(ln_attn|ln_mlp)$", P()),
        (r"wte$", P("tensor", "fsdp")),
        (r"lnf$", P()),
        (r".*", P()),
    ])


def init_llama(key: jax.Array, cfg: LlamaConfig) -> Params:
    L, E, V = cfg.n_layer, cfg.n_embd, cfg.padded_vocab
    hd = cfg.head_dim
    kv_dim = cfg.n_kv_head * hd
    std = 0.02
    out_std = std / math.sqrt(2 * L)
    ks = jax.random.split(key, 8)

    def stack(base, shape, scale):
        keys = jax.random.split(base, L)
        return jnp.stack([jax.random.normal(keys[i], shape, jnp.float32)
                          * scale for i in range(L)])

    return {
        "wte": jax.random.normal(ks[0], (V, E), jnp.float32) * std,
        "blocks": {
            "ln_attn": jnp.ones((L, E)),
            "wq": stack(ks[1], (E, E), std),
            "wk": stack(ks[2], (E, kv_dim), std),
            "wv": stack(ks[3], (E, kv_dim), std),
            "wo": stack(ks[4], (E, E), out_std),
            "ln_mlp": jnp.ones((L, E)),
            "w_gate": stack(ks[5], (E, cfg.intermediate), std),
            "w_up": stack(ks[6], (E, cfg.intermediate), std),
            "w_down": stack(ks[7], (cfg.intermediate, E), out_std),
        },
        "lnf": jnp.ones((E,)),
    }


def _rmsnorm(x, scale, eps):
    x32 = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * rms * scale).astype(x.dtype)


def _rope(x, theta: float):
    """Rotary embedding over the last dim of (B, T, H, D)."""
    B, T, H, D = x.shape
    half = D // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = jnp.arange(T, dtype=jnp.float32)[:, None] * freqs[None, :]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rotated.astype(x.dtype)


def _rope_at(x, positions, theta: float):
    """Rotary embedding for single-token decode: x (B, H, D) rotated by
    each sequence's absolute position (B,). Same formula as `_rope`, so
    cached prefill K and decode K agree bit-for-bit per position."""
    B, H, D = x.shape
    half = D // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions.astype(jnp.float32)[:, None] * freqs[None, :]
    cos = jnp.cos(angles)[:, None, :]
    sin = jnp.sin(angles)[:, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rotated.astype(x.dtype)


def _block_kv(x, p, cfg: LlamaConfig):
    """One block; also returns post-rope, pre-GQA-replication K/V heads
    (B, T, H_kv, D) — the layout serve.llm caches (decode replicates at
    attention time, like the forward path)."""
    B, T, E = x.shape
    dt = cfg.dtype
    hd = cfg.head_dim

    h = _rmsnorm(x, p["ln_attn"], cfg.rms_eps)
    q = (h @ p["wq"].astype(dt)).reshape(B, T, cfg.n_head, hd)
    k = (h @ p["wk"].astype(dt)).reshape(B, T, cfg.n_kv_head, hd)
    v = (h @ p["wv"].astype(dt)).reshape(B, T, cfg.n_kv_head, hd)
    q = _rope(q, cfg.rope_theta)
    k = _rope(k, cfg.rope_theta)
    k_cache, v_cache = k, v
    # GQA: replicate K/V heads up to n_head (reference semantics of
    # repeat_kv; XLA turns the broadcast into reuse, no materialized copy
    # survives fusion)
    rep = cfg.n_head // cfg.n_kv_head
    k = jnp.repeat(k, rep, axis=2)
    v = jnp.repeat(v, rep, axis=2)
    att = causal_attention(q, k, v).reshape(B, T, E)
    att = att @ p["wo"].astype(dt)
    x = x + constrain(att, ("data", "fsdp"), None, None)

    h = _rmsnorm(x, p["ln_mlp"], cfg.rms_eps)
    gate = h @ p["w_gate"].astype(dt)
    up = h @ p["w_up"].astype(dt)
    gate = constrain(gate, ("data", "fsdp"), None, "tensor")
    h = (jax.nn.silu(gate) * up) @ p["w_down"].astype(dt)
    x = x + constrain(h, ("data", "fsdp"), None, None)
    return x, (k_cache, v_cache)


def _block(x, p, cfg: LlamaConfig):
    return _block_kv(x, p, cfg)[0]


def llama_forward(params: Params, tokens: jax.Array,
                  cfg: LlamaConfig) -> jax.Array:
    """tokens (B, T) int32 -> logits (B, T, padded_vocab) float32."""
    B, T = tokens.shape
    dt = cfg.dtype
    wte = constrain(params["wte"].astype(dt), None, None)
    x = wte[tokens]
    x = constrain(x, ("data", "fsdp"), None, None)

    block = _block
    if cfg.remat:
        block = jax.checkpoint(_block, static_argnums=(2,))

    def body(carry, layer_params):
        return block(carry, layer_params, cfg), None

    x, _ = jax.lax.scan(body, x, params["blocks"])
    x = _rmsnorm(x, params["lnf"], cfg.rms_eps)
    logits = x @ params["wte"].astype(dt).T
    logits = constrain(logits, ("data", "fsdp"), None, "tensor")
    return logits.astype(jnp.float32)


# --------------------------------------------------------------------------
# KV-cache inference steps (serve.llm) — see models/gpt2.py for the
# layering contract: models own the math, serve/llm/runner.py owns the
# paged gather/scatter. K is cached POST-rope with n_kv_head heads.


def llama_prefill_kv(
    params: Params, tokens: jax.Array, cfg: LlamaConfig
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """tokens (B, T) -> (logits (B, T, Vp) f32, k, v (L, B, T, Hkv, D))."""
    dt = cfg.dtype
    wte = constrain(params["wte"].astype(dt), None, None)
    x = wte[tokens]
    x = constrain(x, ("data", "fsdp"), None, None)

    def body(carry, layer_params):
        y, (k, v) = _block_kv(carry, layer_params, cfg)
        return y, (k, v)

    x, (k, v) = jax.lax.scan(body, x, params["blocks"])
    x = _rmsnorm(x, params["lnf"], cfg.rms_eps)
    logits = x @ params["wte"].astype(dt).T
    logits = constrain(logits, ("data", "fsdp"), None, "tensor")
    return logits.astype(jnp.float32), k, v


def _rope_chunk(x, start, theta: float):
    """Rotary embedding for a chunk at absolute positions
    start..start+T-1 (start traced): x (B, T, H, D). Same formula as
    `_rope`/`_rope_at`, so chunked K agrees bit-for-bit per position."""
    B, T, H, D = x.shape
    half = D // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    pos = (start + jnp.arange(T)).astype(jnp.float32)
    angles = pos[:, None] * freqs[None, :]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rotated.astype(x.dtype)


def _chunk_block(x, p, k_ctx, v_ctx, ctx_mask, chunk_mask, start,
                 cfg: LlamaConfig, attend=None):
    """Chunked-prefill block step; see models/gpt2.py `_chunk_block`.
    x (B, T, E) at absolute positions start..start+T-1; k_ctx/v_ctx
    (B, C, Hkv, D) post-rope cached context. Returns (x, (k, v)) with
    k/v (B, T, Hkv, D) post-rope, pre-GQA-replication — the cached
    layout. ``attend(q, k, v) -> (B, T, H, D)`` (k/v pre-replication)
    swaps in the paged-attention kernel, which does the GQA head
    mapping itself."""
    B, T, E = x.shape
    dt = cfg.dtype
    hd = cfg.head_dim
    H, HK = cfg.n_head, cfg.n_kv_head

    h = _rmsnorm(x, p["ln_attn"], cfg.rms_eps)
    q = (h @ p["wq"].astype(dt)).reshape(B, T, H, hd)
    k = (h @ p["wk"].astype(dt)).reshape(B, T, HK, hd)
    v = (h @ p["wv"].astype(dt)).reshape(B, T, HK, hd)
    q = _rope_chunk(q, start, cfg.rope_theta)
    k = _rope_chunk(k, start, cfg.rope_theta)
    k_cache, v_cache = k, v

    if attend is not None:
        att = attend(q, k, v).reshape(B, T, E) @ p["wo"].astype(dt)
    else:
        rep = H // HK
        kce = jnp.repeat(k_ctx, rep, axis=2)
        vce = jnp.repeat(v_ctx, rep, axis=2)
        ke = jnp.repeat(k, rep, axis=2)
        ve = jnp.repeat(v, rep, axis=2)

        scale = 1.0 / (hd**0.5)
        s_ctx = jnp.einsum("bthd,bchd->bhtc", q, kce).astype(jnp.float32)
        s_own = jnp.einsum("bthd,bshd->bhts", q, ke).astype(jnp.float32)
        s = jnp.concatenate([s_ctx, s_own], axis=-1) * scale
        causal = jnp.tril(jnp.ones((T, T), dtype=bool))
        valid = jnp.concatenate(
            [jnp.broadcast_to(ctx_mask[:, None, :],
                              (B, T, ctx_mask.shape[1])),
             causal[None] & chunk_mask[:, None, :]], axis=-1)
        s = jnp.where(valid[:, None, :, :], s, -1e30)
        probs = jax.nn.softmax(s, axis=-1).astype(dt)
        C = k_ctx.shape[1]
        att = jnp.einsum("bhtc,bchd->bthd", probs[..., :C], vce) \
            + jnp.einsum("bhts,bshd->bthd", probs[..., C:], ve)
        att = att.reshape(B, T, E) @ p["wo"].astype(dt)
    x = x + constrain(att, ("data", "fsdp"), None, None)

    h = _rmsnorm(x, p["ln_mlp"], cfg.rms_eps)
    gate = h @ p["w_gate"].astype(dt)
    up = h @ p["w_up"].astype(dt)
    gate = constrain(gate, ("data", "fsdp"), None, "tensor")
    x = x + constrain(
        (jax.nn.silu(gate) * up) @ p["w_down"].astype(dt),
        ("data", "fsdp"), None, None)
    return x, (k_cache, v_cache)


def llama_prefill_chunk_kv(
    params: Params,
    tokens: jax.Array,
    start: jax.Array,
    k_ctx: jax.Array,
    v_ctx: jax.Array,
    ctx_mask: jax.Array,
    chunk_mask: jax.Array,
    cfg: LlamaConfig,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Chunked prefill from a position offset; see gpt2_prefill_chunk_kv.
    k_ctx/v_ctx are (L, B, C, Hkv, D); returns (logits (B, T, Vp) f32,
    k, v (L, B, T, Hkv, D))."""
    dt = cfg.dtype
    wte = constrain(params["wte"].astype(dt), None, None)
    x = wte[tokens]
    x = constrain(x, ("data", "fsdp"), None, None)

    def body(carry, xs):
        p, kc, vc = xs
        return _chunk_block(carry, p, kc, vc, ctx_mask, chunk_mask,
                            start, cfg)

    x, (k, v) = jax.lax.scan(body, x, (params["blocks"], k_ctx, v_ctx))
    x = _rmsnorm(x, params["lnf"], cfg.rms_eps)
    logits = x @ params["wte"].astype(dt).T
    logits = constrain(logits, ("data", "fsdp"), None, "tensor")
    return logits.astype(jnp.float32), k, v


def _decode_block(x, p, k_ctx, v_ctx, ctx_mask, positions, cfg: LlamaConfig,
                  attend=None):
    """Single-token block step; x (B, E), k_ctx/v_ctx (B, C, Hkv, D)
    post-rope cached context, ctx_mask (B, C), positions (B,).
    Returns (x, (k_new, v_new)) with k_new/v_new (B, Hkv, D).
    ``attend(q, k, v) -> (B, H, D)`` (k/v pre-replication) swaps in the
    paged-attention kernel (see `_chunk_block`)."""
    B, E = x.shape
    dt = cfg.dtype
    hd = cfg.head_dim
    H, HK = cfg.n_head, cfg.n_kv_head

    h = _rmsnorm(x, p["ln_attn"], cfg.rms_eps)
    q = (h @ p["wq"].astype(dt)).reshape(B, H, hd)
    k = (h @ p["wk"].astype(dt)).reshape(B, HK, hd)
    v = (h @ p["wv"].astype(dt)).reshape(B, HK, hd)
    q = _rope_at(q, positions, cfg.rope_theta)
    k = _rope_at(k, positions, cfg.rope_theta)

    if attend is not None:
        att = attend(q, k, v).reshape(B, E) @ p["wo"].astype(dt)
    else:
        rep = H // HK
        kce = jnp.repeat(k_ctx, rep, axis=2)
        vce = jnp.repeat(v_ctx, rep, axis=2)
        ke = jnp.repeat(k, rep, axis=1)
        ve = jnp.repeat(v, rep, axis=1)

        scale = 1.0 / (hd**0.5)
        s_ctx = jnp.einsum("bhd,bchd->bhc", q, kce).astype(jnp.float32)
        s_own = jnp.sum(q * ke, axis=-1, dtype=jnp.float32)
        s = jnp.concatenate([s_ctx, s_own[:, :, None]], axis=-1) * scale
        valid = jnp.concatenate(
            [ctx_mask, jnp.ones((B, 1), dtype=bool)], axis=-1)
        s = jnp.where(valid[:, None, :], s, -1e30)
        probs = jax.nn.softmax(s, axis=-1).astype(dt)
        att = jnp.einsum("bhc,bchd->bhd", probs[..., :-1], vce) \
            + probs[..., -1:] * ve
        att = att.reshape(B, E) @ p["wo"].astype(dt)
    x = x + constrain(att, ("data", "fsdp"), None)

    h = _rmsnorm(x, p["ln_mlp"], cfg.rms_eps)
    gate = h @ p["w_gate"].astype(dt)
    up = h @ p["w_up"].astype(dt)
    gate = constrain(gate, ("data", "fsdp"), "tensor")
    x = x + constrain(
        (jax.nn.silu(gate) * up) @ p["w_down"].astype(dt),
        ("data", "fsdp"), None)
    return x, (k, v)


def llama_decode_kv(
    params: Params,
    tokens: jax.Array,
    positions: jax.Array,
    k_ctx: jax.Array,
    v_ctx: jax.Array,
    ctx_mask: jax.Array,
    cfg: LlamaConfig,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step; see gpt2_decode_kv. k_ctx/v_ctx are
    (L, B, C, Hkv, D); returns (logits (B, Vp) f32, k_new, v_new
    (L, B, Hkv, D))."""
    dt = cfg.dtype
    x = params["wte"].astype(dt)[tokens]

    def body(carry, xs):
        p, kc, vc = xs
        return _decode_block(carry, p, kc, vc, ctx_mask, positions, cfg)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["blocks"], k_ctx, v_ctx))
    x = _rmsnorm(x, params["lnf"], cfg.rms_eps)
    logits = x @ params["wte"].astype(dt).T
    return logits.astype(jnp.float32), k_new, v_new


# --------------------------------------------------------------------------
# Paged-attention inference steps — see models/gpt2.py: same block math
# through the `attend` hook, attention core is the ops/paged_attention
# kernel over the page pool (L, num_blocks, block_size, Hkv, D). The
# kernel does the GQA head mapping, so K/V stay pre-replication.


def llama_decode_paged_kv(
    params: Params,
    tokens: jax.Array,
    positions: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    tables: jax.Array,
    cfg: LlamaConfig,
    *,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step against the page pool; see llama_decode_kv.
    Returns (logits (B, Vp) f32, k_new, v_new (L, B, Hkv, D))."""
    from ray_tpu.ops.paged_attention import paged_attention

    dt = cfg.dtype
    x = params["wte"].astype(dt)[tokens]

    def body(carry, xs):
        p, kp, vp = xs

        def attend(q, k, v):
            o = paged_attention(q[:, None], k[:, None], v[:, None],
                                kp, vp, tables, positions,
                                interpret=interpret)
            return o[:, 0]

        return _decode_block(carry, p, None, None, None, positions,
                             cfg, attend=attend)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["blocks"], k_pages, v_pages))
    x = _rmsnorm(x, params["lnf"], cfg.rms_eps)
    logits = x @ params["wte"].astype(dt).T
    return logits.astype(jnp.float32), k_new, v_new


def llama_verify_paged_kv(
    params: Params,
    tokens: jax.Array,
    start: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    table: jax.Array,
    cfg: LlamaConfig,
    *,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Speculative verify window against the page pool; see
    gpt2_verify_paged_kv. tokens (1, W) at positions start..start+W-1.
    Returns (logits (1, W, Vp) f32, k, v (L, 1, W, Hkv, D))."""
    from ray_tpu.ops.paged_attention import paged_attention

    dt = cfg.dtype
    x = params["wte"].astype(dt)[tokens]
    tables = table[None]  # (1, maxB)
    ctx_len = jnp.reshape(jnp.asarray(start, jnp.int32), (1,))

    def body(carry, xs):
        p, kp, vp = xs

        def attend(q, k, v):
            return paged_attention(q, k, v, kp, vp, tables, ctx_len,
                                   interpret=interpret)

        return _chunk_block(carry, p, None, None, None, None, start,
                            cfg, attend=attend)

    x, (k, v) = jax.lax.scan(body, x, (params["blocks"], k_pages, v_pages))
    x = _rmsnorm(x, params["lnf"], cfg.rms_eps)
    logits = x @ params["wte"].astype(dt).T
    return logits.astype(jnp.float32), k, v


def llama_loss(params: Params, batch: dict, cfg: LlamaConfig) -> jax.Array:
    logits = llama_forward(params, batch["tokens"], cfg)
    V = cfg.padded_vocab
    mask = jnp.arange(V) < cfg.vocab_size
    logits = jnp.where(mask, logits, -1e9)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, batch["targets"][..., None],
                             axis=-1)[..., 0]
    return -jnp.mean(ll)
