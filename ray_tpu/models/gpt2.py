"""GPT-2 in pure JAX, designed for mesh sharding.

This is the flagship Train model (reference benchmark: "TorchTrainer
GPT-2-small DDP", BASELINE.json). TPU-first design decisions:

- transformer blocks are *stacked* along a leading layer axis and executed
  with `lax.scan`: one compiled block body regardless of depth (fast
  compiles, XLA-friendly), instead of a Python loop of modules,
- parameters are a plain nested-dict pytree with declarative partition
  rules (ray_tpu.parallel.sharding) covering data/fsdp/tensor axes:
  Megatron-style column->row sharding inside attention and the MLP so the
  only tensor-axis collective per block is one psum (inserted by GSPMD),
- activations carry sharding constraints on the batch (data+fsdp) and
  hidden (tensor) dimensions,
- compute dtype bfloat16 (MXU-native), params float32.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel.sharding import PartitionRules, constrain
from ray_tpu.ops.attention import causal_attention

Params = Any


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_layer: int = 12
    n_head: int = 12
    n_embd: int = 768
    block_size: int = 1024
    dtype: Any = jnp.bfloat16
    # Pad the vocab so the logits matmul tiles cleanly onto the MXU and
    # shards evenly over the tensor axis (50257 -> 50304 for gpt2-small).
    vocab_pad_multiple: int = 128
    remat: bool = True

    @property
    def padded_vocab(self) -> int:
        m = self.vocab_pad_multiple
        return (self.vocab_size + m - 1) // m * m

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    @staticmethod
    def small() -> "GPT2Config":
        return GPT2Config()

    @staticmethod
    def medium() -> "GPT2Config":
        return GPT2Config(n_layer=24, n_head=16, n_embd=1024)

    @staticmethod
    def large() -> "GPT2Config":
        return GPT2Config(n_layer=36, n_head=20, n_embd=1280)

    @staticmethod
    def xl() -> "GPT2Config":
        return GPT2Config(n_layer=48, n_head=25, n_embd=1600)

    @staticmethod
    def tiny(vocab_size: int = 512, block_size: int = 128) -> "GPT2Config":
        return GPT2Config(
            vocab_size=vocab_size,
            n_layer=2,
            n_head=4,
            n_embd=128,
            block_size=block_size,
            vocab_pad_multiple=128,
        )


def gpt2_partition_rules() -> PartitionRules:
    """Megatron-style sharding. Stacked block params have a leading layer
    dim (None). Column-parallel: qkv / mlp fc shard output dim on
    'tensor'; row-parallel: attn proj / mlp proj shard input dim on
    'tensor'. 'fsdp' shards the other matmul dim (ZeRO-3-style)."""
    return PartitionRules(
        [
            (r"wte$", P("tensor", "fsdp")),
            (r"wpe$", P(None, "fsdp")),
            (r"attn_qkv/kernel$", P(None, "fsdp", "tensor")),
            (r"attn_proj/kernel$", P(None, "tensor", "fsdp")),
            (r"mlp_fc/kernel$", P(None, "fsdp", "tensor")),
            (r"mlp_proj/kernel$", P(None, "tensor", "fsdp")),
            (r"attn_qkv/bias$", P(None, "tensor")),
            (r"mlp_fc/bias$", P(None, "tensor")),
            # layer norms, row-parallel biases: replicated
            (r".*", P()),
        ]
    )


def _dense_init(key, in_dim, out_dim, scale):
    return jax.random.normal(key, (in_dim, out_dim), jnp.float32) * scale


def init_gpt2(key: jax.Array, cfg: GPT2Config) -> Params:
    """Initialize parameters (float32 master copy), GPT-2 init scheme:
    normal(0.02), residual projections scaled by 1/sqrt(2*n_layer)."""
    k = jax.random.split(key, 8)
    L, E, V = cfg.n_layer, cfg.n_embd, cfg.padded_vocab
    std = 0.02
    resid_std = 0.02 / math.sqrt(2 * cfg.n_layer)

    def stack(idx, initializer):
        keys = jax.random.split(jax.random.fold_in(k[7], idx), L)
        return jnp.stack([initializer(keys[i]) for i in range(L)])

    def qkv(kk):
        return _dense_init(kk, E, 3 * E, std)

    def attn_proj(kk):
        return _dense_init(kk, E, E, resid_std)

    def mlp_fc(kk):
        return _dense_init(kk, E, 4 * E, std)

    def mlp_proj(kk):
        return _dense_init(kk, 4 * E, E, resid_std)

    blocks = {
        "ln1": {"scale": jnp.ones((L, E)), "bias": jnp.zeros((L, E))},
        "attn_qkv": {"kernel": stack(0, qkv), "bias": jnp.zeros((L, 3 * E))},
        "attn_proj": {"kernel": stack(1, attn_proj), "bias": jnp.zeros((L, E))},
        "ln2": {"scale": jnp.ones((L, E)), "bias": jnp.zeros((L, E))},
        "mlp_fc": {"kernel": stack(2, mlp_fc), "bias": jnp.zeros((L, 4 * E))},
        "mlp_proj": {"kernel": stack(3, mlp_proj), "bias": jnp.zeros((L, E))},
    }
    return {
        "wte": jax.random.normal(k[0], (V, E), jnp.float32) * std,
        "wpe": jax.random.normal(k[1], (cfg.block_size, E), jnp.float32) * std,
        "blocks": blocks,
        "lnf": {"scale": jnp.ones((E,)), "bias": jnp.zeros((E,))},
    }


def _layer_norm(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _block_kv(x, p, cfg: GPT2Config):
    """One transformer block. `p` holds this layer's (unstacked) params.
    Also returns this layer's attention K/V heads (B, T, H, D) so
    prefill (serve.llm) can seed a KV cache from the same math the
    training forward uses."""
    B, T, E = x.shape
    dt = cfg.dtype
    h = _layer_norm(x, p["ln1"]["scale"], p["ln1"]["bias"])
    qkv = h @ p["attn_qkv"]["kernel"].astype(dt) + p["attn_qkv"]["bias"].astype(dt)
    qkv = constrain(qkv, ("data", "fsdp"), None, "tensor")
    q, kk, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(B, T, cfg.n_head, cfg.head_dim)

    k_h, v_h = heads(kk), heads(v)
    att = causal_attention(heads(q), k_h, v_h)
    att = att.reshape(B, T, E)
    att = att @ p["attn_proj"]["kernel"].astype(dt) + p["attn_proj"]["bias"].astype(dt)
    x = x + constrain(att, ("data", "fsdp"), None, None)

    h = _layer_norm(x, p["ln2"]["scale"], p["ln2"]["bias"])
    h = h @ p["mlp_fc"]["kernel"].astype(dt) + p["mlp_fc"]["bias"].astype(dt)
    h = constrain(h, ("data", "fsdp"), None, "tensor")
    h = jax.nn.gelu(h)
    h = h @ p["mlp_proj"]["kernel"].astype(dt) + p["mlp_proj"]["bias"].astype(dt)
    x = x + constrain(h, ("data", "fsdp"), None, None)
    return x, (k_h, v_h)


def _block(x, p, cfg: GPT2Config):
    return _block_kv(x, p, cfg)[0]


def gpt2_forward(params: Params, tokens: jax.Array, cfg: GPT2Config) -> jax.Array:
    """tokens (B, T) int32 -> logits (B, T, padded_vocab) float32."""
    B, T = tokens.shape
    dt = cfg.dtype
    # The embedding table is vocab-sharded over 'tensor' (for the logits
    # matmul); a sharded gather would force XLA into an involuntary full
    # rematerialization, so explicitly all-gather it before the lookup
    # (it is small next to activations, and the transposed scatter-add in
    # backward then reduces cleanly).
    wte = constrain(params["wte"].astype(dt), None, None)
    x = wte[tokens] + params["wpe"].astype(dt)[:T]
    x = constrain(x, ("data", "fsdp"), None, None)

    block = _block
    if cfg.remat:
        # RAY_TPU_REMAT_POLICY selects what the backward replay reuses:
        # "full" (default) recomputes everything; "save_flash" keeps the
        # flash kernel's (o, lse); "save_dots" keeps all matmul outputs;
        # "none" disables remat.
        import os as _os

        # Default "full" is MEASURED fastest on v5e-class chips for
        # GPT-2-small (see PERF_NOTES.md): full recompute 0.354 MFU vs
        # save_flash 0.338, save_dots 0.339, none 0.320 — at this
        # model size the HBM traffic of saving residuals costs more
        # than the recompute FLOPs. Larger models (activation-bound)
        # should flip to save_flash/save_dots via this env lever.
        mode = _os.environ.get("RAY_TPU_REMAT_POLICY", "full")
        if mode == "save_flash":
            policy = jax.checkpoint_policies.save_only_these_names(
                "flash_o", "flash_lse")
            block = jax.checkpoint(_block, static_argnums=(2,),
                                   policy=policy)
        elif mode == "save_dots":
            # save every matmul output AND the flash residuals: the
            # replay only redoes elementwise work (LN/gelu)
            policy = jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                jax.checkpoint_policies.save_only_these_names(
                    "flash_o", "flash_lse"))
            block = jax.checkpoint(_block, static_argnums=(2,),
                                   policy=policy)
        elif mode == "none":
            pass  # no remat: all activations saved
        else:  # "full": recompute everything
            block = jax.checkpoint(_block, static_argnums=(2,))

    def body(carry, layer_params):
        return block(carry, layer_params, cfg), None

    x, _ = jax.lax.scan(body, x, params["blocks"])
    x = _layer_norm(x, params["lnf"]["scale"], params["lnf"]["bias"])
    logits = x @ params["wte"].astype(dt).T
    logits = constrain(logits, ("data", "fsdp"), None, "tensor")
    return logits.astype(jnp.float32)


def gpt2_loss(params: Params, batch: dict, cfg: GPT2Config) -> jax.Array:
    """Next-token cross entropy; positions past vocab_size are masked."""
    logits = gpt2_forward(params, batch["tokens"], cfg)
    targets = batch["targets"]
    V = cfg.padded_vocab
    mask = jnp.arange(V) < cfg.vocab_size
    logits = jnp.where(mask, logits, -1e9)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    weights = batch.get("weights")
    if weights is None:
        return -jnp.mean(ll)
    return -jnp.sum(ll * weights) / jnp.maximum(jnp.sum(weights), 1.0)


# --------------------------------------------------------------------------
# KV-cache inference steps (serve.llm). Prefill runs the full-sequence
# forward and additionally returns every layer's K/V heads; decode runs
# ONE token per sequence against externally gathered context K/V (the
# paged-cache gather/scatter lives in ray_tpu/serve/llm/runner.py — the
# model layer only owns the math, so parity with the training forward is
# checkable function-against-function).


def gpt2_prefill_kv(
    params: Params, tokens: jax.Array, cfg: GPT2Config
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """tokens (B, T) -> (logits (B, T, Vp) f32, k, v (L, B, T, H, D))."""
    B, T = tokens.shape
    dt = cfg.dtype
    wte = constrain(params["wte"].astype(dt), None, None)
    x = wte[tokens] + params["wpe"].astype(dt)[:T]
    x = constrain(x, ("data", "fsdp"), None, None)

    def body(carry, layer_params):
        y, (k, v) = _block_kv(carry, layer_params, cfg)
        return y, (k, v)

    x, (k, v) = jax.lax.scan(body, x, params["blocks"])
    x = _layer_norm(x, params["lnf"]["scale"], params["lnf"]["bias"])
    logits = x @ params["wte"].astype(dt).T
    logits = constrain(logits, ("data", "fsdp"), None, "tensor")
    return logits.astype(jnp.float32), k, v


def _chunk_block(x, p, k_ctx, v_ctx, ctx_mask, chunk_mask, cfg: GPT2Config,
                 attend=None):
    """Chunked-prefill block step. x (B, T, E) holds a CHUNK of the
    sequence at absolute positions start..start+T-1; k_ctx/v_ctx
    (B, C, H, D) hold the already-cached context for positions < start
    (ctx_mask (B, C) marks valid slots); chunk_mask (B, T) marks real
    (non-padded) chunk positions. Attention is context + causal within
    the chunk. Returns (x, (k, v)) with k/v (B, T, H, D) — the chunk's
    cache contribution.

    With ``attend`` set (paged-attention path) the dense context math
    is replaced by ``attend(q, k, v) -> (B, T, H, D)``: k_ctx/v_ctx are
    then this layer's page-pool arrays captured by the closure and the
    masking lives inside the kernel; projections/MLP stay shared with
    the dense path."""
    B, T, E = x.shape
    dt = cfg.dtype
    H, D = cfg.n_head, cfg.head_dim
    h = _layer_norm(x, p["ln1"]["scale"], p["ln1"]["bias"])
    qkv = h @ p["attn_qkv"]["kernel"].astype(dt) + p["attn_qkv"]["bias"].astype(dt)
    qkv = constrain(qkv, ("data", "fsdp"), None, "tensor")
    q, k, v = (t.reshape(B, T, H, D) for t in jnp.split(qkv, 3, axis=-1))

    if attend is not None:
        att = attend(q, k, v).reshape(B, T, E)
    else:
        scale = 1.0 / (D**0.5)
        s_ctx = jnp.einsum("bthd,bchd->bhtc", q, k_ctx).astype(jnp.float32)
        s_own = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32)
        s = jnp.concatenate([s_ctx, s_own], axis=-1) * scale
        causal = jnp.tril(jnp.ones((T, T), dtype=bool))
        valid = jnp.concatenate(
            [jnp.broadcast_to(ctx_mask[:, None, :],
                              (B, T, ctx_mask.shape[1])),
             causal[None] & chunk_mask[:, None, :]], axis=-1)
        s = jnp.where(valid[:, None, :, :], s, -1e30)
        probs = jax.nn.softmax(s, axis=-1).astype(dt)
        C = k_ctx.shape[1]
        att = jnp.einsum("bhtc,bchd->bthd", probs[..., :C], v_ctx) \
            + jnp.einsum("bhts,bshd->bthd", probs[..., C:], v)
        att = att.reshape(B, T, E)
    att = att @ p["attn_proj"]["kernel"].astype(dt) + p["attn_proj"]["bias"].astype(dt)
    x = x + constrain(att, ("data", "fsdp"), None, None)

    h = _layer_norm(x, p["ln2"]["scale"], p["ln2"]["bias"])
    h = h @ p["mlp_fc"]["kernel"].astype(dt) + p["mlp_fc"]["bias"].astype(dt)
    h = constrain(h, ("data", "fsdp"), None, "tensor")
    h = jax.nn.gelu(h)
    h = h @ p["mlp_proj"]["kernel"].astype(dt) + p["mlp_proj"]["bias"].astype(dt)
    x = x + constrain(h, ("data", "fsdp"), None, None)
    return x, (k, v)


def gpt2_prefill_chunk_kv(
    params: Params,
    tokens: jax.Array,
    start: jax.Array,
    k_ctx: jax.Array,
    v_ctx: jax.Array,
    ctx_mask: jax.Array,
    chunk_mask: jax.Array,
    cfg: GPT2Config,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Prefill a CHUNK of one or more sequences from a position offset
    (chunked / incremental prefill).

    tokens (B, T) sit at absolute positions start..start+T-1 (start is
    a traced scalar, so one compiled program serves every offset);
    k_ctx/v_ctx (L, B, C, H, D) hold gathered cached context for
    positions < start, ctx_mask (B, C) marks its valid slots and
    chunk_mask (B, T) the chunk's real tokens. Returns
    (logits (B, T, Vp) f32, k, v (L, B, T, H, D)) — the caller scatters
    k/v into the paged cache at the chunk's positions.
    """
    B, T = tokens.shape
    dt = cfg.dtype
    wte = constrain(params["wte"].astype(dt), None, None)
    # gather wpe by absolute position, NOT dynamic_slice: a slice clamps
    # its start when start+T overruns the table (bucket padding can push
    # past n_positions) and would silently shift every real token's
    # positional embedding. Only padded tail rows ever clip here, and
    # their K/V lands in the null page.
    pos = jnp.clip(start + jnp.arange(T), 0, cfg.block_size - 1)
    x = wte[tokens] + params["wpe"].astype(dt)[pos]
    x = constrain(x, ("data", "fsdp"), None, None)

    def body(carry, xs):
        p, kc, vc = xs
        return _chunk_block(carry, p, kc, vc, ctx_mask, chunk_mask, cfg)

    x, (k, v) = jax.lax.scan(body, x, (params["blocks"], k_ctx, v_ctx))
    x = _layer_norm(x, params["lnf"]["scale"], params["lnf"]["bias"])
    logits = x @ params["wte"].astype(dt).T
    logits = constrain(logits, ("data", "fsdp"), None, "tensor")
    return logits.astype(jnp.float32), k, v


def _decode_block(x, p, k_ctx, v_ctx, ctx_mask, cfg: GPT2Config,
                  attend=None):
    """Single-token block step. x (B, E); k_ctx/v_ctx (B, C, H, D) hold
    the sequence's cached context (padded; ctx_mask (B, C) marks valid
    slots). Returns (x, (k_new, v_new)) with k_new/v_new (B, H, D).
    ``attend(q, k, v) -> (B, H, D)`` swaps in the paged-attention
    kernel (see `_chunk_block`)."""
    B, E = x.shape
    dt = cfg.dtype
    H, D = cfg.n_head, cfg.head_dim
    h = _layer_norm(x, p["ln1"]["scale"], p["ln1"]["bias"])
    qkv = h @ p["attn_qkv"]["kernel"].astype(dt) + p["attn_qkv"]["bias"].astype(dt)
    qkv = constrain(qkv, ("data", "fsdp"), "tensor")
    q, k, v = (t.reshape(B, H, D) for t in jnp.split(qkv, 3, axis=-1))

    if attend is not None:
        att = attend(q, k, v).reshape(B, E)
    else:
        scale = 1.0 / (D**0.5)
        # context scores + the token's own (diagonal) score, f32 softmax
        s_ctx = jnp.einsum("bhd,bchd->bhc", q, k_ctx).astype(jnp.float32)
        s_own = jnp.sum(q * k, axis=-1, dtype=jnp.float32)
        s = jnp.concatenate([s_ctx, s_own[:, :, None]], axis=-1) * scale
        valid = jnp.concatenate(
            [ctx_mask, jnp.ones((B, 1), dtype=bool)], axis=-1)
        s = jnp.where(valid[:, None, :], s, -1e30)
        probs = jax.nn.softmax(s, axis=-1).astype(dt)
        att = jnp.einsum("bhc,bchd->bhd", probs[..., :-1], v_ctx) \
            + probs[..., -1:] * v
        att = att.reshape(B, E)
    att = att @ p["attn_proj"]["kernel"].astype(dt) + p["attn_proj"]["bias"].astype(dt)
    x = x + constrain(att, ("data", "fsdp"), None)

    h = _layer_norm(x, p["ln2"]["scale"], p["ln2"]["bias"])
    h = h @ p["mlp_fc"]["kernel"].astype(dt) + p["mlp_fc"]["bias"].astype(dt)
    h = constrain(h, ("data", "fsdp"), "tensor")
    h = jax.nn.gelu(h)
    h = h @ p["mlp_proj"]["kernel"].astype(dt) + p["mlp_proj"]["bias"].astype(dt)
    x = x + constrain(h, ("data", "fsdp"), None)
    return x, (k, v)


def gpt2_decode_kv(
    params: Params,
    tokens: jax.Array,
    positions: jax.Array,
    k_ctx: jax.Array,
    v_ctx: jax.Array,
    ctx_mask: jax.Array,
    cfg: GPT2Config,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step for a batch of sequences.

    tokens/positions (B,) i32; k_ctx/v_ctx (L, B, C, H, D) gathered
    cache context; ctx_mask (B, C). Returns (logits (B, Vp) f32,
    k_new, v_new (L, B, H, D)) — the caller scatters k_new/v_new into
    the cache at each sequence's current position.
    """
    dt = cfg.dtype
    x = params["wte"].astype(dt)[tokens] + params["wpe"].astype(dt)[positions]

    def body(carry, xs):
        p, kc, vc = xs
        return _decode_block(carry, p, kc, vc, ctx_mask, cfg)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["blocks"], k_ctx, v_ctx))
    x = _layer_norm(x, params["lnf"]["scale"], params["lnf"]["bias"])
    logits = x @ params["wte"].astype(dt).T
    return logits.astype(jnp.float32), k_new, v_new


# --------------------------------------------------------------------------
# Paged-attention inference steps: same block math (projections, MLP,
# residuals shared via the `attend` hook), but the attention core is the
# ops/paged_attention.py kernel indexing the page pool in place — no
# dense (L, B, C, H, D) context gather. k_pages/v_pages are the pool
# arrays (L, num_blocks, block_size, H, D); the scan walks layers and
# per-layer page arrays together.


def gpt2_decode_paged_kv(
    params: Params,
    tokens: jax.Array,
    positions: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    tables: jax.Array,
    cfg: GPT2Config,
    *,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step against the page pool. tokens/positions (B,);
    tables (B, max_blocks_per_seq). Returns (logits (B, Vp) f32,
    k_new, v_new (L, B, H, D)) — caller scatters, like gpt2_decode_kv."""
    from ray_tpu.ops.paged_attention import paged_attention

    dt = cfg.dtype
    x = params["wte"].astype(dt)[tokens] \
        + params["wpe"].astype(dt)[positions]

    def body(carry, xs):
        p, kp, vp = xs

        def attend(q, k, v):
            o = paged_attention(q[:, None], k[:, None], v[:, None],
                                kp, vp, tables, positions,
                                interpret=interpret)
            return o[:, 0]

        return _decode_block(carry, p, None, None, None, cfg,
                             attend=attend)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["blocks"], k_pages, v_pages))
    x = _layer_norm(x, params["lnf"]["scale"], params["lnf"]["bias"])
    logits = x @ params["wte"].astype(dt).T
    return logits.astype(jnp.float32), k_new, v_new


def gpt2_verify_paged_kv(
    params: Params,
    tokens: jax.Array,
    start: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    table: jax.Array,
    cfg: GPT2Config,
    *,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Speculative verify window against the page pool: tokens (1, W)
    at absolute positions start..start+W-1, table (max_blocks_per_seq,)
    covering cached positions < start. Causal within the window (no
    chunk mask — a window row only ever attends rows before it, and
    rows past the draft count are discarded by the caller). Returns
    (logits (1, W, Vp) f32, k, v (L, 1, W, H, D))."""
    from ray_tpu.ops.paged_attention import paged_attention

    B, T = tokens.shape
    dt = cfg.dtype
    pos = jnp.clip(start + jnp.arange(T), 0, cfg.block_size - 1)
    x = params["wte"].astype(dt)[tokens] + params["wpe"].astype(dt)[pos]
    tables = table[None]  # (1, maxB)
    ctx_len = jnp.reshape(jnp.asarray(start, jnp.int32), (1,))

    def body(carry, xs):
        p, kp, vp = xs

        def attend(q, k, v):
            return paged_attention(q, k, v, kp, vp, tables, ctx_len,
                                   interpret=interpret)

        return _chunk_block(carry, p, None, None, None, None, cfg,
                            attend=attend)

    x, (k, v) = jax.lax.scan(body, x, (params["blocks"], k_pages, v_pages))
    x = _layer_norm(x, params["lnf"]["scale"], params["lnf"]["bias"])
    logits = x @ params["wte"].astype(dt).T
    return logits.astype(jnp.float32), k, v


def count_params(params: Params) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
