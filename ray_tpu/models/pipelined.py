"""Pipelined transformer — real multi-stage model wiring on a hybrid
dcn x pipe x fsdp x tensor mesh with ring attention.

Reference parity: Megatron-style pipeline-parallel transformer training
(megatron/core/pipeline_parallel/schedules.py interleaved 1F1B +
context parallelism). TPU-native shape:

- transformer BLOCKS are stacked on a leading virtual-stage axis and
  sharded over `pipe`; the interleaved circular schedule
  (parallel/pipeline.py pipeline_apply_interleaved) runs them with an
  (S-1)/(R*M) bubble;
- attention inside every block is RING ATTENTION over the `fsdp` axis:
  the sequence dim is context-parallel across the fsdp group (the
  reference's CP-over-DP-group layout) and kv blocks rotate on ICI;
- embed/head and the loss live OUTSIDE the manual region; jax 0.9
  shard_map(axis_names={"pipe", "fsdp"}) leaves the remaining mesh axes
  (dcn, data, tensor) to GSPMD, so the batch stays sharded over
  (dcn, data) and the block weight matrices over `tensor` with XLA
  inserting the collectives.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.parallel.pipeline import pipeline_apply_interleaved
from ray_tpu.parallel.ring_attention import ring_attention


@dataclasses.dataclass(frozen=True)
class PipelinedConfig:
    vocab_size: int = 256
    n_virtual_stages: int = 4  # total blocks = virtual stages
    n_head: int = 4
    d_model: int = 64
    d_ff: int = 128
    block_size: int = 32
    num_microbatches: int = 4


def init_pipelined(key, cfg: PipelinedConfig) -> dict:
    """Stacked-block params: every block tensor has a leading
    (n_virtual_stages,) dim the caller shards over `pipe`."""
    V, D, F = cfg.n_virtual_stages, cfg.d_model, cfg.d_ff
    ks = jax.random.split(key, 8)

    def n(k, shape, scale):
        return jax.random.normal(k, shape, jnp.float32) * scale

    s = 0.02
    return {
        "embed": n(ks[0], (cfg.vocab_size, D), s),
        "pos": n(ks[1], (cfg.block_size, D), s),
        "blocks": {
            "qkv": n(ks[2], (V, D, 3 * D), s),
            "attn_out": n(ks[3], (V, D, D), s),
            "fc": n(ks[4], (V, D, F), s),
            "proj": n(ks[5], (V, F, D), s),
        },
        "ln_f": jnp.ones((D,)),
        "head": n(ks[6], (D, cfg.vocab_size), s),
    }


def _rms(x):
    return x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + 1e-6)


def _block(cfg: PipelinedConfig, params, h):
    """One transformer block; h is the LOCAL (mb, t, D) shard with the
    sequence dim context-parallel over `fsdp` (ring attention)."""
    mb, t, D = h.shape
    H = cfg.n_head
    qkv = _rms(h) @ params["qkv"]  # (mb, t, 3D)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(mb, t, H, D // H)
    k = k.reshape(mb, t, H, D // H)
    v = v.reshape(mb, t, H, D // H)
    att = ring_attention(q, k, v, "fsdp", causal=True)
    h = h + att.reshape(mb, t, D) @ params["attn_out"]
    h = h + jax.nn.gelu(_rms(h) @ params["fc"]) @ params["proj"]
    return h


def pipelined_loss(params, batch, cfg: PipelinedConfig, mesh,
                   num_repeats: int | None = None):
    """Full forward + next-token loss. Blocks run under
    shard_map(axis_names={pipe, fsdp}); everything else is GSPMD."""
    pipe = dict(mesh.shape).get("pipe", 1)
    R = num_repeats or max(1, cfg.n_virtual_stages // pipe)
    tokens, targets = batch["tokens"], batch["targets"]
    h = params["embed"][tokens] + params["pos"][None, :tokens.shape[1]]

    def body(blocks, hh):
        # hh: (B_local, t_local, D) — batch auto-sharded (dcn/data),
        # sequence manually sharded over fsdp. Microbatching splits the
        # LOCAL batch; blocks: this pipe rank's (R, ...) virtual stages.
        return pipeline_apply_interleaved(
            partial(_block, cfg), blocks, hh, "pipe",
            num_microbatches=cfg.num_microbatches, num_repeats=R)

    # round-robin virtual-stage placement: stage v -> (rank v % S, slot
    # v // S); reorder the stacked dim so shard_map's contiguous split
    # hands rank s exactly its slots in order
    S = pipe
    order = jnp.argsort(jnp.arange(cfg.n_virtual_stages) % S, stable=True)
    blocks = jax.tree.map(lambda p: p[order], params["blocks"])
    sm_specs = dict(in_specs=(P("pipe"), P(None, "fsdp", None)),
                    out_specs=P(None, "fsdp", None))
    if hasattr(jax, "shard_map"):
        sm = jax.shard_map(body, mesh=mesh, axis_names={"pipe", "fsdp"},
                           check_vma=False, **sm_specs)
    else:
        # jax<0.5: experimental entry point; manual-axes subset is
        # expressed as its complement (`auto`), check_vma as check_rep
        from jax.experimental.shard_map import shard_map as _shard_map

        sm = _shard_map(body, mesh=mesh, check_rep=False,
                        auto=frozenset(mesh.axis_names) -
                        {"pipe", "fsdp"}, **sm_specs)
    h = sm(blocks, h)
    logits = _rms(h * params["ln_f"]) @ params["head"]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)


def pipelined_shardings(params, cfg: PipelinedConfig, mesh):
    """NamedShardings: block stacks over pipe (+ tensor on the wide
    dim), embed/head over tensor, rest replicated."""
    def spec(path, leaf):
        name = path[-1] if path else ""
        if name in ("qkv", "fc"):
            return P("pipe", None, "tensor")
        if name in ("attn_out", "proj"):
            return P("pipe", "tensor", None)
        if name in ("embed", "head"):
            return P(None, "tensor")
        return P()

    def walk(tree, path):
        if isinstance(tree, dict):
            return {k: walk(v, path + [k]) for k, v in tree.items()}
        return NamedSharding(mesh, spec(path, tree))

    return walk(params, [])


def pipelined_train_step(cfg: PipelinedConfig, mesh, lr: float = 1e-2):
    """(params, batch) -> (params, loss) SGD step, jitted over the
    hybrid mesh."""

    @jax.jit
    def step(params, batch):
        loss, grads = jax.value_and_grad(pipelined_loss)(
            params, batch, cfg, mesh)
        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return params, loss

    return step
