"""Pipelined transformer — real multi-stage model wiring on a hybrid
dcn x pipe x fsdp x tensor mesh with ring attention.

Reference parity: Megatron-style pipeline-parallel transformer training
(megatron/core/pipeline_parallel/schedules.py interleaved 1F1B +
context parallelism). TPU-native shape:

- transformer BLOCKS are stacked on a leading virtual-stage axis and
  sharded over `pipe`; the interleaved circular schedule
  (parallel/pipeline.py pipeline_apply_interleaved) runs them with an
  (S-1)/(R*M) bubble;
- attention inside every block is RING ATTENTION over the `fsdp` axis:
  the sequence dim is context-parallel across the fsdp group (the
  reference's CP-over-DP-group layout) and kv blocks rotate on ICI;
- embed/head and the loss live OUTSIDE the manual region; jax 0.9
  shard_map(axis_names={"pipe", "fsdp"}) leaves the remaining mesh axes
  (dcn, data, tensor) to GSPMD, so the batch stays sharded over
  (dcn, data) and the block weight matrices over `tensor` with XLA
  inserting the collectives.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.parallel.pipeline import pipeline_apply_interleaved
from ray_tpu.parallel.ring_attention import ring_attention


@dataclasses.dataclass(frozen=True)
class PipelinedConfig:
    vocab_size: int = 256
    n_virtual_stages: int = 4  # total blocks = virtual stages
    n_head: int = 4
    d_model: int = 64
    d_ff: int = 128
    block_size: int = 32
    num_microbatches: int = 4


def init_pipelined(key, cfg: PipelinedConfig) -> dict:
    """Stacked-block params: every block tensor has a leading
    (n_virtual_stages,) dim the caller shards over `pipe`."""
    V, D, F = cfg.n_virtual_stages, cfg.d_model, cfg.d_ff
    ks = jax.random.split(key, 8)

    def n(k, shape, scale):
        return jax.random.normal(k, shape, jnp.float32) * scale

    s = 0.02
    return {
        "embed": n(ks[0], (cfg.vocab_size, D), s),
        "pos": n(ks[1], (cfg.block_size, D), s),
        "blocks": {
            "qkv": n(ks[2], (V, D, 3 * D), s),
            "attn_out": n(ks[3], (V, D, D), s),
            "fc": n(ks[4], (V, D, F), s),
            "proj": n(ks[5], (V, F, D), s),
        },
        "ln_f": jnp.ones((D,)),
        "head": n(ks[6], (D, cfg.vocab_size), s),
    }


def _rms(x):
    return x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + 1e-6)


def _block(cfg: PipelinedConfig, params, h):
    """One transformer block; h is the LOCAL (mb, t, D) shard with the
    sequence dim context-parallel over `fsdp` (ring attention)."""
    mb, t, D = h.shape
    H = cfg.n_head
    qkv = _rms(h) @ params["qkv"]  # (mb, t, 3D)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(mb, t, H, D // H)
    k = k.reshape(mb, t, H, D // H)
    v = v.reshape(mb, t, H, D // H)
    att = ring_attention(q, k, v, "fsdp", causal=True)
    h = h + att.reshape(mb, t, D) @ params["attn_out"]
    h = h + jax.nn.gelu(_rms(h) @ params["fc"]) @ params["proj"]
    return h


def pipelined_loss(params, batch, cfg: PipelinedConfig, mesh,
                   num_repeats: int | None = None):
    """Full forward + next-token loss. Blocks run under
    shard_map(axis_names={pipe, fsdp}); everything else is GSPMD."""
    pipe = dict(mesh.shape).get("pipe", 1)
    R = num_repeats or max(1, cfg.n_virtual_stages // pipe)
    tokens, targets = batch["tokens"], batch["targets"]
    h = params["embed"][tokens] + params["pos"][None, :tokens.shape[1]]

    def body(blocks, hh):
        # hh: (B_local, t_local, D) — batch auto-sharded (dcn/data),
        # sequence manually sharded over fsdp. Microbatching splits the
        # LOCAL batch; blocks: this pipe rank's (R, ...) virtual stages.
        return pipeline_apply_interleaved(
            partial(_block, cfg), blocks, hh, "pipe",
            num_microbatches=cfg.num_microbatches, num_repeats=R)

    # round-robin virtual-stage placement: stage v -> (rank v % S, slot
    # v // S); reorder the stacked dim so shard_map's contiguous split
    # hands rank s exactly its slots in order
    S = pipe
    order = jnp.argsort(jnp.arange(cfg.n_virtual_stages) % S, stable=True)
    blocks = jax.tree.map(lambda p: p[order], params["blocks"])
    sm_specs = dict(in_specs=(P("pipe"), P(None, "fsdp", None)),
                    out_specs=P(None, "fsdp", None))
    if hasattr(jax, "shard_map"):
        sm = jax.shard_map(body, mesh=mesh, axis_names={"pipe", "fsdp"},
                           check_vma=False, **sm_specs)
    else:
        # jax<0.5: experimental entry point; manual-axes subset is
        # expressed as its complement (`auto`), check_vma as check_rep
        from jax.experimental.shard_map import shard_map as _shard_map

        sm = _shard_map(body, mesh=mesh, check_rep=False,
                        auto=frozenset(mesh.axis_names) -
                        {"pipe", "fsdp"}, **sm_specs)
    h = sm(blocks, h)
    logits = _rms(h * params["ln_f"]) @ params["head"]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)


# ---------------------------------------------------------------------------
# MPMD stage split — the 1F1B worker-group strategy's model face
# ---------------------------------------------------------------------------


def split_pipeline_stages(params, cfg: PipelinedConfig,
                          num_stages: int) -> list[dict]:
    """Split a full pipelined-param tree into `num_stages` contiguous
    stage subtrees for the MPMD strategy (train/pipeline_strategy.py):
    stage s gets blocks[V*s//S : V*(s+1)//S]; stage 0 additionally owns
    embed/pos, the last stage ln_f/head. Union of stages == the full
    tree, so a single-program run of the same params is the parity
    reference."""
    V, S = cfg.n_virtual_stages, num_stages
    if not 1 <= S <= V:
        raise ValueError(f"need 1 <= stages <= {V} blocks, got {S}")
    stages = []
    for s in range(S):
        lo, hi = V * s // S, V * (s + 1) // S
        stage = {"blocks": jax.tree.map(lambda p: p[lo:hi],
                                        params["blocks"])}
        if s == 0:
            stage["embed"], stage["pos"] = params["embed"], params["pos"]
        if s == S - 1:
            stage["ln_f"], stage["head"] = params["ln_f"], params["head"]
        stages.append(stage)
    return stages


def merge_pipeline_stages(stages: list[dict]) -> dict:
    """Inverse of `split_pipeline_stages` (checkpointing / parity)."""
    blocks = jax.tree.map(
        lambda *leaves: jnp.concatenate(leaves, axis=0),
        *[st["blocks"] for st in stages])
    return {"embed": stages[0]["embed"], "pos": stages[0]["pos"],
            "blocks": blocks, "ln_f": stages[-1]["ln_f"],
            "head": stages[-1]["head"]}


def split_pipeline_stages_interleaved(params, cfg: PipelinedConfig,
                                      num_stages: int, num_repeats: int
                                      ) -> list[list[dict]]:
    """Round-robin virtual-stage split for the interleaved MPMD
    strategy: the model becomes V = S*R virtual chunks (contiguous
    block runs, split exactly like `split_pipeline_stages(.., V)`), and
    worker s owns chunks [s, s+S, .., s+(R-1)S] — result[s][r] is
    virtual stage r*S + s. Chunk 0 carries embed/pos (it lives on
    worker 0), chunk V-1 carries ln_f/head (worker S-1), so each chunk
    is directly usable with `stage_apply(.., stage_idx=v,
    num_stages=V, ..)`."""
    V = num_stages * num_repeats
    chunks = split_pipeline_stages(params, cfg, V)
    return [[chunks[r * num_stages + s] for r in range(num_repeats)]
            for s in range(num_stages)]


def merge_pipeline_stages_interleaved(stage_chunks: list[list[dict]]
                                      ) -> dict:
    """Inverse of `split_pipeline_stages_interleaved`: reassemble the
    full tree from per-worker chunk lists (checkpointing / parity)."""
    S, R = len(stage_chunks), len(stage_chunks[0])
    flat = [stage_chunks[v % S][v // S] for v in range(S * R)]
    return merge_pipeline_stages(flat)


def _local_mesh():
    """One-device mesh carrying the `fsdp` axis so `_block`'s ring
    attention resolves outside the hybrid-mesh program (size-1 ring ==
    plain causal attention, numerically the same blockwise softmax)."""
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:1]), ("fsdp",))


def stage_apply(cfg: PipelinedConfig, stage_params: dict, stage_idx: int,
                num_stages: int, payload, targets=None, mesh=None):
    """One pipeline stage's forward: tokens -> h for stage 0, h -> h in
    the middle, h -> scalar loss (or logits when `targets` is None) on
    the last stage. Runs the SAME `_block` math as `pipelined_loss`
    (under a size-1 fsdp shard_map), so chaining all stages reproduces
    the single-program loss bit-for-bit modulo float reassociation.
    Differentiable — the MPMD strategy takes jax.vjp of this per
    microbatch. A `mesh` carrying a `data` axis (the strategy's
    intra-stage ZeRO data-parallel group) splits the microbatch over it
    — block weights stay replicated (or ZeRO-resharded by the caller)
    and GSPMD inserts the loss-mean reduction."""
    from ray_tpu.parallel.ops import shard_map as _shard_map
    from jax.sharding import PartitionSpec as P

    first, last = stage_idx == 0, stage_idx == num_stages - 1
    if first:
        tokens = payload
        h = stage_params["embed"][tokens] \
            + stage_params["pos"][None, :tokens.shape[1]]
    else:
        h = payload
    mesh = mesh if mesh is not None else _local_mesh()
    bspec = P("data") if dict(mesh.shape).get("data", 1) > 1 else P()

    def body(blocks, hh):
        def one(carry, blk):
            return _block(cfg, blk, carry), None

        out, _ = jax.lax.scan(one, hh, blocks)
        return out

    h = _shard_map(body, mesh, in_specs=(P(), bspec), out_specs=bspec)(
        stage_params["blocks"], h)
    if not last:
        return h
    logits = _rms(h * stage_params["ln_f"]) @ stage_params["head"]
    if targets is None:
        return logits
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)


def pipelined_shardings(params, cfg: PipelinedConfig, mesh):
    """NamedShardings: block stacks over pipe (+ tensor on the wide
    dim), embed/head over tensor, rest replicated."""
    def spec(path, leaf):
        name = path[-1] if path else ""
        if name in ("qkv", "fc"):
            return P("pipe", None, "tensor")
        if name in ("attn_out", "proj"):
            return P("pipe", "tensor", None)
        if name in ("embed", "head"):
            return P(None, "tensor")
        return P()

    def walk(tree, path):
        if isinstance(tree, dict):
            return {k: walk(v, path + [k]) for k, v in tree.items()}
        return NamedSharding(mesh, spec(path, tree))

    return walk(params, [])


def pipelined_train_step(cfg: PipelinedConfig, mesh, lr: float = 1e-2):
    """(params, batch) -> (params, loss) SGD step, jitted over the
    hybrid mesh."""

    @jax.jit
    def step(params, batch):
        loss, grads = jax.value_and_grad(pipelined_loss)(
            params, batch, cfg, mesh)
        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return params, loss

    return step
