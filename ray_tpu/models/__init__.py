"""Model zoo for the TPU-native framework (pure-JAX, mesh-shardable):
GPT-2, Llama-family (RoPE/RMSNorm/SwiGLU/GQA), MoE layer."""

from ray_tpu.models.gpt2 import (
    GPT2Config,
    gpt2_forward,
    gpt2_partition_rules,
    init_gpt2,
)
from ray_tpu.models.llama import (
    LlamaConfig,
    init_llama,
    llama_forward,
    llama_loss,
    llama_partition_rules,
)

__all__ = ["GPT2Config", "LlamaConfig", "gpt2_forward",
           "gpt2_partition_rules", "init_gpt2", "init_llama",
           "llama_forward", "llama_loss", "llama_partition_rules"]
