"""Model zoo for the TPU-native framework (pure-JAX, mesh-shardable)."""

from ray_tpu.models.gpt2 import GPT2Config, gpt2_partition_rules, init_gpt2, gpt2_forward

__all__ = ["GPT2Config", "gpt2_partition_rules", "init_gpt2", "gpt2_forward"]
