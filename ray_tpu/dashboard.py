"""Dashboard-lite: HTTP view of cluster state.

Reference parity: the dashboard head's REST surface
(python/ray/dashboard/head.py + modules/{node,actor,job}) scoped to the
state endpoints and a minimal auto-refreshing HTML page — no React
frontend. Serves: / (HTML), /api/state, /api/nodes, /api/actors,
/api/pgs, /api/jobs, /api/objects, /api/memory (owner-side object
tables + per-node store usage, the `ray memory` role), /api/history
(ring buffer of cluster summaries, 1h at 5s), /metrics (this
process's Prometheus registry)."""

from __future__ import annotations

import json
import threading
import time
from collections import deque

# node-metrics history ring (reference role: the dashboard's metrics
# module keeps time series; here a bounded in-memory ring served at
# /api/history — 720 samples x 5s = 1h)
_HISTORY_MAXLEN = 720
_HISTORY_INTERVAL_S = 5.0
_history: deque = deque(maxlen=_HISTORY_MAXLEN)
_sampler_stop = None

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<meta http-equiv="refresh" content="5">
<style>
 body { font-family: monospace; margin: 2em; }
 table { border-collapse: collapse; margin-bottom: 2em; }
 td, th { border: 1px solid #999; padding: 4px 10px; text-align: left; }
 h2 { margin-bottom: .3em; }
</style></head>
<body>
<h1>ray_tpu cluster</h1>
<div id="content">loading…</div>
<script>
async function load() {
  const s = await (await fetch('/api/state')).json();
  const nodes = await (await fetch('/api/nodes')).json();
  const actors = await (await fetch('/api/actors')).json();
  const jobs = await (await fetch('/api/jobs')).json();
  let h = '<h2>Summary</h2><table>';
  for (const [k, v] of Object.entries(s))
    h += `<tr><th>${k}</th><td>${JSON.stringify(v)}</td></tr>`;
  h += '</table><h2>Nodes</h2><table><tr><th>id</th><th>address</th>' +
       '<th>alive</th><th>resources</th><th>available</th></tr>';
  for (const n of nodes)
    h += `<tr><td>${n.node_id.slice(0,12)}</td><td>${n.address}</td>` +
         `<td>${n.alive}</td><td>${JSON.stringify(n.resources)}</td>` +
         `<td>${JSON.stringify(n.available)}</td></tr>`;
  h += '</table><h2>Actors</h2><table><tr><th>id</th><th>name</th>' +
       '<th>state</th><th>node</th></tr>';
  for (const a of actors)
    h += `<tr><td>${a.actor_id.slice(0,12)}</td><td>${a.name||''}</td>` +
         `<td>${a.state}</td><td>${(a.node_id||'').slice(0,12)}</td></tr>`;
  h += '</table><h2>Jobs</h2><table><tr><th>id</th><th>status</th>' +
       '<th>entrypoint</th></tr>';
  for (const j of jobs)
    h += `<tr><td>${j.submission_id}</td><td>${j.status}</td>` +
         `<td>${j.entrypoint}</td></tr>`;
  h += '</table>';
  const train = await (await fetch('/api/train')).json();
  h += '<h2>Train runs</h2><table><tr><th>name</th><th>status</th>' +
       '<th>iteration</th><th>workers</th></tr>';
  for (const t of train)
    h += `<tr><td>${t.name}</td><td>${t.status}</td>` +
         `<td>${t.iteration}</td><td>${t.num_workers||''}</td></tr>`;
  h += '</table>';
  const serve = await (await fetch('/api/serve')).json();
  h += '<h2>Serve</h2><pre>' +
       JSON.stringify(serve, null, 1).slice(0, 4000) + '</pre>';
  const data = await (await fetch('/api/data')).json();
  h += '<h2>Data executions</h2><table><tr><th>id</th><th>status</th>' +
       '<th>submitted</th><th>yielded</th></tr>';
  for (const d of data)
    h += `<tr><td>${d.name}</td><td>${d.status}</td>` +
         `<td>${d.submitted}</td><td>${d.yielded}</td></tr>`;
  h += '</table>';
  document.getElementById('content').innerHTML = h;
}
load();
</script></body></html>"""

_server = None

# -------------------------------------------------- subsystem views
# Train/Data publish lightweight run records into the head KV under the
# "dashboard" namespace; /api/train and /api/data list them (reference:
# dashboard/modules/train + modules/data reading subsystem state).


_publish_q: "deque" = deque(maxlen=64)  # drop-oldest when the head lags
_publish_wake = threading.Event()
_publisher_started = False
_publisher_lock = threading.Lock()


def publish_view(kind: str, name: str, payload: dict,
                 address: str | None = None):
    """Best-effort: write one subsystem record into head KV. The RPC
    runs on a background publisher thread (short timeout, drop-oldest
    queue) so a slow or unreachable head can never stall the caller's
    hot loop (train result loop / data executor)."""
    payload = {**payload, "name": name, "updated_at": time.time()}
    _publish_q.append((kind, name, payload, address))
    global _publisher_started
    with _publisher_lock:
        if not _publisher_started:
            _publisher_started = True
            threading.Thread(target=_publish_loop, daemon=True,
                             name="dashboard-publish").start()
    _publish_wake.set()


def _publish_loop():
    from ray_tpu.core.gcs_client import GcsClient

    while True:
        _publish_wake.wait(timeout=5.0)
        _publish_wake.clear()
        while _publish_q:
            try:
                kind, name, payload, address = _publish_q.popleft()
            except IndexError:
                break
            try:
                GcsClient(address, timeout=2.0).internal_kv_put(
                    f"{kind}/{name}",
                    json.dumps(payload, default=str).encode(),
                    namespace="dashboard")
            except Exception:  # noqa: BLE001
                pass  # no cluster runtime / head gone: views are optional


def read_views(kind: str, address: str | None = None) -> list[dict]:
    try:
        from ray_tpu.core.gcs_client import GcsClient

        gcs = GcsClient(address)
        out = []
        for key in gcs.internal_kv_keys(f"{kind}/", namespace="dashboard"):
            raw = gcs.internal_kv_get(key, namespace="dashboard")
            if raw:
                try:
                    out.append(json.loads(raw))
                except ValueError:
                    pass
        out.sort(key=lambda r: r.get("updated_at", 0), reverse=True)
        return out
    except Exception:  # noqa: BLE001
        return []


def _serve_view(head_address) -> dict:
    try:
        from ray_tpu.util.state import serve_status

        return serve_status(head_address)
    except Exception as e:  # noqa: BLE001
        return {"error": repr(e), "apps": {}}


def _sample_loop(head_address, stop: threading.Event):
    from ray_tpu.util import state

    while not stop.wait(_HISTORY_INTERVAL_S):
        try:
            s = state.summarize(head_address)
            _history.append({"time": time.time(), **s})
        except Exception:  # noqa: BLE001
            pass  # head briefly unreachable; the gap itself is the signal


def start_dashboard(head_address: str | None = None, port: int = 8265) -> int:
    """Start the dashboard HTTP server; returns the bound port."""
    global _server, _sampler_stop
    import http.server

    from ray_tpu.util import metrics as metrics_mod
    from ray_tpu.util import state

    class Handler(http.server.BaseHTTPRequestHandler):
        def _send(self, body: bytes, ctype: str, code: int = 200):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            try:
                if self.path == "/" or self.path == "/index.html":
                    self._send(_PAGE.encode(), "text/html")
                elif self.path == "/api/state":
                    self._send(json.dumps(
                        state.summarize(head_address)).encode(),
                        "application/json")
                elif self.path == "/api/nodes":
                    self._send(json.dumps(
                        state.list_nodes(head_address)).encode(),
                        "application/json")
                elif self.path == "/api/actors":
                    self._send(json.dumps(
                        state.list_actors(head_address)).encode(),
                        "application/json")
                elif self.path == "/api/pgs":
                    self._send(json.dumps(
                        state.list_placement_groups(head_address),
                        default=str).encode(), "application/json")
                elif self.path == "/api/jobs":
                    self._send(json.dumps(_jobs(head_address)).encode(),
                               "application/json")
                elif self.path == "/api/objects":
                    self._send(json.dumps(
                        state.list_objects(head_address)).encode(),
                        "application/json")
                elif self.path == "/api/memory":
                    self._send(json.dumps(
                        state.memory_summary(head_address)).encode(),
                        "application/json")
                elif self.path == "/api/history":
                    self._send(json.dumps(list(_history)).encode(),
                               "application/json")
                elif self.path == "/api/train":
                    self._send(json.dumps(
                        read_views("train", head_address)).encode(),
                        "application/json")
                elif self.path == "/api/data":
                    self._send(json.dumps(
                        read_views("data", head_address)).encode(),
                        "application/json")
                elif self.path == "/api/serve":
                    self._send(json.dumps(
                        _serve_view(head_address), default=str).encode(),
                        "application/json")
                elif self.path.startswith("/api/node_stats"):
                    # /api/node_stats?node=<hex> — the per-node agent
                    # tier through the nodelet (dashboard/agent.py role)
                    from urllib.parse import parse_qs, urlparse

                    q = parse_qs(urlparse(self.path).query)
                    node = (q.get("node") or [""])[0]
                    self._send(json.dumps(
                        state.node_stats(node, head_address)).encode(),
                        "application/json")
                elif self.path == "/metrics":
                    self._send(metrics_mod.prometheus_text().encode(),
                               "text/plain; version=0.0.4")
                elif self.path.startswith("/api/logs"):
                    # /api/logs?node=<hex>[&file=<name>[&nbytes=N]]
                    # (reference: dashboard log streaming via the log
                    # monitor, _private/log_monitor.py:103)
                    from urllib.parse import parse_qs, urlparse

                    q = parse_qs(urlparse(self.path).query)
                    node = (q.get("node") or [""])[0]
                    fname = (q.get("file") or [None])[0]
                    if fname is None:
                        self._send(json.dumps(
                            state.list_logs(node, head_address)).encode(),
                            "application/json")
                    else:
                        nbytes = int((q.get("nbytes") or ["65536"])[0])
                        text, _ = state.tail_log(node, fname, nbytes,
                                                 address=head_address)
                        self._send(text.encode(), "text/plain")
                else:
                    self._send(b"not found", "text/plain", 404)
            except Exception as e:  # noqa: BLE001
                self._send(json.dumps({"error": repr(e)}).encode(),
                           "application/json", 500)

        def log_message(self, *a):
            pass

    _server = http.server.ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(target=_server.serve_forever, daemon=True,
                     name="dashboard-http").start()
    _sampler_stop = threading.Event()
    threading.Thread(target=_sample_loop, args=(head_address, _sampler_stop),
                     daemon=True, name="dashboard-sampler").start()
    return _server.server_address[1]


def _jobs(head_address: str | None) -> list[dict]:
    try:
        from ray_tpu.job_submission import JobSubmissionClient

        client = JobSubmissionClient(head_address)
        return [
            {"submission_id": j.submission_id, "status": j.status.value,
             "entrypoint": j.entrypoint}
            for j in client.list_jobs()
        ]
    except Exception:  # noqa: BLE001
        return []


def stop_dashboard():
    global _server, _sampler_stop
    if _sampler_stop is not None:
        _sampler_stop.set()
        _sampler_stop = None
    if _server is not None:
        _server.shutdown()
        _server = None
    _history.clear()
