"""Cluster CLI.

Reference parity: python/ray/scripts/scripts.py — `ray start --head`,
`ray start --address`, `ray stop`, `ray status`, `ray list`. Usage:

  python -m ray_tpu.scripts.cli start --head [--node-ip IP] \
      [--num-cpus N] [--num-tpus N] [--resources JSON] [--block]
  python -m ray_tpu.scripts.cli start --address HOST:PORT [...]
  python -m ray_tpu.scripts.cli status  --address HOST:PORT
  python -m ray_tpu.scripts.cli summary --address HOST:PORT [--json]
  python -m ray_tpu.scripts.cli explain TASK_ID --address HOST:PORT
  python -m ray_tpu.scripts.cli critpath --address HOST:PORT
      [--trace-id T] [--json]
  python -m ray_tpu.scripts.cli list {actors|nodes|pgs} --address ...
  python -m ray_tpu.scripts.cli timeline --address HOST:PORT -o out.json
  python -m ray_tpu.scripts.cli metrics  --address HOST:PORT
  python -m ray_tpu.scripts.cli alerts   --address HOST:PORT [--json]
  python -m ray_tpu.scripts.cli profile  --address HOST:PORT [-d SECS]
  python -m ray_tpu.scripts.cli logs     --address HOST:PORT [--follow]
      [--grep RE] [--level error] [--node N] [--task TID] [--trace-id T]
  python -m ray_tpu.scripts.cli debug-dump --address HOST:PORT [-o DIR]
  python -m ray_tpu.scripts.cli stop   [--session-dir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

_DEFAULT_DIR = "/tmp/ray_tpu"


def _pidfile(session_dir: str) -> str:
    return os.path.join(session_dir, "cli_pids.json")


def _record_pid(session_dir: str, role: str):
    os.makedirs(session_dir, exist_ok=True)
    path = _pidfile(session_dir)
    pids = []
    if os.path.exists(path):
        try:
            with open(path) as f:
                pids = json.load(f)
        except (OSError, ValueError):
            pids = []
    pids.append({"pid": os.getpid(), "role": role, "t": time.time()})
    with open(path, "w") as f:
        json.dump(pids, f)


def cmd_start(args):
    if args.node_ip:
        os.environ["RAY_TPU_NODE_IP"] = args.node_ip
    from ray_tpu.core.head import Head
    from ray_tpu.core.nodelet import Nodelet

    session_dir = args.session_dir or os.path.join(
        _DEFAULT_DIR, f"session_cli_{int(time.time())}")
    os.makedirs(os.path.join(session_dir, "logs"), exist_ok=True)
    res = json.loads(args.resources) if args.resources else {}
    res.setdefault("CPU", float(args.num_cpus if args.num_cpus is not None
                                else os.cpu_count() or 1))
    if args.num_tpus:
        res["TPU"] = float(args.num_tpus)

    head = None
    if args.head:
        head = Head(session_name=os.path.basename(session_dir)).start()
        head_address = head.address
        print(f"head started at {head_address}")
        print(f"connect with: ray_tpu.init(address={head_address!r})")
    else:
        if not args.address:
            print("error: start needs --head or --address", file=sys.stderr)
            return 2
        head_address = args.address
    nodelet = Nodelet(head_address, res,
                      labels=json.loads(args.labels or "{}"),
                      session_dir=session_dir).start()
    # this process (head+nodelet or nodelet) joins the structured log
    # plane too, so control-plane warnings are queryable via
    # `ray_tpu logs` like any worker's
    from ray_tpu.utils import logging as slog

    slog.install_process_logging(
        role="head" if args.head else "nodelet",
        log_dir=nodelet.log_dir,
        node_id=nodelet.node_id.hex()[:12], proc="nodelet")
    print(f"nodelet started at {nodelet.address} with {res}")
    if getattr(args, "node_info_file", None):
        # machine-readable handle for the cluster launcher / autoscaler
        # provider (reference: the node's metadata in the GCS node table)
        tmp = args.node_info_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"node_id_hex": nodelet.node_id.hex(),
                       "address": nodelet.address,
                       "head_address": head_address,
                       "pid": os.getpid()}, f)
        os.replace(tmp, args.node_info_file)
    if args.address_file:
        tmp = args.address_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(head_address)
        os.replace(tmp, args.address_file)
    _record_pid(session_dir, "head+nodelet" if args.head else "nodelet")
    if args.block or True:  # services are in-process threads: must block
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:  # graftlint: disable=except-hygiene
            pass  # ^C IS the stop signal: shutdown continues right below
        nodelet.stop()
        if head is not None:
            head.stop()
    return 0


def cmd_status(args):
    from ray_tpu.util import state

    s = state.summarize(address=args.address)
    print(f"nodes: {s['nodes_alive']} alive, {s['nodes_dead']} dead")
    print(f"actors: {s['actors_alive']} alive / {s['actors_total']} total")
    print("resources:")
    for r, q in sorted(s["resources_total"].items()):
        a = s["resources_available"].get(r, 0.0)
        print(f"  {r}: {a:g}/{q:g} available")
    return 0


def cmd_summary(args):
    """One-screen cluster overview: nodes, actors by state, ledger
    task counts by lifecycle state, object bytes + stranded, firing
    alerts (reference: `ray summary`)."""
    from ray_tpu.util import state

    s = state.cluster_summary(address=args.address)
    if args.json:
        print(json.dumps(s, indent=2, default=str))
        return 0
    c = s.get("cluster") or {}
    if c:
        print(f"nodes:  {c['nodes_alive']} alive, {c['nodes_dead']} dead")
        res = " ".join(
            f"{r}={c['resources_available'].get(r, 0.0):g}/{q:g}"
            for r, q in sorted(c["resources_total"].items()))
        print(f"resources (avail/total): {res}")
    ab = s.get("actors_by_state") or {}
    print("actors: " + (" ".join(f"{k}={v}" for k, v in sorted(ab.items()))
                        or "none"))
    t = s.get("tasks") or {}
    counts = t.get("counts") or {}
    print("tasks:  " + (" ".join(f"{k}={v}"
                                 for k, v in sorted(counts.items()))
                        or "none"))
    st = t.get("stats") or {}
    if st:
        print(f"ledger: {st.get('records', 0)}/{st.get('capacity', 0)} "
              f"records, {st.get('events_total', 0)} events, "
              f"{st.get('dropped_transitions_total', 0)} dropped, "
              f"{st.get('spilled_records_total', 0)} spilled")
    o = s.get("objects") or {}
    if o:
        print(f"objects: {o['objects_total']} "
              f"({o['objects_bytes'] / (1 << 20):.1f}MB), "
              f"stranded {o['stranded_count']} "
              f"({o['stranded_bytes'] / (1 << 20):.1f}MB)")
    al = s.get("alerts")
    if al:
        print(f"alerts: {len(al)} active")
        for a in al:
            print(f"  {a['rule']:<24} {a['severity']:<9} {a['state']}")
    elif al is not None:
        print("alerts: none")
    for name, err in sorted((s.get("errors") or {}).items()):
        print(f"  UNAVAILABLE {name}: {err}", file=sys.stderr)
    return 0


def cmd_explain(args):
    """Why is this task pending / why was it slow: the ledger
    transition waterfall plus the scheduler's placement verdict and
    each node's live feasibility view."""
    from ray_tpu.util import state

    r = state.explain_task(args.task_id, address=args.address)
    if args.json:
        print(json.dumps(r, indent=2, default=str))
        return 0
    rec = r.get("record")
    if rec is None:
        print(f"task {args.task_id!r}: not in the ledger "
              "(never submitted here, or evicted beyond the spill)")
    else:
        print(f"task {rec['task_id'][:16]} {rec.get('name', '')!r} "
              f"state={rec['state']}")
        for tr in rec.get("transitions", ()):
            t = time.strftime("%H:%M:%S", time.localtime(tr["t"]))
            where = tr.get("node_id", "")[:12]
            detail = tr.get("detail", "")
            print(f"  {t} {tr['state']:<10} {where:<12} {detail}")
        wf = r.get("waterfall") or {}
        for ph in wf.get("phases", ()):
            print(f"  {ph['phase']:<24} {ph['ms']:>10.3f}ms")
        if wf.get("total_ms") is not None:
            print(f"  total {wf['total_ms']:.3f}ms  "
                  f"queue {wf.get('queue_ms', 0.0):.3f}ms  "
                  f"exec {wf.get('exec_ms', 0.0):.3f}ms")
    verdict = r.get("verdict") or (rec or {}).get("verdict")
    if verdict:
        print(f"verdict: {verdict.get('decision', '?')}"
              + (f" — {verdict['constraint']}"
                 if verdict.get("constraint") else ""))
        for n in verdict.get("nodes_considered", ()):
            print(f"  node {n['node_id']:<12} "
                  f"{'OK ' if n.get('ok') else 'NO '} {n.get('reason', '')}")
    for nid, info in sorted((r.get("nodes") or {}).items()):
        if not info.get("queued"):
            continue
        print(f"queued on {nid}: position {info.get('queue_position')} "
              f"of {info.get('queue_len')}, waited "
              f"{info.get('waited_s', 0.0)}s")
        if info.get("constraint"):
            print(f"  why pending: {info['constraint']}")
        for n in info.get("nodes_considered", ()):
            print(f"  node {n['node_id']:<12} "
                  f"{'OK ' if n.get('ok') else 'NO '} {n.get('reason', '')}")
    for nid, err in sorted((r.get("errors") or {}).items()):
        print(f"  MISSING node {nid}: {err}", file=sys.stderr)
    return 0


def cmd_critpath(args):
    """Critical path: with --trace-id, the blocking chain of one
    execution; without, the cross-execution aggregate (which work
    blocks, how often, for how much total time)."""
    from ray_tpu.util import state

    r = state.critical_path(trace_id=args.trace_id, address=args.address)
    if args.json:
        print(json.dumps(r, indent=2, default=str))
        return 0
    if args.trace_id:
        print(f"trace {r['trace_id'][:16]}: e2e {r['e2e_ms']:.3f}ms, "
              f"path {r['path_ms']:.3f}ms "
              f"({r['coverage'] * 100:.1f}% coverage), "
              f"slowest: {r['slowest']}")
        for c in r["chain"]:
            print(f"  {c['name']:<32} {c['dur_ms']:>10.3f}ms "
                  f"slack={c['slack_ms']:>8.3f}ms "
                  f"node={c.get('node', '')[:12]}")
    else:
        print(f"{r['traces']} traces analyzed")
        print(f"{'NAME':<32} {'COUNT':>6} {'TOTAL':>12} {'MEAN':>10} "
              f"{'MAX':>10}  SHARE")
        for e in r["entries"][:args.limit]:
            print(f"{e['name']:<32} {e['count']:>6} "
                  f"{e['total_ms']:>10.3f}ms {e['mean_ms']:>8.3f}ms "
                  f"{e['max_ms']:>8.3f}ms  {e['share'] * 100:5.1f}%")
    return 0


def cmd_list(args):
    from ray_tpu.util import state

    if args.kind == "actors":
        rows = state.list_actors(address=args.address)
    elif args.kind == "tasks":
        rows = state.list_tasks(address=args.address)
    elif args.kind == "nodes":
        rows = state.list_nodes(address=args.address)
    elif args.kind == "pgs":
        rows = state.list_placement_groups(address=args.address)
    elif args.kind == "objects":
        rows = state.list_objects(address=args.address)
    else:
        print(f"unknown kind {args.kind}", file=sys.stderr)
        return 2
    print(json.dumps(rows, indent=2, default=str))
    return 0


def cmd_memory(args):
    """Per-node store usage + per-owner object footprint (reference:
    `ray memory`)."""
    from ray_tpu.util import state

    print(state.memory_report(address=args.address))
    return 0


def cmd_timeline(args):
    """Dump the merged cluster chrome trace (reference: `ray timeline`).
    Open the file at chrome://tracing or ui.perfetto.dev."""
    from ray_tpu.util import state

    path = state.cluster_timeline(address=args.address,
                                  filename=args.output)
    print(f"wrote merged timeline to {path}")
    return 0


def cmd_metrics(args):
    """Print the cluster-wide Prometheus page (node/proc tags injected;
    the same text the head's /metrics HTTP endpoint serves)."""
    from ray_tpu.util import state

    sys.stdout.write(state.cluster_metrics(address=args.address))
    return 0


def cmd_alerts(args):
    """Watchtower alerts: active pending/firing alerts plus the recent
    transition history (the same facts `util.state.alerts()` returns
    and `watchtower_alerts_firing{severity}` gauges on the metrics
    page)."""
    from ray_tpu.util import state

    data = state.alerts(address=args.address)
    if args.json:
        print(json.dumps(data, indent=2, default=str))
        return 0
    active = sorted(data.get("alerts", ()),
                    key=lambda a: (a["state"], a["rule"]))
    if not active:
        print(f"no active alerts ({len(data.get('rules', ()))} rules "
              "watching)")
    else:
        print(f"{'RULE':<24} {'SEV':<9} {'STATE':<8} {'VALUE':>12} "
              f"{'THRESHOLD':>12}  SINCE")
        for a in active:
            since = time.strftime("%H:%M:%S",
                                  time.localtime(a["since"]))
            print(f"{a['rule']:<24} {a['severity']:<9} "
                  f"{a['state']:<8} {a['value']:>12.4g} "
                  f"{a['threshold']:>12.4g}  {since}")
    history = data.get("history", ())
    if history:
        print(f"--- last {min(len(history), args.limit)} transitions ---")
        for ev in list(history)[-args.limit:]:
            t = time.strftime("%H:%M:%S", time.localtime(ev["t"]))
            value = (f" value={ev['value']:.4g}"
                     if ev.get("value") is not None else "")
            print(f"  {t} {ev['rule']:<24} "
                  f"{ev['from'] or '-':<9}-> {ev['to']:<9}{value}")
    return 0


def cmd_profile(args):
    """Cluster-wide sampling profile: arm a capture window in every
    process (head, nodelets, workers, this CLI excluded) and write
    merged node/proc-tagged collapsed stacks — feed the .collapsed file
    to flamegraph.pl / speedscope, or --chrome for a chrome://tracing
    flame view."""
    from ray_tpu.util import profiler, state

    r = state.profile(duration_s=args.duration, hz=args.hz,
                      address=args.address, include_driver=False)
    profiler.write_collapsed(args.output, r["stacks"])
    print(f"wrote {len(r['stacks'])} unique stacks to {args.output} "
          f"({r['samples']} samples @ {r['hz']:g}Hz across "
          f"{r['procs']} procs, {r['dropped']} dropped)")
    for nid, err in sorted(r.get("errors", {}).items()):
        print(f"  MISSING node {nid}: {err}", file=sys.stderr)
    if args.chrome:
        profiler.collapsed_to_chrome(r["stacks"], r["hz"],
                                     filename=args.chrome)
        print(f"wrote chrome flame view to {args.chrome}")
    return 0


def cmd_debug_dump(args):
    """Flight recorder: one post-mortem directory — state listings,
    memory report, serve/llm status, merged timeline, cluster metrics,
    per-node log tails. Deadline-bounded and best-effort, so it works
    against a degraded cluster too."""
    from ray_tpu.util import state

    out = state.debug_dump(out_dir=args.output, address=args.address,
                           deadline_s=args.deadline)
    with open(os.path.join(out, "summary.json")) as f:
        summary = json.load(f)
    ok, bad = summary.get("artifacts", {}), summary.get("errors", {})
    print(f"wrote debug dump to {out} "
          f"({len(ok)} artifacts, {len(bad)} failures, "
          f"{summary.get('elapsed_s', 0.0)}s)")
    for name, err in bad.items():
        print(f"  FAILED {name}: {err}", file=sys.stderr)
    return 0


def cmd_logs(args):
    """Cluster logs (reference: `ray logs` over the log monitor,
    _private/log_monitor.py:103 — here structured-first). Default mode
    queries the STRUCTURED log plane cluster-wide with
    grep/level/node/task/trace filters and supports `--follow`
    (incremental, offset-cursored). Legacy raw-file mode remains:
    `ray_tpu logs NODE [FILE] --address ...` lists/tails one node's
    raw log files byte-for-byte."""
    from ray_tpu.util import state
    from ray_tpu.utils.logging import format_record

    if args.node_or_file:
        # legacy raw-file mode
        if args.file is None:
            print(json.dumps(
                state.list_logs(args.node_or_file, address=args.address),
                indent=2))
            return 0
        text, _ = state.tail_log(args.node_or_file, args.file,
                                 nbytes=args.nbytes,
                                 address=args.address)
        sys.stdout.write(text)
        return 0

    def query(offsets=None, limit=None, window_s=None):
        return state.cluster_logs(
            address=args.address, level=args.level, grep=args.grep,
            node=args.node, task=args.task, trace_id=args.trace_id,
            proc=args.proc, limit=limit or args.tail,
            window_s=window_s, offsets=offsets,
            timeout=args.rpc_timeout)

    def show(reply, following=False):
        for rec in reply["records"]:
            print(json.dumps(rec, default=str) if args.json
                  else format_record(rec))
        if reply.get("truncated"):
            # never a silent gap: the reply cap dropped older records
            hint = ("burst exceeded the per-poll cap, older records "
                    "in the gap were skipped — narrow with "
                    "--grep/--level" if following else
                    "more matching records than the reply cap — "
                    "narrow with --grep/--level/--window or raise "
                    "--tail")
            print(f"  ... truncated: {hint}", file=sys.stderr)

    follow_since = time.monotonic()
    try:
        r = query(window_s=args.window)
    except ValueError as e:  # e.g. an invalid --grep regex
        print(f"logs: {e}", file=sys.stderr)
        return 2
    show(r)
    for nid, err in sorted(r.get("errors", {}).items()):
        print(f"  MISSING node {nid}: {err}", file=sys.stderr)
    if not args.follow:
        return 0
    # follow: pass each reply's offsets back so only NEW records ship.
    # A dead head ends the follow CLEANLY (note + exit 0): tailing a
    # cluster through its shutdown is the normal way this loop ends.
    offsets = dict(r.get("offsets") or {})
    drain = False
    misses = 0
    last_missing = set(r.get("errors") or {})
    try:
        while True:
            if not drain:
                time.sleep(args.poll)
            try:
                # per-poll limit pinned at the reply cap (a follow
                # wants everything new, not the one-shot's --tail
                # view) and time-bounded to the follow itself: a node
                # recovering mid-follow has no cursor yet, and its
                # fresh tail scan must not re-dump pre-follow history
                # into the stream
                r = query(offsets=offsets, limit=5000,
                          window_s=time.monotonic() - follow_since)
                misses = 0
            except Exception as e:  # noqa: BLE001
                # a busy head can miss one poll budget mid-incident —
                # exactly when someone is tailing; only consecutive
                # misses mean the head is actually gone
                misses += 1
                if misses < 3:
                    drain = False
                    continue
                print(f"log follow ended: head unreachable ({e})",
                      file=sys.stderr)
                return 0
            # merge PER FILE: a node that errored this round (absent
            # from the reply) keeps its cursors, and a file a nodelet
            # skipped on a transient read error keeps its cursor too —
            # replacing wholesale would rescan tails and re-print
            # already-shown records next poll
            for nid, cur in (r.get("offsets") or {}).items():
                merged = dict(offsets.get(nid) or {})
                merged.update(cur or {})
                offsets[nid] = merged
            show(r, following=True)
            # per-node errors surface on TRANSITION (noting a dead
            # node once beats repeating it every poll — and a quiet
            # tail must never mean "that node had nothing to say")
            missing = set(r.get("errors") or {})
            for nid in sorted(missing - last_missing):
                print(f"  MISSING node {nid}: {r['errors'][nid]}",
                      file=sys.stderr)
            for nid in sorted(last_missing - missing):
                print(f"  node {nid} answering again", file=sys.stderr)
            last_missing = missing
            # a truncated poll means a burst is in flight: poll again
            # immediately to drain instead of sleeping into more loss
            drain = bool(r.get("truncated"))
    except KeyboardInterrupt:  # graftlint: disable=except-hygiene
        return 0  # ^C IS how an operator ends a follow


def cmd_stop(args):
    session_dir = args.session_dir
    roots = ([session_dir] if session_dir else
             [os.path.join(_DEFAULT_DIR, d)
              for d in os.listdir(_DEFAULT_DIR)] if
             os.path.isdir(_DEFAULT_DIR) else [])
    n = 0
    for root in roots:
        path = _pidfile(root)
        if not os.path.exists(path):
            continue
        try:
            with open(path) as f:
                pids = json.load(f)
        except (OSError, ValueError):
            continue
        for entry in pids:
            try:
                os.kill(entry["pid"], signal.SIGTERM)
                n += 1
            except ProcessLookupError:
                pass
        os.unlink(path)
    print(f"stopped {n} process(es)")
    return 0


def cmd_up(args):
    from ray_tpu import launcher

    cfg = launcher.load_cluster_config(args.config_file)
    state = launcher.up(cfg, state_dir=args.state_dir)
    print(f"cluster {cfg['cluster_name']!r} up: "
          f"head at {state['head']['address']}, "
          f"{len(state['workers'])} workers")
    print(f"connect with: ray_tpu.init(address="
          f"{state['head']['address']!r})")
    return 0


def cmd_down(args):
    from ray_tpu import launcher

    state = launcher.down(args.cluster_name, state_dir=args.state_dir)
    n = len(state.get("workers", [])) + (1 if state.get("head") else 0)
    print(f"cluster {args.cluster_name!r} down ({n} nodes terminated)")
    return 0


def cmd_exec(args):
    from ray_tpu import launcher

    cmd = " ".join(args.command)
    if cmd.startswith("-- "):
        cmd = cmd[3:]
    return launcher.exec_on_cluster(args.cluster_name, cmd,
                                    state_dir=args.state_dir)


def cmd_attach(args):
    from ray_tpu import launcher

    return launcher.attach(args.cluster_name, state_dir=args.state_dir)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="ray_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address")
    p.add_argument("--node-ip")
    p.add_argument("--num-cpus", type=float)
    p.add_argument("--num-tpus", type=float)
    p.add_argument("--resources")
    p.add_argument("--labels")
    p.add_argument("--session-dir")
    p.add_argument("--address-file")
    p.add_argument("--node-info-file")
    p.add_argument("--block", action="store_true")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("status")
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("summary", help="one-screen cluster overview "
                                       "(nodes, actors, ledger task "
                                       "states, objects, alerts)")
    p.add_argument("--address", required=True)
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser("explain", help="why is this task pending / "
                                       "why was it slow (ledger "
                                       "waterfall + placement verdict)")
    p.add_argument("task_id", help="task id hex (prefix ok)")
    p.add_argument("--address", required=True)
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_explain)

    p = sub.add_parser("critpath", help="critical-path analysis over "
                                        "the merged span timeline")
    p.add_argument("--address", required=True)
    p.add_argument("--trace-id", dest="trace_id", default=None,
                   help="one execution's blocking chain (default: "
                        "aggregate across traces)")
    p.add_argument("--limit", type=int, default=20,
                   help="aggregate rows to show")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_critpath)

    p = sub.add_parser("list")
    p.add_argument("kind",
                   choices=["actors", "nodes", "objects", "pgs", "tasks"])
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("memory")
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser("timeline", help="dump the merged cluster "
                                        "chrome trace")
    p.add_argument("--address", required=True)
    p.add_argument("-o", "--output", default="timeline.json")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("metrics", help="print the cluster-wide "
                                       "Prometheus metrics page")
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser("alerts", help="print watchtower alerts "
                                      "(active + recent transitions)")
    p.add_argument("--address", required=True)
    p.add_argument("--json", action="store_true")
    p.add_argument("--limit", type=int, default=20,
                   help="transition-history lines to show")
    p.set_defaults(fn=cmd_alerts)

    p = sub.add_parser("profile",
                       help="cluster-wide sampling profile -> "
                            "flamegraph-compatible .collapsed stacks")
    p.add_argument("--address", required=True)
    p.add_argument("-d", "--duration", type=float, default=5.0,
                   help="capture window in seconds (default 5)")
    p.add_argument("--hz", type=float, default=None,
                   help="sampling rate (default 25)")
    p.add_argument("-o", "--output", default="profile.collapsed")
    p.add_argument("--chrome", default=None,
                   help="also write a chrome-trace flame view here")
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser("debug-dump",
                       help="write a one-call post-mortem directory "
                            "(state listings, memory, serve/llm "
                            "status, timeline, metrics, log tails)")
    p.add_argument("--address", required=True)
    p.add_argument("-o", "--output", default=None,
                   help="output directory (default: timestamped)")
    p.add_argument("--deadline", type=float, default=60.0,
                   help="total wall-time budget in seconds")
    p.set_defaults(fn=cmd_debug_dump)

    p = sub.add_parser("logs",
                       help="search/follow structured cluster logs; "
                            "NODE [FILE] = legacy raw-file mode")
    p.add_argument("node_or_file", nargs="?",
                   help="node id hex prefix (raw-file mode; omit for "
                        "the structured query)")
    p.add_argument("file", nargs="?", help="raw log file name "
                                           "(omit to list)")
    p.add_argument("--address", required=True)
    p.add_argument("--nbytes", type=int, default=64 * 1024)
    p.add_argument("--grep", help="regex over msg/logger")
    p.add_argument("--level",
                   choices=["debug", "info", "warning", "error",
                            "critical"],
                   help="minimum level (a typo must not silently "
                        "widen the filter to info-and-up)")
    p.add_argument("--node", help="node id hex prefix filter")
    p.add_argument("--task", help="task id (hex) filter")
    p.add_argument("--trace-id", dest="trace_id",
                   help="trace id filter (correlates with the merged "
                        "timeline)")
    p.add_argument("--proc", help="worker id (hex12) filter")
    p.add_argument("--tail", type=int, default=100,
                   help="records to show (most recent; default 100)")
    p.add_argument("--window", type=float, default=None,
                   help="trailing window in seconds")
    p.add_argument("-f", "--follow", action="store_true",
                   help="keep streaming new records (exits cleanly "
                        "when the head goes away)")
    p.add_argument("--poll", type=float, default=1.0,
                   help="follow poll interval in seconds")
    p.add_argument("--rpc-timeout", type=float, default=5.0,
                   help="per-query RPC budget")
    p.add_argument("--json", action="store_true",
                   help="raw JSONL records instead of formatted lines")
    p.set_defaults(fn=cmd_logs)

    p = sub.add_parser("stop")
    p.add_argument("--session-dir")
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("submit")
    p.add_argument("--address", required=True)
    p.add_argument("--submission-id")
    p.add_argument("--no-wait", action="store_true")
    p.add_argument("entrypoint", nargs=argparse.REMAINDER,
                   help="-- command to run")
    p.set_defaults(fn=cmd_submit)

    p = sub.add_parser("job")
    p.add_argument("action", choices=["status", "logs", "stop", "list"])
    p.add_argument("--address", required=True)
    p.add_argument("--id")
    p.set_defaults(fn=cmd_job)

    p = sub.add_parser("up", help="boot a cluster from a YAML config")
    p.add_argument("config_file")
    p.add_argument("--state-dir")
    p.set_defaults(fn=cmd_up)

    p = sub.add_parser("down", help="terminate a launched cluster")
    p.add_argument("cluster_name")
    p.add_argument("--state-dir")
    p.set_defaults(fn=cmd_down)

    p = sub.add_parser("exec", help="run a command with the cluster "
                                    "address exported")
    p.add_argument("cluster_name")
    p.add_argument("command", nargs=argparse.REMAINDER)
    p.add_argument("--state-dir")
    p.set_defaults(fn=cmd_exec)

    p = sub.add_parser("attach", help="interactive shell against the "
                                      "cluster")
    p.add_argument("cluster_name")
    p.add_argument("--state-dir")
    p.set_defaults(fn=cmd_attach)

    args = ap.parse_args(argv)
    return args.fn(args)


def cmd_submit(args):
    import ray_tpu
    from ray_tpu.job_submission import JobSubmissionClient

    ray_tpu.init(address=args.address)
    client = JobSubmissionClient(args.address)
    entry = args.entrypoint
    if entry and entry[0] == "--":
        entry = entry[1:]
    if not entry:
        print("error: no entrypoint given (use: submit --address A -- cmd)",
              file=sys.stderr)
        return 2
    job_id = client.submit_job(entrypoint=" ".join(entry),
                               submission_id=args.submission_id)
    print(f"submitted {job_id}")
    if args.no_wait:
        return 0
    status = client.wait_until_finished(job_id, timeout=3600)
    print(f"job {job_id}: {status.value}")
    print(client.get_job_logs(job_id), end="")
    return 0 if status.value == "SUCCEEDED" else 1


def cmd_job(args):
    import ray_tpu
    from ray_tpu.job_submission import JobSubmissionClient

    ray_tpu.init(address=args.address)
    client = JobSubmissionClient(args.address)
    if args.action == "list":
        for j in client.list_jobs():
            print(f"{j.submission_id}\t{j.status.value}\t{j.entrypoint}")
        return 0
    if not args.id:
        print("error: --id required", file=sys.stderr)
        return 2
    if args.action == "status":
        info = client.get_job_info(args.id)
        print(f"{info.status.value} {info.message}")
    elif args.action == "logs":
        print(client.get_job_logs(args.id), end="")
    elif args.action == "stop":
        print("stopped" if client.stop_job(args.id) else "not found")
    return 0


if __name__ == "__main__":
    sys.exit(main())
