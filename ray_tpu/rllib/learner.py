"""PPO Learner — jitted SPMD update.

Reference parity: Learner (rllib/core/learner/learner.py:109 —
compute_losses/compute_gradients/apply_gradients/update_from_batch) with
the torch DDP wrap (torch_learner.py:483,500) replaced by ONE jitted
update over a learner mesh: batch sharded on the data axis, params
replicated (or fsdp-sharded for big modules), GSPMD inserting the
gradient psum that DDP does by hand. GAE is computed host-side before
the jit (the reference puts it in the learner connector)."""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

# Importing parallel.mesh forces jax_threefry_partitionable BEFORE any
# learner inits params. Without this, a learner constructed before the
# first build_mesh() call inits under legacy threefry and one
# constructed after inits under partitionable threefry — different
# random bits, so sharded-vs-single parity (the DDP guarantee
# test_ppo_multi_learner_mesh_parity asserts) breaks at init, not in
# the update. Same invariant family as graftlint GL003.
import ray_tpu.parallel.mesh  # noqa: F401


@dataclasses.dataclass
class PPOLearnerConfig:
    lr: float = 3e-4
    clip_param: float = 0.2
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.0
    vf_clip_param: float = 10.0
    grad_clip: float = 0.5
    num_sgd_iter: int = 6
    minibatch_size: int = 128
    hidden: tuple = (64, 64)


def compute_gae(rewards, values, dones, last_values, gamma: float,
                lam: float):
    """(T, N) arrays -> (advantages, value_targets), host-side numpy
    (reference: GAE in the learner connector,
    rllib/connectors/learner/general_advantage_estimation.py)."""
    T, N = rewards.shape
    adv = np.zeros((T, N), np.float32)
    last_gae = np.zeros(N, np.float32)
    next_value = last_values
    for t in range(T - 1, -1, -1):
        nonterminal = 1.0 - dones[t].astype(np.float32)
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last_gae = delta + gamma * lam * nonterminal * last_gae
        adv[t] = last_gae
        next_value = values[t]
    targets = adv + values
    return adv, targets


class PPOLearner:
    """Owns params + optimizer; `update` runs epochs of jitted
    minibatch SGD. Pass a mesh to shard the batch over its 'data' axis
    (single-chip and CPU run with a trivial mesh)."""

    def __init__(self, obs_dim, n_actions: int,
                 config: PPOLearnerConfig | None = None, mesh=None,
                 seed: int = 0, model_config: dict | None = None,
                 module=None):
        self.config = config or PPOLearnerConfig()
        self.mesh = mesh
        self.tx = optax.chain(
            optax.clip_by_global_norm(self.config.grad_clip),
            optax.adam(self.config.lr),
        )
        # obs_dim: int (vector, legacy towers) or a 3-tuple image shape
        # (catalog conv actor-critic — core/models/catalog.py:33);
        # the RLModule owns the net (reference: Learner builds its module
        # from the spec, core/learner/learner.py) — runner and learner
        # construct identical modules so weight sync is a pytree copy
        mc = dict(model_config or {})
        mc.setdefault("hidden", self.config.hidden)
        if module is None:
            from ray_tpu.rllib.rl_module import DefaultActorCriticModule

            module = DefaultActorCriticModule(obs_dim, n_actions, mc)
        self.module = module
        self.params = self.module.init(jax.random.PRNGKey(seed))
        self.opt_state = self.tx.init(self.params)
        cfg = self.config
        fwd = self.module.forward_train

        def loss_fn(params, batch):
            out = fwd(params, batch)
            logits, value = out["action_dist_inputs"], out["vf_preds"]
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=1)[:, 0]
            ratio = jnp.exp(logp - batch["logp_old"])
            adv = batch["advantages"]
            surr = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - cfg.clip_param, 1 + cfg.clip_param) * adv)
            policy_loss = -jnp.mean(surr)
            vf_err = jnp.clip((value - batch["value_targets"]) ** 2,
                              0.0, cfg.vf_clip_param)
            vf_loss = jnp.mean(vf_err)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
            total = policy_loss + cfg.vf_loss_coeff * vf_loss \
                - cfg.entropy_coeff * entropy
            return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                           "entropy": entropy,
                           "mean_kl": jnp.mean(batch["logp_old"] - logp)}

        def sgd_step(params, opt_state, batch):
            (total, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["total_loss"] = total
            return params, opt_state, aux

        self._sgd_step = jax.jit(sgd_step, donate_argnums=(0, 1))

    # -- public ----------------------------------------------------------

    def update(self, train_batch: dict[str, np.ndarray]) -> dict:
        """Epochs of shuffled minibatch SGD (reference:
        Learner.update_from_batch minibatch loop, learner.py:967)."""
        cfg = self.config
        n = train_batch["obs"].shape[0]
        adv = train_batch["advantages"]
        train_batch = dict(train_batch)
        train_batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
        mb = min(cfg.minibatch_size, n)
        n_mb = max(1, n // mb)
        rng = np.random.RandomState(0)
        metrics: dict[str, Any] = {}
        shard = self._batch_sharding()
        for _ in range(cfg.num_sgd_iter):
            perm = rng.permutation(n)
            for i in range(n_mb):
                idx = perm[i * mb:(i + 1) * mb]
                batch = {k: v[idx] for k, v in train_batch.items()}
                if shard is not None:
                    batch = jax.device_put(batch, shard)
                self.params, self.opt_state, metrics = self._sgd_step(
                    self.params, self.opt_state, batch)
        return {k: float(np.asarray(v)) for k, v in metrics.items()}

    def _batch_sharding(self):
        if self.mesh is None:
            return None
        axes = [a for a, s in self.mesh.shape.items() if s > 1]
        if not axes:
            return None
        return NamedSharding(self.mesh, P(tuple(axes)))

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights)
