"""DreamerV3 — model-based RL: world model + actor-critic in imagination.

Reference parity: rllib/algorithms/dreamerv3/dreamerv3.py:1 (config:
model_size presets + training_ratio), dreamerv3_rl_module.py (world
model = RSSM with discrete categorical latents, reward/continue heads,
symlog/twohot targets; actor/critic heads), dreamerv3_learner.py and
tf/dreamerv3_tf_learner.py (the three losses: world-model prediction +
KL-balanced dynamics/representation, critic twohot + EMA regularizer,
actor REINFORCE with percentile return normalization). The reference
is TensorFlow/Keras; this is a functional jax redesign: the whole
update — sequence posterior scan, imagination rollout scan, all three
losses — is ONE jitted program; the RSSM scans are `lax.scan`s that
XLA unrolls onto the MXU, and the imagination rollout never leaves the
device.

Observations: vectors (symlog MLP encoder + symlog-MSE decoder) AND
images (catalog conv encoder over [-0.5, 0.5]-scaled pixels + dense
pixel decoder — proportionate to the MinAtar-scale grids this image
can host; the reference's 64x64 Atari decoder is a deconv stack).
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig

# ------------------------------------------------------------ symlog/twohot
# Reference: utils/symlog used throughout DreamerV3 (predict in a
# squashed space so one set of hyperparams survives reward scales).


def symlog(x):
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


NUM_BINS = 63
BINS = jnp.linspace(-20.0, 20.0, NUM_BINS)


def twohot(y):
    """Symlog value -> two-hot distribution over the fixed bins."""
    y = jnp.clip(symlog(y), BINS[0], BINS[-1])
    idx = jnp.sum((BINS[None, :] <= y[..., None]).astype(jnp.int32),
                  axis=-1) - 1
    idx = jnp.clip(idx, 0, NUM_BINS - 2)
    lo, hi = BINS[idx], BINS[idx + 1]
    w_hi = (y - lo) / (hi - lo)
    oh_lo = jax.nn.one_hot(idx, NUM_BINS) * (1.0 - w_hi)[..., None]
    oh_hi = jax.nn.one_hot(idx + 1, NUM_BINS) * w_hi[..., None]
    return oh_lo + oh_hi


def twohot_mean(logits):
    """Expected symexp'd value of a twohot head."""
    return symexp(jnp.sum(jax.nn.softmax(logits) * BINS, axis=-1))


# ------------------------------------------------------------ tiny nn


def _dense_init(key, sizes):
    layers = []
    for a, b in zip(sizes[:-1], sizes[1:]):
        key, k = jax.random.split(key)
        layers.append({"w": jax.random.normal(k, (a, b)) * np.sqrt(1.0 / a),
                       "b": jnp.zeros((b,))})
    return layers


def _mlp(layers, x, act=jax.nn.silu, out_act=False):
    for i, lyr in enumerate(layers):
        x = x @ lyr["w"] + lyr["b"]
        if i < len(layers) - 1 or out_act:
            x = act(x)
    return x


def _gru_init(key, in_dim, units):
    k1, k2 = jax.random.split(key)
    return {"wi": jax.random.normal(k1, (in_dim, 3 * units)) *
            np.sqrt(1.0 / in_dim),
            "wh": jax.random.normal(k2, (units, 3 * units)) *
            np.sqrt(1.0 / units),
            "b": jnp.zeros((3 * units,))}


def _gru(p, h, x):
    gates = x @ p["wi"] + h @ p["wh"] + p["b"]
    r, z, n = jnp.split(gates, 3, axis=-1)
    r, z = jax.nn.sigmoid(r), jax.nn.sigmoid(z)
    n = jnp.tanh(r * n)
    return (1.0 - z) * n + z * h


# ------------------------------------------------------------ buffer


class EpisodeSequenceBuffer:
    """Sequence replay for world-model training (reference role:
    utils/env_runner + the episode replay buffer DreamerV3 samples
    (B, T) windows from). One contiguous stream per vector env; windows
    are time-contiguous within a stream and `first` flags let the RSSM
    reset latents at episode boundaries inside a window."""

    def __init__(self, capacity: int, num_envs: int, seed: int = 0):
        self._cap = max(1, capacity // max(1, num_envs))
        self._streams = [{} for _ in range(num_envs)]
        self._rng = np.random.default_rng(seed)

    def add_step(self, batch: dict):
        """batch: field -> (num_envs, ...) arrays for ONE env step."""
        for i, stream in enumerate(self._streams):
            for k, v in batch.items():
                buf = stream.setdefault(k, [])
                buf.append(np.asarray(v[i]))
                if len(buf) > self._cap:
                    del buf[:len(buf) - self._cap]

    def __len__(self):
        return sum(len(next(iter(s.values()), [])) for s in self._streams)

    def can_sample(self, B: int, T: int) -> bool:
        return any(len(next(iter(s.values()), [])) >= T
                   for s in self._streams)

    def sample_sequences(self, B: int, T: int) -> dict:
        eligible = [i for i, s in enumerate(self._streams)
                    if len(next(iter(s.values()), [])) >= T]
        out: dict[str, list] = {}
        for _ in range(B):
            s = self._streams[self._rng.choice(eligible)]
            n = len(next(iter(s.values())))
            off = int(self._rng.integers(0, n - T + 1))
            for k, buf in s.items():
                out.setdefault(k, []).append(np.stack(buf[off:off + T]))
        return {k: np.stack(v) for k, v in out.items()}  # (B, T, ...)


# ------------------------------------------------------------ config


@dataclasses.dataclass
class DreamerV3Config(AlgorithmConfig):
    """Reference: DreamerV3Config (dreamerv3.py) — the two knobs that
    matter are model_size and training_ratio; rides the shared
    AlgorithmConfig so DreamerV3 runs as a Tune trial."""

    env: str = "CartPole-v1"
    model_size: str = "XS"  # XS | S (test scale; larger follow the table)
    training_ratio: float = 512.0  # replayed steps per env step
    batch_size_B: int = 8
    batch_length_T: int = 16
    horizon_H: int = 15
    gamma: float = 0.997
    gae_lambda: float = 0.95
    lr_world: float = 1e-4
    lr_actor: float = 3e-5
    lr_critic: float = 3e-5
    entropy_scale: float = 3e-4
    free_bits: float = 1.0
    buffer_capacity: int = 100_000
    num_envs: int = 4
    rollout_fragment_length: int = 16

    def dims(self):
        # reference model-size table (dreamerv3.py): deter/units scale
        table = {"XS": (128, 128, 4, 4), "S": (512, 512, 32, 32)}
        deter, units, n_cat, n_cls = table[self.model_size]
        return {"deter": deter, "units": units, "n_cat": n_cat,
                "n_cls": n_cls}

    def build(self) -> "DreamerV3":
        return DreamerV3(self)


# ------------------------------------------------------------ algorithm


class DreamerV3(Algorithm):
    config_class = DreamerV3Config
    STATE_COMPONENTS = ("wm", "actor", "critic", "critic_ema",
                        "_env_steps", "_iteration", "_timesteps_total")

    def setup(self, config: DreamerV3Config):
        if config.evaluation_interval:
            raise ValueError(
                "DreamerV3 has no separate evaluation runner — "
                "episode_return_mean from training IS the "
                "evaluation surface; unset evaluation_interval")
        import gymnasium as gym

        cfg = config
        d = cfg.dims()
        deter, units = d["deter"], d["units"]
        self.n_cat, self.n_cls = d["n_cat"], d["n_cls"]
        stoch = self.n_cat * self.n_cls

        from ray_tpu.rllib import envs as _envs

        _envs.register_envs()
        self.envs = gym.make_vec(cfg.env, num_envs=cfg.num_envs)
        obs_shape = tuple(self.envs.single_observation_space.shape)
        self._obs_shape = obs_shape
        self._image_obs = len(obs_shape) == 3  # catalog.is_image rule
        self.obs_dim = int(np.prod(obs_shape))
        self.n_actions = int(self.envs.single_action_space.n)
        A, O = self.n_actions, self.obs_dim

        key = jax.random.PRNGKey(cfg.seed)
        ks = jax.random.split(key, 12)
        if self._image_obs:
            from ray_tpu.rllib.catalog import init_conv_encoder

            encoder, _ = init_conv_encoder(ks[0], obs_shape,
                                           out_dim=units)
        else:
            encoder = _dense_init(ks[0], (O, units, units))
        # world model (reference: dreamerv3_rl_module.py components)
        self.wm = {
            "encoder": encoder,
            "gru_in": _dense_init(ks[1], (stoch + A, units)),
            "gru": _gru_init(ks[2], units, deter),
            "prior": _dense_init(ks[3], (deter, units, stoch)),
            "post": _dense_init(ks[4], (deter + units, units, stoch)),
            "decoder": _dense_init(ks[5], (deter + stoch, units, units, O)),
            "reward": _dense_init(ks[6], (deter + stoch, units, NUM_BINS)),
            "cont": _dense_init(ks[7], (deter + stoch, units, 1)),
        }
        self.actor = _dense_init(ks[8], (deter + stoch, units, units, A))
        self.critic = _dense_init(ks[9], (deter + stoch, units, units,
                                          NUM_BINS))
        self.critic_ema = jax.tree.map(jnp.copy, self.critic)

        self.wm_tx = optax.adam(cfg.lr_world)
        self.actor_tx = optax.adam(cfg.lr_actor)
        self.critic_tx = optax.adam(cfg.lr_critic)
        self.wm_opt = self.wm_tx.init(self.wm)
        self.actor_opt = self.actor_tx.init(self.actor)
        self.critic_opt = self.critic_tx.init(self.critic)

        self.buffer = EpisodeSequenceBuffer(cfg.buffer_capacity,
                                            cfg.num_envs, seed=cfg.seed)
        self._key = jax.random.PRNGKey(cfg.seed + 1)
        self.obs, _ = self.envs.reset(seed=cfg.seed)
        self._h = np.zeros((cfg.num_envs, deter), np.float32)
        self._z = np.zeros((cfg.num_envs, stoch), np.float32)
        self._prev_done = np.zeros(cfg.num_envs, np.bool_)
        self._ep_returns = np.zeros(cfg.num_envs)
        self._completed: list[float] = []
        self._env_steps = 0
        self._replayed = 0
        self._build_fns(deter, stoch, A)

    # -------------------------------------------------------------- fns

    def _latent(self, wm, key, logits):
        """Sample the categorical latent with straight-through gradients
        and 1% uniform mixing (reference: 'unimix' in the RSSM)."""
        B = logits.shape[:-1]
        lg = logits.reshape(*B, self.n_cat, self.n_cls)
        probs = 0.99 * jax.nn.softmax(lg) + 0.01 / self.n_cls
        idx = jax.random.categorical(key, jnp.log(probs))
        oh = jax.nn.one_hot(idx, self.n_cls)
        oh = oh + probs - jax.lax.stop_gradient(probs)  # straight-through
        return oh.reshape(*B, self.n_cat * self.n_cls), jnp.log(probs)

    def _build_fns(self, deter, stoch, A):
        cfg = self.config
        n_cat, n_cls = self.n_cat, self.n_cls
        image = self._image_obs

        def prep(obs):
            """Raw obs -> the encoder/decoder target space: pixels scale
            to [-0.5, 0.5] (reference image preprocessing), vectors go
            through symlog."""
            obs = obs.astype(jnp.float32)
            return obs / 255.0 - 0.5 if image else symlog(obs)

        def encode(wm, obs):
            if image:
                from ray_tpu.rllib.catalog import apply_conv_encoder

                return apply_conv_encoder(wm["encoder"], obs)
            return _mlp(wm["encoder"], obs, out_act=True)

        def obs_step(wm, key, h, z, a_onehot, obs):
            """One posterior RSSM step with real (preprocessed) obs."""
            x = _mlp(wm["gru_in"], jnp.concatenate([z, a_onehot], -1),
                     out_act=True)
            h = _gru(wm["gru"], h, x)
            emb = encode(wm, obs)
            post_logits = _mlp(wm["post"], jnp.concatenate([h, emb], -1))
            prior_logits = _mlp(wm["prior"], h)
            z, _ = self._latent(wm, key, post_logits)
            return h, z, post_logits, prior_logits

        def img_step(wm, key, h, z, a_onehot):
            x = _mlp(wm["gru_in"], jnp.concatenate([z, a_onehot], -1),
                     out_act=True)
            h = _gru(wm["gru"], h, x)
            prior_logits = _mlp(wm["prior"], h)
            z, _ = self._latent(wm, key, prior_logits)
            return h, z

        def kl_cat(lhs_logits, rhs_logits):
            """KL between the n_cat categorical factors, summed."""
            ll = lhs_logits.reshape(*lhs_logits.shape[:-1], n_cat, n_cls)
            rl = rhs_logits.reshape(*rhs_logits.shape[:-1], n_cat, n_cls)
            lp = 0.99 * jax.nn.softmax(ll) + 0.01 / n_cls
            rp = 0.99 * jax.nn.softmax(rl) + 0.01 / n_cls
            return jnp.sum(lp * (jnp.log(lp) - jnp.log(rp)), axis=(-2, -1))

        def wm_loss(wm, batch, key):
            """World-model loss over (B, T) sequences (reference:
            dreamerv3_tf_learner.py world-model part): symlog MSE
            decoder + twohot reward + bernoulli continue + KL-balanced
            dyn/rep with free bits."""
            B, T = batch["obs"].shape[:2]
            h0 = jnp.zeros((B, deter))
            z0 = jnp.zeros((B, stoch))
            a_oh = jax.nn.one_hot(batch["actions"], A)
            keys = jax.random.split(key, T)

            def scan_fn(carry, t_in):
                h, z = carry
                k, obs_t, a_prev, first = t_in
                # episode boundary: reset the latent state
                h = jnp.where(first[:, None], jnp.zeros_like(h), h)
                z = jnp.where(first[:, None], jnp.zeros_like(z), z)
                a_prev = jnp.where(first[:, None], jnp.zeros_like(a_prev),
                                   a_prev)
                h, z, post_l, prior_l = obs_step(wm, k, h, z, a_prev, obs_t)
                return (h, z), (h, z, post_l, prior_l)

            a_prev = jnp.concatenate([jnp.zeros_like(a_oh[:, :1]),
                                      a_oh[:, :-1]], axis=1)
            enc_in = prep(batch["obs"])  # encoder + decoder target space
            (_, _), (hs, zs, post_l, prior_l) = jax.lax.scan(
                scan_fn, (h0, z0),
                (keys, enc_in.swapaxes(0, 1),
                 a_prev.swapaxes(0, 1), batch["first"].swapaxes(0, 1)))
            # scan outputs are (T, B, ...) -> (B, T, ...)
            hs, zs = hs.swapaxes(0, 1), zs.swapaxes(0, 1)
            post_l, prior_l = post_l.swapaxes(0, 1), prior_l.swapaxes(0, 1)
            feat = jnp.concatenate([hs, zs], -1)

            recon = _mlp(wm["decoder"], feat)
            l_dec = jnp.mean(jnp.sum(
                (recon - enc_in.reshape(B, T, -1)) ** 2, -1))
            r_logits = _mlp(wm["reward"], feat)
            l_rew = -jnp.mean(jnp.sum(
                twohot(batch["rewards"]) * jax.nn.log_softmax(r_logits), -1))
            c_logit = _mlp(wm["cont"], feat)[..., 0]
            cont = 1.0 - batch["dones"]
            l_cont = jnp.mean(optax.sigmoid_binary_cross_entropy(
                c_logit, cont))
            # KL balancing (0.5 dyn / 0.1 rep) with free bits
            dyn = kl_cat(jax.lax.stop_gradient(post_l), prior_l)
            rep = kl_cat(post_l, jax.lax.stop_gradient(prior_l))
            l_dyn = jnp.mean(jnp.maximum(dyn, cfg.free_bits))
            l_rep = jnp.mean(jnp.maximum(rep, cfg.free_bits))
            total = l_dec + l_rew + l_cont + 0.5 * l_dyn + 0.1 * l_rep
            return total, (feat, {"wm/decoder": l_dec, "wm/reward": l_rew,
                                  "wm/continue": l_cont, "wm/dyn": l_dyn,
                                  "wm/rep": l_rep})

        def imagine(wm, actor, key, feat0):
            """Dream H steps from every posterior state (B*T starts)."""
            S = feat0.shape[0]
            h, z = feat0[:, :deter], feat0[:, deter:]
            keys = jax.random.split(key, cfg.horizon_H)

            def scan_fn(carry, k):
                h, z = carry
                ka, kz = jax.random.split(k)
                feat = jnp.concatenate([h, z], -1)
                logits = _mlp(actor, jax.lax.stop_gradient(feat))
                probs = 0.99 * jax.nn.softmax(logits) + 0.01 / A
                a = jax.random.categorical(ka, jnp.log(probs))
                a_oh = jax.nn.one_hot(a, A)
                h, z = img_step(wm, kz, h, z, a_oh)
                logp = jnp.take_along_axis(jnp.log(probs), a[:, None],
                                           1)[:, 0]
                ent = -jnp.sum(probs * jnp.log(probs), -1)
                return (h, z), (jnp.concatenate([h, z], -1), logp, ent)

            (_, _), (feats, logps, ents) = jax.lax.scan(
                scan_fn, (h, z), keys)
            return feats, logps, ents  # (H, S, ...)

        def lambda_returns(rewards, conts, values):
            """TD(lambda) over the imagined horizon."""
            def scan_fn(nxt, t_in):
                r, c, v_next = t_in
                ret = r + cfg.gamma * c * (
                    (1 - cfg.gae_lambda) * v_next + cfg.gae_lambda * nxt)
                return ret, ret

            _, rets = jax.lax.scan(
                scan_fn, values[-1],
                (rewards[:-1][::-1], conts[:-1][::-1], values[1:][::-1]))
            return rets[::-1]

        def ac_losses(actor, critic, critic_ema, wm, key, feat_post):
            feat0 = jax.lax.stop_gradient(
                feat_post.reshape(-1, feat_post.shape[-1]))
            feats, logps, ents = imagine(wm, actor, key, feat0)
            feats = jnp.concatenate([feat0[None], feats], 0)  # (H+1, S, F)
            feats = jax.lax.stop_gradient(feats)
            rew = twohot_mean(_mlp(wm["reward"], feats))
            cont = jax.nn.sigmoid(_mlp(wm["cont"], feats)[..., 0])
            v = twohot_mean(_mlp(critic, feats))
            rets = lambda_returns(rew, cont, v)  # (H, S)
            weights = jnp.cumprod(
                jnp.concatenate([jnp.ones((1,) + cont.shape[1:]),
                                 cfg.gamma * cont[:-1]], 0), 0)
            weights = jax.lax.stop_gradient(weights)
            # actor: REINFORCE on percentile-normalized returns
            # (reference: the 5th-95th percentile scale)
            offset = jnp.percentile(rets, 5)
            scale = jnp.maximum(1.0, jnp.percentile(rets, 95) - offset)
            adv = jax.lax.stop_gradient(
                (rets - v[:-1]) / scale)
            l_actor = -jnp.mean(weights[:-1] * (logps * adv +
                                                cfg.entropy_scale * ents))
            # critic: twohot CE toward lambda returns + EMA regularizer
            c_logits = _mlp(critic, feats[:-1])
            tgt = jax.lax.stop_gradient(twohot(rets))
            l_critic = -jnp.mean(weights[:-1] * jnp.sum(
                tgt * jax.nn.log_softmax(c_logits), -1))
            ema_tgt = jax.lax.stop_gradient(
                jax.nn.softmax(_mlp(critic_ema, feats[:-1])))
            l_critic += -jnp.mean(weights[:-1] * jnp.sum(
                ema_tgt * jax.nn.log_softmax(c_logits), -1))
            return l_actor, l_critic, {
                "actor/entropy": jnp.mean(ents),
                "actor/adv": jnp.mean(adv),
                "critic/value": jnp.mean(v),
                "imagined_return": jnp.mean(rets),
            }

        def update(wm, wm_opt, actor, actor_opt, critic, critic_opt,
                   critic_ema, batch, key):
            kw, ka = jax.random.split(key)
            (wl, (feat, wmetrics)), wgrads = jax.value_and_grad(
                wm_loss, has_aux=True)(wm, batch, kw)

            def a_loss(actor):
                la, _, _ = ac_losses(actor, critic, critic_ema, wm, ka,
                                     feat)
                return la

            def c_loss(critic):
                _, lc, m = ac_losses(actor, critic, critic_ema, wm, ka,
                                     feat)
                return lc, m

            agrads = jax.grad(a_loss)(actor)
            (lc, acm), cgrads = jax.value_and_grad(
                c_loss, has_aux=True)(critic)
            wup, wm_opt = self.wm_tx.update(wgrads, wm_opt)
            wm = optax.apply_updates(wm, wup)
            aup, actor_opt = self.actor_tx.update(agrads, actor_opt)
            actor = optax.apply_updates(actor, aup)
            cup, critic_opt = self.critic_tx.update(cgrads, critic_opt)
            critic = optax.apply_updates(critic, cup)
            critic_ema = jax.tree.map(lambda e, c: 0.98 * e + 0.02 * c,
                                      critic_ema, critic)
            metrics = {**wmetrics, **acm, "wm/total": wl,
                       "critic/loss": lc}
            return (wm, wm_opt, actor, actor_opt, critic, critic_opt,
                    critic_ema, metrics)

        self._update = jax.jit(update)

        def act(wm, actor, key, h, z, obs, first):
            h = jnp.where(first[:, None], jnp.zeros_like(h), h)
            z = jnp.where(first[:, None], jnp.zeros_like(z), z)
            emb = encode(wm, prep(obs))
            post_logits = _mlp(wm["post"], jnp.concatenate([h, emb], -1))
            kz, ka = jax.random.split(key)
            z, _ = self._latent(wm, kz, post_logits)
            feat = jnp.concatenate([h, z], -1)
            logits = _mlp(actor, feat)
            probs = 0.99 * jax.nn.softmax(logits) + 0.01 / A
            a = jax.random.categorical(ka, jnp.log(probs))
            a_oh = jax.nn.one_hot(a, A)
            x = _mlp(wm["gru_in"], jnp.concatenate([z, a_oh], -1),
                     out_act=True)
            h = _gru(wm["gru"], h, x)
            return a, h, z

        self._act = jax.jit(act)

    # ------------------------------------------------------------ train

    def training_step(self) -> dict:
        cfg = self.config
        t0 = time.perf_counter()
        # -- collect real experience through the posterior policy
        for _ in range(cfg.rollout_fragment_length):
            self._key, k = jax.random.split(self._key)
            first = self._prev_done.copy()
            a, h, z = self._act(self.wm, self.actor, k,
                                jnp.asarray(self._h), jnp.asarray(self._z),
                                jnp.asarray(self.obs, jnp.float32),
                                jnp.asarray(first))
            a = np.asarray(a)
            self._h, self._z = np.asarray(h), np.asarray(z)
            nxt, rew, term, trunc, _ = self.envs.step(a)
            done = np.logical_or(term, trunc)
            # next-step autoreset: the step AFTER done carries the reset
            # obs with the action ignored — store it as a sequence start
            self.buffer.add_step({
                # native dtype: uint8 pixels stay uint8 in replay (4x
                # smaller); prep() scales on device at train time
                "obs": np.asarray(self.obs),
                "actions": a,
                "rewards": np.asarray(rew, np.float32),
                "dones": np.asarray(term, np.float32),
                "first": first.astype(np.float32),
            })
            self._prev_done = done
            self._ep_returns += rew
            for i in np.nonzero(done)[0]:
                self._completed.append(float(self._ep_returns[i]))
                self._ep_returns[i] = 0.0
            self.obs = nxt
            self._env_steps += cfg.num_envs

        # -- replay-train at the configured training ratio (bounded per
        # iteration so one train() call stays responsive)
        metrics = {}
        want = self._env_steps * cfg.training_ratio
        max_updates = 64
        while max_updates > 0 and self._replayed < want and \
                self.buffer.can_sample(cfg.batch_size_B, cfg.batch_length_T):
            max_updates -= 1
            batch = self.buffer.sample_sequences(cfg.batch_size_B,
                                                 cfg.batch_length_T)
            batch = {k: jnp.asarray(v) for k, v in batch.items()}
            self._key, k = jax.random.split(self._key)
            (self.wm, self.wm_opt, self.actor, self.actor_opt,
             self.critic, self.critic_opt, self.critic_ema,
             m) = self._update(self.wm, self.wm_opt, self.actor,
                               self.actor_opt, self.critic,
                               self.critic_opt, self.critic_ema, batch, k)
            metrics = {k2: float(v) for k2, v in m.items()}
            self._replayed += cfg.batch_size_B * cfg.batch_length_T

        window = self._completed[-100:]
        self._completed = window
        return {
            "episode_return_mean": float(np.mean(window)) if window
            else float("nan"),
            "num_env_steps_sampled_lifetime": self._env_steps,
            "num_steps_replayed": self._replayed,
            "time_s": time.perf_counter() - t0,
            **metrics,
        }

    def get_weights(self):
        return jax.tree.map(np.asarray, {"wm": self.wm, "actor": self.actor,
                                         "critic": self.critic})

    def evaluate(self) -> dict:
        # Dreamer's env loop lives in the driver with its own buffer —
        # episode_return_mean from training is the evaluation surface
        raise NotImplementedError(
            "DreamerV3 evaluation rides episode_return_mean from training")

    def cleanup(self):
        self.envs.close()
