"""Multi-agent RL — MultiRLModule + multi-agent PPO.

Reference parity: rllib/core/rl_module/multi_rl_module.py:49 (a dict of
RLModules keyed by module id), the MultiAgentEnv API
(rllib/env/multi_agent_env.py — dict obs/rewards/dones with "__all__"),
and policy mapping (config.multi_agent(policy_mapping_fn=...)). The
learner side reuses the single-agent PPO machinery per module: each
module's batch is assembled from the agents mapped to it and updated
with the same jitted SPMD step.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

import numpy as np

from ray_tpu.rllib.learner import PPOLearner, PPOLearnerConfig, compute_gae


class MultiAgentEnv:
    """Dict-keyed env protocol (reference: rllib/env/multi_agent_env.py).
    step() returns (obs, rewards, terminateds, truncateds, infos) dicts;
    terminateds["__all__"] ends the episode."""

    agents: list[str] = []

    def reset(self, *, seed=None):
        raise NotImplementedError

    def step(self, action_dict: dict):
        raise NotImplementedError


class CoordinationGame(MultiAgentEnv):
    """Two agents are rewarded for choosing the SAME action; obs is the
    one-hot of the previous joint action. A minimal learnable testbed
    (the repeated-matrix-game pattern of rllib/examples/multi_agent)."""

    agents = ["a0", "a1"]
    obs_dim = 4
    n_actions = 2

    def __init__(self, episode_len: int = 25):
        self.episode_len = episode_len
        self._rng = np.random.default_rng(0)

    def _obs(self):
        o = np.zeros(4, np.float32)
        o[self._prev] = 1.0
        return {a: o.copy() for a in self.agents}

    def reset(self, *, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        self._prev = int(self._rng.integers(0, 4))
        return self._obs(), {}

    def step(self, action_dict: dict):
        a0, a1 = int(action_dict["a0"]), int(action_dict["a1"])
        r = 1.0 if a0 == a1 else 0.0
        self._prev = a0 * 2 + a1
        self._t += 1
        done = self._t >= self.episode_len
        rewards = {a: r for a in self.agents}
        term = {a: done for a in self.agents}
        term["__all__"] = done
        trunc = {a: False for a in self.agents}
        trunc["__all__"] = False
        return self._obs(), rewards, term, trunc, {}


class MultiRLModule:
    """Dict of per-module policy params (reference:
    multi_rl_module.py:49). Modules are the unit of optimization;
    agents map onto modules via policy_mapping_fn (parameter sharing =
    many agents -> one module)."""

    def __init__(self, learners: dict[str, PPOLearner],
                 policy_mapping_fn: Callable[[str], str]):
        self.learners = learners
        self.policy_mapping_fn = policy_mapping_fn

    def __getitem__(self, module_id: str) -> PPOLearner:
        return self.learners[module_id]

    def module_for(self, agent_id: str) -> str:
        return self.policy_mapping_fn(agent_id)

    def get_weights(self) -> dict:
        return {m: l.get_weights() for m, l in self.learners.items()}


@dataclasses.dataclass
class MultiAgentPPOConfig:
    env_maker: Callable[[], MultiAgentEnv] = CoordinationGame
    policies: tuple = ("shared",)  # module ids
    policy_mapping_fn: Callable[[str], str] = lambda aid: "shared"
    rollout_episodes: int = 16
    gamma: float = 0.99
    lambda_: float = 0.95
    lr: float = 5e-3
    num_sgd_iter: int = 4
    minibatch_size: int = 256
    entropy_coeff: float = 0.01
    hidden: tuple = (32, 32)
    seed: int = 0

    def multi_agent(self, policies=None, policy_mapping_fn=None
                    ) -> "MultiAgentPPOConfig":
        if policies is not None:
            self.policies = tuple(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO(self)


class MultiAgentPPO:
    """training_step: sample episodes from the multi-agent env, split
    experience per MODULE, per-module GAE + PPO update (reference:
    multi-agent training_step assembling MultiAgentBatch per module)."""

    def __init__(self, config: MultiAgentPPOConfig):
        import jax

        self.config = config
        self.env = config.env_maker()
        probe_obs, _ = self.env.reset(seed=config.seed)
        obs_dim = len(next(iter(probe_obs.values())))
        n_actions = getattr(self.env, "n_actions", 2)
        lcfg = PPOLearnerConfig(
            lr=config.lr, entropy_coeff=config.entropy_coeff,
            num_sgd_iter=config.num_sgd_iter,
            minibatch_size=config.minibatch_size, hidden=config.hidden)
        self.module = MultiRLModule(
            {m: PPOLearner(obs_dim, n_actions, lcfg,
                           seed=config.seed + i)
             for i, m in enumerate(config.policies)},
            config.policy_mapping_fn)
        from ray_tpu.rllib import models

        self._sample_fn = jax.jit(models.sample_actions)
        self._key = jax.random.PRNGKey(config.seed + 99)
        self._jax = jax
        self._iteration = 0

    def _rollout(self):
        """Sample episodes; returns per-agent trajectories."""
        jax = self._jax
        cfg = self.config
        trajs = {a: {"obs": [], "actions": [], "logp": [], "values": [],
                     "rewards": [], "dones": []}
                 for a in self.env.agents}
        ep_returns = []
        for ep in range(cfg.rollout_episodes):
            obs, _ = self.env.reset(seed=cfg.seed * 1000 + self._iteration
                                    * 100 + ep)
            done, total = False, 0.0
            while not done:
                actions = {}
                for a, o in obs.items():
                    m = self.module.module_for(a)
                    self._key, k = jax.random.split(self._key)
                    act, logp, val = self._sample_fn(
                        self.module[m].params,
                        np.asarray(o, np.float32)[None], k)
                    actions[a] = int(np.asarray(act)[0])
                    t = trajs[a]
                    t["obs"].append(np.asarray(o, np.float32))
                    t["actions"].append(actions[a])
                    t["logp"].append(float(np.asarray(logp)[0]))
                    t["values"].append(float(np.asarray(val)[0]))
                obs, rewards, term, trunc, _ = self.env.step(actions)
                done = term.get("__all__") or trunc.get("__all__")
                for a, r in rewards.items():
                    trajs[a]["rewards"].append(float(r))
                    trajs[a]["dones"].append(bool(done))
                total += sum(rewards.values()) / len(rewards)
            ep_returns.append(total)
        return trajs, ep_returns

    def train(self) -> dict:
        cfg = self.config
        t0 = time.perf_counter()
        trajs, ep_returns = self._rollout()
        # assemble per-MODULE batches from the agents mapped to each
        per_module: dict[str, dict] = {}
        for agent, t in trajs.items():
            m = self.module.module_for(agent)
            T = len(t["rewards"])
            if T == 0:
                continue
            adv, targets = compute_gae(
                np.asarray(t["rewards"], np.float32).reshape(T, 1),
                np.asarray(t["values"], np.float32).reshape(T, 1),
                np.asarray(t["dones"]).reshape(T, 1),
                np.zeros(1, np.float32), cfg.gamma, cfg.lambda_)
            dst = per_module.setdefault(
                m, {"obs": [], "actions": [], "logp_old": [],
                    "advantages": [], "value_targets": []})
            dst["obs"].append(np.stack(t["obs"]))
            dst["actions"].append(np.asarray(t["actions"], np.int64))
            dst["logp_old"].append(np.asarray(t["logp"], np.float32))
            dst["advantages"].append(adv.reshape(-1))
            dst["value_targets"].append(targets.reshape(-1))
        metrics = {}
        for m, batch in per_module.items():
            flat = {k: np.concatenate(v) for k, v in batch.items()}
            metrics[m] = self.module[m].update(flat)
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "episode_return_mean": float(np.mean(ep_returns)),
            "env_steps_per_sec": (sum(len(t["rewards"])
                                      for t in trajs.values())
                                  / (time.perf_counter() - t0)),
            **{f"learner/{m}/{k}": v for m, mm in metrics.items()
               for k, v in mm.items()},
        }
