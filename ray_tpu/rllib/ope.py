"""Off-policy evaluation over logged experiences.

Reference parity: rllib/offline/estimators — ImportanceSampling,
WeightedImportanceSampling (is/wis.py), and the doubly-robust family
(doubly_robust.py). Estimators consume the same jsonl/parquet episode
rows `record_experiences` writes (obs/action/reward/done/truncated/
logp): the logged `logp` is the behavior policy's action
log-probability, and the TARGET policy is a params pytree evaluated
with the functional model (`models.forward`) — one jit-able batch pass
per dataset, no environment interaction.

Estimates follow the per-decision formulation:
  IS :  V = E_ep [ sum_t gamma^t * rho_{0:t} * r_t ]
  WIS:  same, but rho_{0:t} is normalized per t by its mean over
        episodes (self-normalized weights — lower variance, small bias)
  DR :  V = E_ep [ V_hat(s_0) + sum_t gamma^t * rho_{0:t} *
              (r_t + gamma * V_hat(s_{t+1}) - V_hat(s_t)) ]
        with the target policy's value head as the state baseline.
"""

from __future__ import annotations

import jax
import numpy as np

from ray_tpu.rllib import models


def split_episodes(rows: list[dict]) -> list[list[dict]]:
    """Env-major row stream -> list of trajectories (cut at done or
    truncated — a truncated tail is still a usable partial episode)."""
    episodes: list[list[dict]] = []
    cur: list[dict] = []
    for r in rows:
        cur.append(r)
        if r.get("done") or r.get("truncated"):
            episodes.append(cur)
            cur = []
    if cur:
        episodes.append(cur)
    return episodes


def _target_logp_and_values(params, episodes):
    """One batched forward over every logged step: per-episode arrays of
    target-policy log-probs and state values."""
    obs = np.asarray([r["obs"] for ep in episodes for r in ep],
                     np.float32)
    acts = np.asarray([r["action"] for ep in episodes for r in ep],
                      np.int64)
    logits, values = jax.jit(models.forward)(params, obs)
    logp_all = jax.nn.log_softmax(logits)
    logp = np.asarray(logp_all)[np.arange(len(acts)), acts]
    values = np.asarray(values)
    out_logp, out_v, i = [], [], 0
    for ep in episodes:
        out_logp.append(logp[i:i + len(ep)])
        out_v.append(values[i:i + len(ep)])
        i += len(ep)
    return out_logp, out_v


class OffPolicyEstimator:
    """Base (reference: offline/estimators/off_policy_estimator.py)."""

    def __init__(self, params, gamma: float = 0.99):
        self.params = params
        self.gamma = gamma

    def estimate(self, rows: list[dict]) -> dict:
        episodes = [ep for ep in split_episodes(rows) if ep]
        if not episodes:
            return {"v_target": float("nan"),
                    "v_behavior": float("nan"), "v_gain": float("nan")}
        t_logp, t_val = _target_logp_and_values(self.params, episodes)
        g = self.gamma
        v_behavior = float(np.mean([
            sum(g ** t * r["reward"] for t, r in enumerate(ep))
            for ep in episodes]))
        v_target = self._estimate(episodes, t_logp, t_val)
        return {
            "v_target": float(v_target),
            "v_behavior": v_behavior,
            "v_gain": float(v_target / v_behavior) if v_behavior else
            float("nan"),
            "num_episodes": len(episodes),
        }

    # rho_{0:t} per episode, clipped for numeric sanity
    def _cum_rhos(self, episodes, t_logp, clip: float = 1e3):
        out = []
        for ep, tl in zip(episodes, t_logp):
            beh = np.asarray([r["logp"] for r in ep], np.float64)
            rho = np.exp(np.cumsum(tl.astype(np.float64) - beh))
            out.append(np.clip(rho, 0.0, clip))
        return out

    def _estimate(self, episodes, t_logp, t_val) -> float:
        raise NotImplementedError


class ImportanceSampling(OffPolicyEstimator):
    """Per-decision ordinary IS (reference: estimators/is.py)."""

    def _estimate(self, episodes, t_logp, t_val) -> float:
        g = self.gamma
        vals = []
        for ep, rho in zip(episodes, self._cum_rhos(episodes, t_logp)):
            vals.append(sum(g ** t * rho[t] * r["reward"]
                            for t, r in enumerate(ep)))
        return float(np.mean(vals))


class WeightedImportanceSampling(OffPolicyEstimator):
    """Self-normalized per-decision IS (reference: estimators/wis.py):
    rho_{0:t} divided by its mean over episodes at each t."""

    def _estimate(self, episodes, t_logp, t_val) -> float:
        g = self.gamma
        rhos = self._cum_rhos(episodes, t_logp)
        T = max(len(ep) for ep in episodes)
        # mean weight per timestep over the episodes still alive at t
        denom = np.array([
            np.mean([rho[t] for rho in rhos if len(rho) > t]) or 1.0
            for t in range(T)])
        vals = []
        for ep, rho in zip(episodes, rhos):
            vals.append(sum(
                g ** t * (rho[t] / max(denom[t], 1e-12)) * r["reward"]
                for t, r in enumerate(ep)))
        return float(np.mean(vals))


class DoublyRobust(OffPolicyEstimator):
    """DR with the target value head as state baseline (reference:
    estimators/doubly_robust.py; Jiang & Li 2016 with V as the control
    variate): exact when either the weights or the baseline are right,
    lower variance than IS when the baseline is decent."""

    def _estimate(self, episodes, t_logp, t_val) -> float:
        g = self.gamma
        vals = []
        for ep, rho, v in zip(episodes,
                              self._cum_rhos(episodes, t_logp), t_val):
            total = float(v[0])
            for t, r in enumerate(ep):
                terminal = bool(r.get("done"))
                v_next = 0.0 if (terminal or t + 1 >= len(ep)) \
                    else float(v[t + 1])
                td = r["reward"] + g * v_next - float(v[t])
                total += g ** t * rho[t] * td
            vals.append(total)
        return float(np.mean(vals))
