"""ray_tpu.rllib.llm — the RL-for-LLMs flywheel.

Closes the loop between the repo's two halves (RL.md has the full
walkthrough):

- **rollout** (`rollout.py`): the serve.llm continuous-batching engine
  is the rollout actor — N completions per prompt share the task's
  system prefix through the PR 4 prefix cache, per-token logprobs and
  weight-version tags ride the stream, trajectory groups stream into
  the object store as they finish;
- **learn** (`learner.py`): a GRPO-style clipped policy-gradient
  update, ONE jitted program over the train/ SPMD machinery
  (make_train_step + the models' own forwards/partition rules), with a
  staleness guard keyed on the weight-version tags;
- **swap** (`flywheel.py` + serve.llm): the learner publishes params
  through the object store and live replicas install them at an engine
  step boundary — drain-free, no stream drops, in-flight sequences
  tagged stale when they span versions.
"""

from ray_tpu.rllib.llm.flywheel import FlywheelConfig, RLFlywheel
from ray_tpu.rllib.llm.learner import LLMLearner, LLMLearnerConfig
from ray_tpu.rllib.llm.reward import (
    DigitSumTask,
    SortTask,
    get_reward,
    register_reward,
)
from ray_tpu.rllib.llm.rollout import RolloutConfig, RolloutWorker
from ray_tpu.rllib.llm.trajectory import (
    Trajectory,
    group_relative_advantages,
    to_train_batch,
)

__all__ = [
    "DigitSumTask",
    "FlywheelConfig",
    "LLMLearner",
    "LLMLearnerConfig",
    "RLFlywheel",
    "RolloutConfig",
    "RolloutWorker",
    "SortTask",
    "Trajectory",
    "get_reward",
    "group_relative_advantages",
    "register_reward",
    "to_train_batch",
]
