"""GRPO-style LLM learner over the train/ SPMD machinery.

The update is ONE jitted program built by `train.spmd.make_train_step`
— the same TrainState/partition-rules/batch-sharding path the
supervised trainer uses (learner mesh: batch sharded over the data
axis, params replicated or rule-sharded, GSPMD inserting the gradient
collectives) — with a GRPO policy-gradient loss instead of next-token
cross entropy:

    ratio  = exp(logp_new - logp_old)          per generated token
    adv    = (r - mean_group) / (std_group+ε)  per sequence (GRPO)
    loss   = -mean over generated tokens of
             min(ratio * adv, clip(ratio, 1±ε_clip) * adv)

`logp_old` comes from the serve.llm engine's rollout stream (the
behaviour policy at the tagged weight version), so the clipped
importance ratio absorbs exactly one flywheel lap of staleness; the
**staleness guard** drops trajectories that are older than
`max_staleness` versions or tagged stale (mixed weight versions) —
their logprobs are not reproducible at any single version, and feeding
them in corrupts the ratios silently.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax

# forces jax_threefry_partitionable before any param init (same init-
# parity invariant as rllib/learner.py — see the note there)
import ray_tpu.parallel.mesh  # noqa: F401
from ray_tpu.rllib.llm.trajectory import (
    Trajectory,
    group_relative_advantages,
    to_train_batch,
)


@dataclasses.dataclass
class LLMLearnerConfig:
    lr: float = 1e-3
    clip_eps: float = 0.2  # PPO-style ratio clip
    grad_clip: float = 1.0
    group_eps: float = 1e-6  # GRPO advantage denominator
    # trajectories sampled more than this many weight versions before
    # the CURRENT learner version are dropped (0 = on-policy only; the
    # synchronous flywheel produces staleness 0, pipelined rollouts 1)
    max_staleness: int = 1
    # sampling temperature the rollouts ran at; logp_new is scaled the
    # same way so ratio == 1 at zero divergence
    temperature: float = 1.0


class LLMLearner:
    """Owns params + optimizer for one model family ("gpt2"/"llama");
    `update(trajectories)` runs one jitted GRPO step and bumps the
    weight version; `publish_weights()` hands the new version to the
    serving side (through the object store when a runtime is up)."""

    def __init__(self, model: str = "gpt2", model_config: Any = None,
                 *, params: Any = None, mesh=None,
                 config: LLMLearnerConfig | None = None, seed: int = 0):
        from ray_tpu.models import gpt2, llama
        from ray_tpu.train.spmd import TrainState, make_train_step

        families = {
            "gpt2": (gpt2.gpt2_forward, gpt2.init_gpt2,
                     gpt2.gpt2_partition_rules, gpt2.GPT2Config.tiny),
            "llama": (llama.llama_forward, llama.init_llama,
                      llama.llama_partition_rules, llama.LlamaConfig.tiny),
        }
        if model not in families:
            raise ValueError(
                f"unknown model {model!r}; have {sorted(families)}")
        forward, init_fn, rules_fn, default_cfg = families[model]
        self.model = model
        self.cfg = model_config if model_config is not None \
            else default_cfg()
        self.config = config or LLMLearnerConfig()
        self.mesh = mesh
        self._forward = forward
        self._rules = rules_fn()
        self.version = 0  # last PUBLISHED weight version
        self.tx = optax.chain(
            optax.clip_by_global_norm(self.config.grad_clip),
            optax.adam(self.config.lr),
        )
        if params is None:
            params = init_fn(jax.random.PRNGKey(seed), self.cfg)
        if mesh is not None:
            from ray_tpu.parallel.sharding import shard_pytree

            params = shard_pytree(params, self._rules, mesh)
        # optimizer moments are zeros_like(params): they inherit the
        # param shardings, same layout state_shardings would pick
        self.state = TrainState.create(params, self.tx)

        cfg = self.config
        vocab = self.cfg.vocab_size
        temp = max(cfg.temperature, 1e-6)

        def loss_fn(params, batch):
            logits = forward(params, batch["inputs"], self.cfg)
            logp_all = jax.nn.log_softmax(
                logits[..., :vocab] / temp, axis=-1)
            lp = jnp.take_along_axis(
                logp_all, batch["targets"][..., None], axis=-1)[..., 0]
            mask = batch["mask"]
            ratio = jnp.exp(lp - batch["old_logprobs"]) * mask
            adv = batch["advantages"][:, None]
            surr = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - cfg.clip_eps, 1 + cfg.clip_eps) * adv)
            denom = jnp.maximum(mask.sum(), 1.0)
            return -(surr * mask).sum() / denom

        self._train_step = make_train_step(loss_fn, self.tx)
        self._build_metrics()

    # ----------------------------------------------------------- metrics

    def _build_metrics(self):
        from ray_tpu.util.metrics import Counter, Histogram

        tags = {"model": self.model}
        self._m_tags = tags
        self._m_staleness = Histogram(
            "rl_traj_staleness",
            "Weight-version lag (learner version - trajectory version) "
            "of trajectories offered to the learner",
            boundaries=(0, 1, 2, 3, 5, 8), tag_keys=("model",))
        self._m_dropped = Counter(
            "rl_traj_dropped_total",
            "Trajectories dropped by the staleness guard",
            tag_keys=("model", "reason"))

    # ------------------------------------------------------------ update

    def filter_stale(self, trajs: list[Trajectory]
                     ) -> tuple[list[Trajectory], dict]:
        """The staleness guard. Observes rl_traj_staleness for every
        offered trajectory, drops `stale` (mixed-version) ones and ones
        more than `max_staleness` versions behind the current learner
        version; returns (kept, drop-count dict)."""
        kept: list[Trajectory] = []
        dropped = {"stale": 0, "too_old": 0}
        for t in trajs:
            lag = self.version - t.weight_version
            self._m_staleness.observe(max(0, lag), tags=self._m_tags)
            if t.stale:
                dropped["stale"] += 1
            elif lag > self.config.max_staleness:
                dropped["too_old"] += 1
            else:
                kept.append(t)
        for reason, n in dropped.items():
            if n:
                self._m_dropped.inc(
                    n, tags={"model": self.model, "reason": reason})
        return kept, dropped

    def _check_temperature(self, trajs: list[Trajectory]) -> None:
        """The loss scales logp_new by config.temperature; rollout
        logprobs were recorded at each trajectory's own τ (greedy
        records the unscaled policy log-prob, i.e. effective τ=1). A
        mismatch silently biases every importance ratio, so fail loud
        instead of training on corrupted ratios."""
        want = max(self.config.temperature, 1e-6)
        for t in trajs:
            eff = t.temperature if t.temperature > 0 else 1.0
            if abs(eff - want) > 1e-6:
                raise ValueError(
                    f"trajectory sampled at temperature {eff} but the "
                    f"learner is configured for {want}: importance "
                    f"ratios would be systematically biased — set "
                    f"RolloutConfig.temperature == "
                    f"LLMLearnerConfig.temperature")

    def update(self, trajs: list[Trajectory]) -> dict:
        """One GRPO step over a trajectory batch: staleness guard →
        group-relative advantages → jitted clipped policy-gradient
        update. Bumps the published weight version."""
        from ray_tpu.util import tracing

        t0 = time.perf_counter()
        with tracing.span("rl.learner_update"):
            kept, dropped = self.filter_stale(trajs)
            self._check_temperature(kept)
            if not kept:
                return {"skipped": True, "kept": 0,
                        "dropped_stale": dropped["stale"],
                        "dropped_too_old": dropped["too_old"]}
            adv = group_relative_advantages(kept, self.config.group_eps)
            batch = to_train_batch(kept, adv,
                                   max_len=self.cfg.block_size)
            if self.mesh is not None:
                from ray_tpu.train.spmd import batch_shardings

                batch = jax.device_put(
                    batch, batch_shardings(self.mesh, batch))
                with self.mesh:
                    self.state, metrics = self._train_step(self.state,
                                                           batch)
            else:
                self.state, metrics = self._train_step(self.state, batch)
            self.version += 1
        rewards = np.asarray([t.reward for t in kept], np.float32)
        return {
            "loss": float(np.asarray(metrics["loss"])),
            "grad_norm": float(np.asarray(metrics["grad_norm"])),
            "version": self.version,
            "kept": len(kept),
            "dropped_stale": dropped["stale"],
            "dropped_too_old": dropped["too_old"],
            "reward_mean": float(rewards.mean()),
            "reward_std": float(rewards.std()),
            "update_seconds": time.perf_counter() - t0,
        }

    # ----------------------------------------------------------- weights

    def get_weights(self):
        """Host-side float32 copy of the params pytree."""
        return jax.tree.map(np.asarray, self.state.params)

    def publish_weights(self) -> tuple[int, Any]:
        """(version, weights-or-ref) for the serving side. With a
        runtime initialized the params go through the object store —
        ONE put, every replica pulls the same ref via
        `DeploymentHandle.update_weights(version, ref)`; in-process
        callers (bench, tests) get the pytree directly."""
        import ray_tpu

        w = self.get_weights()
        if ray_tpu.is_initialized():
            return self.version, ray_tpu.put(w)
        return self.version, w

    def teacher_forced_logprobs(self, traj: Trajectory,
                                params: Any = None) -> np.ndarray:
        """Per-generated-token log-probs of `traj` under a teacher-
        forced forward at `params` (default: current learner params),
        scaled by the TRAJECTORY's own sampling temperature (greedy
        recorded the unscaled policy log-prob, so τ=0 maps to 1) —
        exactly how the engine recorded them. For a non-stale
        trajectory whose weight_version matches the params, these
        reproduce `traj.logprobs` — the determinism contract RL.md
        documents and tests gate."""
        from ray_tpu.serve.llm.runner import logprob_at

        p = self.state.params if params is None else params
        seq = np.asarray([traj.prompt + traj.tokens], np.int32)
        logits = np.asarray(
            self._forward(p, jnp.asarray(seq), self.cfg),
            np.float64)[0]
        g0 = len(traj.prompt) - 1
        # the engine records logprobs with the same shared logprob_at,
        # so the contract holds by construction
        out = [logprob_at(logits[g0 + i], tok, traj.temperature,
                          self.cfg.vocab_size)
               for i, tok in enumerate(traj.tokens)]
        return np.asarray(out, np.float64)
