"""RLFlywheel: rollout → stream → learn → hot-swap, closed.

One `iteration()` is one lap: the rollout worker samples completions
through the serve.llm engine (prefix cache serving the shared task
prefix), trajectory groups stream through the object store into the
GRPO learner as they finish, the learner takes one clipped
policy-gradient step, publishes the new weight version, and the
serving side installs it with a drain-free hot-swap — in-flight
streams keep running, tagged by version, and the next lap's rollouts
sample from the updated policy.

The learner and the engine MUST start from the same params (pass
``learner.get_weights()`` — or the same init seed's pytree — into
`LLMEngine(..., params=...)`); otherwise the first lap's importance
ratios are wrong in a way the staleness guard cannot see.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Sequence

import ray_tpu
from ray_tpu.rllib.llm.learner import LLMLearner
from ray_tpu.rllib.llm.rollout import RolloutWorker
from ray_tpu.rllib.llm.trajectory import Trajectory


@dataclasses.dataclass
class FlywheelConfig:
    # overlap: start installing weights while the NEXT batch's prompts
    # are being built? (the hot-swap itself is drain-free; rollouts in
    # flight during a swap come back version-mixed and are dropped by
    # the staleness guard — the bench does this deliberately to prove
    # zero streams drop)
    swap_during_rollout: bool = False
    # how many prompts of the NEXT batch to launch before swapping when
    # swap_during_rollout is set (keeps streams provably in flight)
    overlap_prompts: int = 2


class RLFlywheel:
    """Synchronous closed loop over (RolloutWorker, LLMLearner)."""

    def __init__(self, worker: RolloutWorker, learner: LLMLearner,
                 prompt_fn: Callable[[int], Sequence[Sequence[int]]],
                 config: FlywheelConfig | None = None):
        """`prompt_fn(iteration) -> list of token-id prompts` supplies
        each lap's prompt batch (tasks randomize digits per lap but
        share the system prefix, so the cache stays warm across
        laps)."""
        self.worker = worker
        self.learner = learner
        self.prompt_fn = prompt_fn
        self.config = config or FlywheelConfig()
        self.iteration_idx = 0
        self.history: list[dict] = []

    def _install(self, version: int, weights: Any) -> dict | list:
        if self.worker.engine is not None:
            return self.worker.engine.update_weights(version, weights)
        return self.worker.handle.update_weights(version, weights)

    def iteration(self) -> dict:
        """One lap. Returns learner metrics + rollout/swap stats."""
        from ray_tpu.util import tracing

        t0 = time.perf_counter()
        with tracing.span("rl.iteration"):
            prompts = self.prompt_fn(self.iteration_idx)
            trajs: list[Trajectory] = []
            for ref in self.worker.rollout_stream(prompts):
                group = ray_tpu.get(ref) if not isinstance(ref, list) \
                    else ref
                trajs.extend(group)
            metrics = self.learner.update(trajs)
            version, weights = self.learner.publish_weights()
            swap = None
            if not metrics.get("skipped"):
                if self.config.swap_during_rollout \
                        and self.worker.engine is not None:
                    swap = self._swap_with_streams_in_flight(
                        version, weights)
                else:
                    swap = self._install(version, weights)
        self.iteration_idx += 1
        all_rewards = [t.reward for t in trajs]
        out = dict(metrics)
        out.update({
            "iteration": self.iteration_idx,
            "rollout_reward_mean": (sum(all_rewards) / len(all_rewards))
            if all_rewards else float("nan"),
            "num_trajectories": len(trajs),
            "rollout_tokens": sum(len(t) for t in trajs),
            "swap": swap,
            "iteration_seconds": time.perf_counter() - t0,
        })
        self.history.append(out)
        return out

    def _swap_with_streams_in_flight(self, version: int,
                                     weights: Any) -> dict:
        """Prove the drain-free contract every lap: launch a few probe
        streams from the next batch's prompts, hot-swap while they
        decode, then let them finish. Their finals are checked for
        drops and version mixing (reported in the swap stats) and then
        discarded — version-mixed trajectories are what the staleness
        guard drops anyway."""
        sp = self.worker._sampling()
        probes = []
        for prompt in list(self.prompt_fn(self.iteration_idx + 1))[
                :self.config.overlap_prompts]:
            probes.append(self.worker.engine.add_request(list(prompt),
                                                         sp))
        for _ in range(2):  # streams genuinely mid-generation
            self.worker.engine.step()
        swap = self._install(version, weights)
        if swap["in_flight_streams"] < 1:
            # the probes finished before the swap landed — the lap
            # proved nothing; fail loud rather than report a vacuous
            # "zero drops" (raise the probes' max_tokens or
            # overlap_prompts so they outlive the priming steps)
            raise RuntimeError(
                "weight swap landed with zero streams in flight: the "
                "drain-free probe was vacuous")
        deadline = time.monotonic() + 120
        while any(s.final() is None for s in probes):
            if not self.worker.engine.step():
                time.sleep(0.001)
            if time.monotonic() > deadline:
                raise TimeoutError("in-flight probe stream stalled")
        finals = [s.final() for s in probes]
        swap = dict(swap)
        swap["probe_streams"] = len(finals)
        swap["probe_dropped"] = sum(
            1 for f in finals if f is None or not f.get("done"))
        swap["probe_stale"] = sum(1 for f in finals if f.get("stale"))
        return swap
