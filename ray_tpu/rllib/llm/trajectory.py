"""Token-level trajectory schema for the RL-for-LLMs flywheel.

A `Trajectory` is one completion sampled through the serve.llm engine:
the prompt, the generated tokens, the per-token log-probs under the
distribution they were sampled from, the scalar reward, and the weight
version the engine tagged the stream with. It is deliberately a plain
dataclass of primitives so it cloudpickles cheaply through the object
store (the rollout worker `ray_tpu.put`s lists of these; the learner
gets them back) and round-trips through JSON for debugging.

Version/staleness contract (RL.md): a trajectory is *on-policy for
version v* iff ``weight_version == v and not stale``. `stale` is set by
the engine when the stream spanned a weight hot-swap (tokens or the KV
they were decoded against mix versions) — such trajectories have
logprobs that no single-version teacher-forced forward reproduces, so
the learner's staleness guard drops them rather than feeding corrupted
importance ratios into the update.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class Trajectory:
    """One sampled completion, token-level."""

    prompt: list[int]
    tokens: list[int]  # generated token ids
    logprobs: list[float]  # one per generated token, at sampling time
    reward: float
    weight_version: int  # version the stream finished on
    weight_versions: list[int]  # every version that sampled a token
    stale: bool  # mixed versions (tokens or KV): see module docstring
    group_id: int  # GRPO group (all completions of one prompt)
    temperature: float  # sampling temperature (logprobs are τ-scaled)
    cached_tokens: int = 0  # prompt tokens served from the prefix cache

    @staticmethod
    def from_final(prompt: list[int], final: dict, *, reward: float,
                   group_id: int, temperature: float) -> "Trajectory":
        """Build from a serve.llm final stream event (requires the
        request to have run with ``SamplingParams(logprobs=True)``)."""
        if "logprobs" not in final:
            raise ValueError(
                "final event carries no logprobs — sample with "
                "SamplingParams(logprobs=True)")
        return Trajectory(
            prompt=[int(t) for t in prompt],
            tokens=[int(t) for t in final["token_ids"]],
            logprobs=[float(l) for l in final["logprobs"]],
            reward=float(reward),
            weight_version=int(final["weight_version"]),
            weight_versions=[int(v) for v in final["weight_versions"]],
            stale=bool(final["stale"]),
            group_id=int(group_id),
            temperature=float(temperature),
            cached_tokens=int(final.get("cached_tokens", 0)),
        )

    def __len__(self) -> int:
        return len(self.tokens)


def group_relative_advantages(trajs: list[Trajectory],
                              eps: float = 1e-6) -> np.ndarray:
    """GRPO advantages: within each group (the N completions of one
    prompt), advantage = (reward - group mean) / (group std + eps). A
    group where every completion scored the same contributes zero
    advantage — no gradient, which is exactly right (nothing to prefer).
    Returns one float per trajectory, in input order."""
    rewards = np.asarray([t.reward for t in trajs], np.float32)
    adv = np.zeros_like(rewards)
    groups: dict[int, list[int]] = {}
    for i, t in enumerate(trajs):
        groups.setdefault(t.group_id, []).append(i)
    for idx in groups.values():
        r = rewards[idx]
        adv[idx] = (r - r.mean()) / (r.std() + eps)
    return adv


def _next_pow2(n: int, lo: int) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


def to_train_batch(trajs: list[Trajectory], advantages: np.ndarray,
                   *, max_len: int, pad_token: int = 0) -> dict:
    """Pack trajectories into one padded next-token batch for the
    jitted GRPO step.

    Layout: ``inputs[b, t]`` feeds the forward whose position-``t``
    logits predict ``targets[b, t]``; ``mask[b, t]`` is 1 exactly where
    that target is a *generated* token (prompt positions and padding
    contribute no loss); ``old_logprobs`` aligns with targets/mask.
    Sequence length pads to a power of two (capped at `max_len`) and
    batch to a power of two, so compiled program count stays bounded
    the same way the serving runner buckets shapes."""
    if not trajs:
        raise ValueError("empty trajectory batch")
    seq_lens = [len(t.prompt) + len(t.tokens) for t in trajs]
    if max(seq_lens) > max_len:
        raise ValueError(
            f"trajectory of {max(seq_lens)} tokens exceeds max_len "
            f"{max_len}")
    T = min(_next_pow2(max(seq_lens), 16), max_len)
    B = _next_pow2(len(trajs), 1)
    inputs = np.full((B, T), pad_token, np.int32)
    targets = np.full((B, T), pad_token, np.int32)
    mask = np.zeros((B, T), np.float32)
    old_lp = np.zeros((B, T), np.float32)
    adv = np.zeros((B,), np.float32)
    for b, t in enumerate(trajs):
        seq = t.prompt + t.tokens
        np_seq = np.asarray(seq, np.int32)
        n = len(seq) - 1
        inputs[b, :n] = np_seq[:-1]
        targets[b, :n] = np_seq[1:]
        g0 = len(t.prompt) - 1  # first generated target position
        mask[b, g0:g0 + len(t.tokens)] = 1.0
        old_lp[b, g0:g0 + len(t.tokens)] = np.asarray(t.logprobs,
                                                      np.float32)
        adv[b] = advantages[b]
    return {"inputs": inputs, "targets": targets, "mask": mask,
            "old_logprobs": old_lp, "advantages": adv}
