"""Pluggable reward functions + toy verifiable tasks.

A reward fn has signature ``(prompt: list[int], tokens: list[int]) ->
float`` — pure, host-side, cheap. The registry lets serialized configs
name a reward by string (configs stay pure data, shippable to rollout
actors) instead of cloudpickling closures.

The toy tasks are the closed-loop demonstrators for bench_rl.py: a
reward a program can verify exactly (RLAX-style "verifiable task"), on
prompts that share a common system prefix so rollouts exercise the
serve.llm prefix cache the way real RLHF sampling does.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable

RewardFn = Callable[[list, list], float]

_REG_LOCK = threading.Lock()
# name -> reward fn; guarded_by(_REG_LOCK)
_REWARD_FNS: dict[str, RewardFn] = {}


def register_reward(name: str, fn: RewardFn) -> None:
    """Register a reward fn under `name` (idempotent re-register wins
    last; rollout actors and drivers may both import task modules)."""
    with _REG_LOCK:
        _REWARD_FNS[name] = fn


def get_reward(name: str) -> RewardFn:
    with _REG_LOCK:
        try:
            return _REWARD_FNS[name]
        except KeyError:
            raise ValueError(
                f"unknown reward {name!r}; have "
                f"{sorted(_REWARD_FNS)}") from None


@dataclasses.dataclass(frozen=True)
class DigitSumTask:
    """Verifiable toy task: the prompt is a shared system prefix
    followed by two "digit" tokens; the correct completion's FIRST
    generated token is the digit token encoding ``(a + b) % 10``.

    Digits 0..9 live at token ids ``digit_base .. digit_base+9``; the
    shared prefix occupies ``prefix_base .. prefix_base+prefix_len-1``
    (one fixed run of tokens, so every rollout prompt shares it — the
    prefix cache serves it after the first admission). Reward is
    shaped but exactly checkable: 1.0 for the correct digit, 0.1 for
    any *digit* token (the model first learns to answer in digits —
    dense signal while p(correct) is ~1/vocab — then which digit), 0.0
    otherwise."""

    prefix_len: int = 16
    prefix_base: int = 20
    digit_base: int = 2

    @property
    def prefix(self) -> list[int]:
        return [self.prefix_base + i for i in range(self.prefix_len)]

    def make_prompt(self, a: int, b: int) -> list[int]:
        if not (0 <= a <= 9 and 0 <= b <= 9):
            raise ValueError(f"digits must be 0..9, got {a}, {b}")
        return self.prefix + [self.digit_base + a, self.digit_base + b]

    def target(self, prompt: list[int]) -> int:
        a = prompt[-2] - self.digit_base
        b = prompt[-1] - self.digit_base
        return self.digit_base + (a + b) % 10

    def reward(self, prompt: list[int], tokens: list[int]) -> float:
        if not tokens:
            return 0.0
        if tokens[0] == self.target(prompt):
            return 1.0
        if self.digit_base <= tokens[0] < self.digit_base + 10:
            return 0.1
        return 0.0

    def min_vocab(self) -> int:
        return max(self.prefix_base + self.prefix_len,
                   self.digit_base + 10)


@dataclasses.dataclass(frozen=True)
class SortTask:
    """Verifiable toy task: prompt = shared prefix + k digit tokens;
    reward is the fraction of the first k generated tokens that equal
    the prompt digits sorted ascending (partial credit keeps the
    learning signal dense)."""

    k: int = 3
    prefix_len: int = 16
    prefix_base: int = 20
    digit_base: int = 2

    @property
    def prefix(self) -> list[int]:
        return [self.prefix_base + i for i in range(self.prefix_len)]

    def make_prompt(self, digits: list[int]) -> list[int]:
        if len(digits) != self.k:
            raise ValueError(f"need {self.k} digits, got {len(digits)}")
        return self.prefix + [self.digit_base + d for d in digits]

    def reward(self, prompt: list[int], tokens: list[int]) -> float:
        want = sorted(prompt[-self.k:])
        got = tokens[:self.k]
        hits = sum(1 for w, g in zip(want, got) if w == g)
        return hits / self.k

    def min_vocab(self) -> int:
        return max(self.prefix_base + self.prefix_len,
                   self.digit_base + 10)


register_reward("digit_sum", DigitSumTask().reward)
register_reward("sort", SortTask().reward)
