"""RLModule equivalent: jax policy/value networks.

Reference parity: RLModule (rllib/core/rl_module/rl_module.py:260 —
forward_inference/_exploration/_train) + the default MLP catalog
(rllib/core/models/catalog.py). Functional jax style: params are a
pytree, `forward` is pure — the same function runs under jit in the
learner (SPMD over the learner mesh) and on CPU inside env-runner
actors."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def init_mlp_policy(key, obs_dim: int, n_actions: int,
                    hidden=(64, 64)) -> dict:
    """Separate policy and value MLP towers (reference default for
    PPO-style actor-critic with vf_share_layers=False)."""

    def tower(key, sizes):
        params = []
        for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
            key, k = jax.random.split(key)
            scale = np.sqrt(2.0 / fan_in) if i < len(sizes) - 2 else 0.01
            params.append({
                "w": jax.random.normal(k, (fan_in, fan_out)) * scale,
                "b": jnp.zeros((fan_out,)),
            })
        return params

    kp, kv = jax.random.split(key)
    return {
        "pi": tower(kp, (obs_dim, *hidden, n_actions)),
        "vf": tower(kv, (obs_dim, *hidden, 1)),
    }


def _mlp(layers, x):
    for i, lyr in enumerate(layers):
        x = x @ lyr["w"] + lyr["b"]
        if i < len(layers) - 1:
            x = jnp.tanh(x)
    return x


def forward(params: dict, obs: jax.Array) -> tuple[jax.Array, jax.Array]:
    """obs (B, obs_dim) -> (logits (B, A), value (B,))."""
    logits = _mlp(params["pi"], obs)
    value = _mlp(params["vf"], obs)[..., 0]
    return logits, value


def sample_actions(params: dict, obs: jax.Array, key) -> tuple:
    """forward_exploration: sample from the categorical head."""
    logits, value = forward(params, obs)
    action = jax.random.categorical(key, logits)
    logp = jax.nn.log_softmax(logits)[jnp.arange(logits.shape[0]), action]
    return action, logp, value
