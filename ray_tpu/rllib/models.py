"""RLModule equivalent: jax policy/value networks.

Reference parity: RLModule (rllib/core/rl_module/rl_module.py:260 —
forward_inference/_exploration/_train) + the default MLP catalog
(rllib/core/models/catalog.py). Functional jax style: params are a
pytree, `forward` is pure — the same function runs under jit in the
learner (SPMD over the learner mesh) and on CPU inside env-runner
actors."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def init_mlp_policy(key, obs_dim: int, n_actions: int,
                    hidden=(64, 64)) -> dict:
    """Separate policy and value MLP towers (reference default for
    PPO-style actor-critic with vf_share_layers=False)."""

    def tower(key, sizes):
        params = []
        for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
            key, k = jax.random.split(key)
            scale = np.sqrt(2.0 / fan_in) if i < len(sizes) - 2 else 0.01
            params.append({
                "w": jax.random.normal(k, (fan_in, fan_out)) * scale,
                "b": jnp.zeros((fan_out,)),
            })
        return params

    kp, kv = jax.random.split(key)
    return {
        "pi": tower(kp, (obs_dim, *hidden, n_actions)),
        "vf": tower(kv, (obs_dim, *hidden, 1)),
    }


def _mlp(layers, x):
    for i, lyr in enumerate(layers):
        x = x @ lyr["w"] + lyr["b"]
        if i < len(layers) - 1:
            x = jnp.tanh(x)
    return x


def init_actor_critic(key, obs_shape, n_actions: int,
                      model_config: dict | None = None) -> dict:
    """Catalog-built actor-critic: a shared encoder (conv for image
    spaces, MLP for vectors — reference: catalog.py:33 encoder choice +
    the shared-trunk Atari default) with small policy/value heads."""
    from ray_tpu.rllib.catalog import Catalog, init_head

    ke, kp, kv = jax.random.split(key, 3)
    enc_params, _, dim = Catalog.build_encoder(ke, tuple(obs_shape),
                                               model_config)
    return {
        "encoder": enc_params,
        "pi_head": init_head(kp, dim, n_actions),
        "vf_head": init_head(kv, dim, 1, scale=1.0),
    }


def forward(params: dict, obs: jax.Array) -> tuple[jax.Array, jax.Array]:
    """obs (B, *obs_shape) -> (logits (B, A), value (B,)). Dispatches on
    the param-tree structure: catalog actor-critic (shared encoder) or
    the legacy separate MLP towers."""
    if "encoder" in params:
        from ray_tpu.rllib import catalog as C

        enc = params["encoder"]
        feats = (C.apply_conv_encoder(enc, obs) if "conv" in enc
                 else C.apply_mlp_encoder(enc, obs))
        logits = C.apply_head(params["pi_head"], feats)
        value = C.apply_head(params["vf_head"], feats)[..., 0]
        return logits, value
    logits = _mlp(params["pi"], obs)
    value = _mlp(params["vf"], obs)[..., 0]
    return logits, value


def sample_actions(params: dict, obs: jax.Array, key) -> tuple:
    """forward_exploration: sample from the categorical head."""
    logits, value = forward(params, obs)
    action = jax.random.categorical(key, logits)
    logp = jax.nn.log_softmax(logits)[jnp.arange(logits.shape[0]), action]
    return action, logp, value
