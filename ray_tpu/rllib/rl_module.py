"""RLModule — the neural-network abstraction of the new API stack.

Reference parity: rllib/core/rl_module/rl_module.py:260 (RLModule with
forward_inference / forward_exploration / forward_train) and
RLModuleSpec (:65 — build() from observation/action spaces + model
config). The torch nn.Module becomes a FUNCTIONAL module: params are a
jax pytree created by `init`, every forward is a pure function of
(params, batch) — so the same module runs jitted on the learner mesh and
on CPU inside env-runner actors, and weight sync is a plain pytree
broadcast instead of a state_dict copy.
"""

from __future__ import annotations

import abc
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np


class RLModule(abc.ABC):
    """Functional policy/value module. Subclasses define the param
    pytree (`init`) and the three forward passes; defaults derive
    inference (greedy) and exploration (sampled) from `forward_train`'s
    action logits."""

    @abc.abstractmethod
    def init(self, key) -> dict:
        """Create the parameter pytree."""

    @abc.abstractmethod
    def forward_train(self, params: dict, batch: dict) -> dict:
        """Training forward: returns at least {"action_dist_inputs",
        "vf_preds"} (reference: forward_train output keys)."""

    def forward_inference(self, params: dict, batch: dict) -> dict:
        """Greedy action selection (reference: forward_inference —
        deterministic, used for evaluation/serving)."""
        out = self.forward_train(params, batch)
        out["actions"] = jnp.argmax(out["action_dist_inputs"], axis=-1)
        return out

    def forward_exploration(self, params: dict, batch: dict, key) -> dict:
        """Stochastic action selection (reference: forward_exploration —
        used by env runners while sampling)."""
        out = self.forward_train(params, batch)
        logits = out["action_dist_inputs"]
        actions = jax.random.categorical(key, logits)
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(logits), actions[:, None], axis=1)[:, 0]
        out["actions"] = actions
        out["action_logp"] = logp
        return out

    # -- flat helpers for the env-runner hot loop -------------------------

    def explore(self, params, obs, key):
        """(action, logp, value) triple — the env runner's jitted
        sampling signature."""
        out = self.forward_exploration(params, {"obs": obs}, key)
        return out["actions"], out["action_logp"], out["vf_preds"]

    def infer(self, params, obs):
        out = self.forward_inference(params, {"obs": obs})
        return out["actions"]


class DefaultActorCriticModule(RLModule):
    """Catalog-backed discrete actor-critic: conv encoder for image
    spaces, MLP towers for vectors (reference: DefaultPPORLModule +
    catalog.py:33 encoder selection)."""

    def __init__(self, obs_spec, n_actions: int,
                 model_config: dict | None = None):
        from ray_tpu.rllib import models

        self.obs_spec = obs_spec
        self.n_actions = int(n_actions)
        self.model_config = dict(model_config or {})
        self.model_config.setdefault("hidden", (64, 64))
        self._models = models

    def init(self, key) -> dict:
        m = self._models
        if isinstance(self.obs_spec, tuple) and len(self.obs_spec) == 3:
            return m.init_actor_critic(key, self.obs_spec, self.n_actions,
                                       self.model_config)
        return m.init_mlp_policy(key, int(np.prod(self.obs_spec)),
                                 self.n_actions,
                                 tuple(self.model_config["hidden"]))

    def forward_train(self, params: dict, batch: dict) -> dict:
        logits, value = self._models.forward(params, batch["obs"])
        return {"action_dist_inputs": logits, "vf_preds": value}


@dataclasses.dataclass
class RLModuleSpec:
    """Build recipe (reference: RLModuleSpec — module class + spaces +
    model config, resolved inside learners and env runners so actors
    construct identical modules from plain data)."""

    module_class: type = DefaultActorCriticModule
    obs_spec: tuple | int = 4
    n_actions: int = 2
    model_config: dict | None = None

    def build(self) -> RLModule:
        return self.module_class(self.obs_spec, self.n_actions,
                                 self.model_config)
