"""DQN — the second algorithm family (off-policy, replay-buffer based).

Reference parity: rllib/algorithms/dqn (new API stack): EnvRunners
collect transitions with epsilon-greedy exploration into a replay buffer
(utils/replay_buffers/), the learner samples minibatches and applies the
(double-)DQN TD target with a periodically-synced target network; the
update is one jitted SPMD step (torch variant: dqn_torch_learner.py)."""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import models
from ray_tpu.rllib.env_runner import EnvRunnerGroup


class ReplayBuffer:
    """Uniform FIFO replay (reference: EpisodeReplayBuffer simplified to
    transition granularity)."""

    def __init__(self, capacity: int, obs_dim: int):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), np.float32)
        self.actions = np.zeros((capacity,), np.int64)
        self.rewards = np.zeros((capacity,), np.float32)
        self.dones = np.zeros((capacity,), np.bool_)
        self.size = 0
        self.pos = 0

    def add_batch(self, obs, actions, rewards, next_obs, dones):
        n = len(actions)
        idx = (self.pos + np.arange(n)) % self.capacity
        self.obs[idx] = obs
        self.actions[idx] = actions
        self.rewards[idx] = rewards
        self.next_obs[idx] = next_obs
        self.dones[idx] = dones
        self.pos = int((self.pos + n) % self.capacity)
        self.size = int(min(self.size + n, self.capacity))

    def __len__(self):
        return self.size

    def sample(self, batch_size: int, rng: np.random.RandomState) -> dict:
        idx = rng.randint(0, self.size, batch_size)
        return {
            "obs": self.obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "next_obs": self.next_obs[idx],
            "dones": self.dones[idx].astype(np.float32),
        }


from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig


@dataclasses.dataclass
class DQNConfig(AlgorithmConfig):
    rollout_fragment_length: int = 16
    lr: float = 5e-4
    buffer_capacity: int = 50_000
    train_batch_size: int = 64
    num_steps_sampled_before_learning: int = 1000
    target_update_freq: int = 500  # learner updates between target syncs
    updates_per_iteration: int = 32
    double_q: bool = True
    epsilon_initial: float = 1.0
    epsilon_final: float = 0.05
    epsilon_decay_steps: int = 10_000
    # proportional prioritized replay (reference: PER via segment trees,
    # rllib/execution/segment_tree.py + prioritized_episode_buffer)
    prioritized_replay: bool = False
    per_alpha: float = 0.6
    per_beta: float = 0.4

    def build(self) -> "DQN":
        return DQN(self)


class DQN(Algorithm):
    """Epsilon-greedy sampling rides the PPO env-runner machinery: the
    runner samples with a stochastic policy head; DQN overrides sampled
    actions toward greedy as epsilon decays by syncing a temperature-less
    Q-head (the categorical over Q-logits acts as exploration — with
    epsilon mixed in on the learner-side weight sync)."""

    config_class = DQNConfig
    STATE_COMPONENTS = ("params", "target_params", "opt_state",
                        "_env_steps", "_updates", "_iteration",
                        "_timesteps_total")

    def setup(self, config: DQNConfig):
        import gymnasium as gym

        probe = gym.make(config.env)
        self.obs_dim = int(np.prod(probe.observation_space.shape))
        self.n_actions = int(probe.action_space.n)
        probe.close()

        key = jax.random.PRNGKey(config.seed)
        self.params = models.init_mlp_policy(
            key, self.obs_dim, self.n_actions, config.hidden)
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.tx = optax.adam(config.lr)
        self.opt_state = self.tx.init(self.params)
        if config.prioritized_replay:
            from ray_tpu.rllib.replay import PrioritizedReplayBuffer

            self.buffer = PrioritizedReplayBuffer(
                config.buffer_capacity, alpha=config.per_alpha,
                beta=config.per_beta, seed=config.seed)
        else:
            self.buffer = ReplayBuffer(config.buffer_capacity, self.obs_dim)
        self._rng = np.random.RandomState(config.seed)
        self._env_steps = 0
        self._updates = 0

        self.env_runner_group = EnvRunnerGroup(
            num_env_runners=config.num_env_runners,
            remote=config.num_env_runners > 0,
            env=config.env,
            num_envs=config.num_envs_per_env_runner,
            rollout_fragment_length=config.rollout_fragment_length,
            seed=config.seed,
            hidden=config.hidden,
        )

        cfg = config

        def td_loss(params, target_params, batch):
            q = models.forward(params, batch["obs"])[0]  # pi head = Q values
            q_taken = jnp.take_along_axis(
                q, batch["actions"][:, None], axis=1)[:, 0]
            q_next_target = models.forward(target_params,
                                           batch["next_obs"])[0]
            if cfg.double_q:
                q_next_online = models.forward(params, batch["next_obs"])[0]
                best = jnp.argmax(q_next_online, axis=1)
                q_next = jnp.take_along_axis(
                    q_next_target, best[:, None], axis=1)[:, 0]
            else:
                q_next = jnp.max(q_next_target, axis=1)
            target = batch["rewards"] + cfg.gamma * (1 - batch["dones"]) \
                * q_next
            td = q_taken - jax.lax.stop_gradient(target)
            huber = jnp.where(jnp.abs(td) < 1.0, 0.5 * td ** 2,
                              jnp.abs(td) - 0.5)
            # importance weights correct the PER sampling bias (uniform
            # replay passes ones)
            return jnp.mean(batch["weights"] * huber), td

        def update(params, opt_state, target_params, batch):
            (loss, td), grads = jax.value_and_grad(td_loss, has_aux=True)(
                params, target_params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td

        self._update = jax.jit(update, donate_argnums=(0, 1))
        self._sync_runner_weights()

    # -- exploration -----------------------------------------------------

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._env_steps / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final -
                                             cfg.epsilon_initial)

    def _sync_runner_weights(self):
        """Scale Q-logits so the runner's categorical sampling acts
        epsilon-greedy-ish: low epsilon -> sharp (greedy) distribution."""
        eps = max(self._epsilon(), 1e-3)
        sharpness = 1.0 / eps
        w = jax.tree.map(np.asarray, self.params)
        last = w["pi"][-1]
        w["pi"][-1] = {"w": last["w"] * sharpness, "b": last["b"] * sharpness}
        self.env_runner_group.sync_weights(w)

    # -- training --------------------------------------------------------

    def training_step(self) -> dict:
        cfg = self.config
        t0 = time.perf_counter()
        samples = self.env_runner_group.sample()
        ep_returns, env_steps = [], 0
        for s in samples:
            # transitions (o_t, a_t, r_t, o_{t+1}): the final step of a
            # fragment has no in-fragment successor — drop it (1/T of
            # data) rather than fabricate one
            # drop autoreset steps: their action was ignored by the env
            # and their successor belongs to the next episode (done-step
            # pairs stay — done=1 already masks their bootstrap)
            rm = s["reset_mask"]
            valid = (~rm[:-1]).reshape(-1)
            obs = s["obs"][:-1].reshape(-1, s["obs"].shape[-1])[valid]
            nxt = s["obs"][1:].reshape(-1, s["obs"].shape[-1])[valid]
            acts = s["actions"][:-1].reshape(-1)[valid]
            rews = s["rewards"][:-1].reshape(-1)[valid]
            dns = s["dones"][:-1].reshape(-1)[valid]
            if cfg.prioritized_replay:
                self.buffer.add_batch({
                    "obs": obs, "actions": acts, "rewards": rews,
                    "next_obs": nxt, "dones": dns.astype(np.float32),
                })
            else:
                self.buffer.add_batch(obs, acts, rews, nxt, dns)
            env_steps += s["env_steps"]
            if s["num_episodes"]:
                ep_returns.append(s["episode_return_mean"])
        self._env_steps += env_steps

        losses = []
        if len(self.buffer) >= cfg.num_steps_sampled_before_learning:
            for _ in range(cfg.updates_per_iteration):
                if cfg.prioritized_replay:
                    batch = self.buffer.sample(cfg.train_batch_size)
                    idxs = batch.pop("idxs")
                else:
                    batch = self.buffer.sample(cfg.train_batch_size,
                                               self._rng)
                    batch["weights"] = np.ones(
                        len(batch["actions"]), np.float32)
                    idxs = None
                batch = {k: jnp.asarray(v) for k, v in batch.items()}
                self.params, self.opt_state, loss, td = self._update(
                    self.params, self.opt_state, self.target_params, batch)
                if idxs is not None:
                    self.buffer.update_priorities(idxs, np.asarray(td))
                losses.append(float(loss))
                self._updates += 1
                if self._updates % cfg.target_update_freq == 0:
                    self.target_params = jax.tree.map(jnp.copy, self.params)
        self._sync_runner_weights()
        dt = time.perf_counter() - t0
        return {
            "episode_return_mean": float(np.mean(ep_returns))
            if ep_returns else float("nan"),
            "num_env_steps_sampled_lifetime": self._env_steps,
            "env_steps_per_sec": env_steps / dt,
            "epsilon": self._epsilon(),
            "learner/td_loss": float(np.mean(losses)) if losses
            else float("nan"),
            "buffer_size": len(self.buffer),
        }

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def cleanup(self):
        self.env_runner_group.shutdown()
