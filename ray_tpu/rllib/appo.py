"""APPO — asynchronous PPO (IMPALA architecture + clipped surrogate).

Reference parity: rllib/algorithms/appo/appo.py (APPOConfig: IMPALA's
async sampling/learner pipeline with the PPO clipped-ratio loss,
optional KL penalty against a periodically-updated TARGET network —
appo.py:36 docstring, target_network_update_freq, use_kl_loss). Built on
ray_tpu's IMPALA driver: same env-runner/queue/learner-thread plumbing,
the jitted update swapped for the APPO loss.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import models
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig


@dataclasses.dataclass
class APPOConfig(IMPALAConfig):
    clip_param: float = 0.2
    use_kl_loss: bool = False
    kl_coeff: float = 0.2
    target_update_freq: int = 20  # learner steps between target syncs
    lr: float = 3e-4

    def build(self) -> "APPO":
        return APPO(self)


class APPO(IMPALA):
    def __init__(self, config: APPOConfig):
        super().__init__(config)
        cfg = config
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self._appo_updates = 0

        def loss_fn(params, target_params, batch):
            logits, value = models.forward(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=1)[:, 0]
            # clipped surrogate against the BEHAVIOR policy's logp (the
            # sample is off-policy; V-trace already corrected the targets)
            ratio = jnp.exp(logp - batch["logp_old"])
            adv = batch["advantages"]
            surr = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - cfg.clip_param,
                         1 + cfg.clip_param) * adv)
            m = batch["mask"]  # autoreset steps carry no loss
            denom = jnp.maximum(jnp.sum(m), 1.0)
            pg = -jnp.sum(m * surr) / denom
            vf = jnp.sum(m * (value - batch["vs"]) ** 2) / denom
            ent = -jnp.sum(m * jnp.sum(
                jnp.exp(logp_all) * logp_all, axis=-1)) / denom
            total = pg + cfg.vf_loss_coeff * vf - cfg.entropy_coeff * ent
            if cfg.use_kl_loss:
                t_logits, _ = models.forward(target_params, batch["obs"])
                t_logp_all = jax.nn.log_softmax(t_logits)
                kl = jnp.sum(m * jnp.sum(
                    jnp.exp(t_logp_all) * (t_logp_all - logp_all),
                    axis=-1)) / denom
                total = total + cfg.kl_coeff * kl
            return total

        def step(params, opt_state, target_params, batch):
            loss, grads = jax.value_and_grad(loss_fn)(
                params, target_params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._appo_step = jax.jit(step)

        def update(params, opt_state, batch):
            new_params, new_opt, loss = self._appo_step(
                params, opt_state, self.target_params, batch)
            self._appo_updates += 1
            if self._appo_updates % cfg.target_update_freq == 0:
                self.target_params = jax.tree.map(jnp.copy, new_params)
            return new_params, new_opt, loss

        self._update = update  # the learner thread calls this
