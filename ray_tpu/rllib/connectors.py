"""Connector pipelines — env→module and learner-side batch transforms.

Reference parity: ConnectorV2 (rllib/connectors/connector_v2.py:31) and
the pipeline container (connector_pipeline_v2.py): small composable
pieces that reshape raw env observations into module inputs
(frame-stacking, normalization, flattening) and enrich train batches in
the learner (GAE — rllib/connectors/learner/
general_advantage_estimation.py). Functional numpy on the env side
(runs in env-runner actors per step), the learner connector feeds the
jitted update.
"""

from __future__ import annotations

import numpy as np


class ConnectorV2:
    """One batch transform. Env-side connectors receive the vectorized
    observation batch (N, ...) plus the `dones` mask from the previous
    step so stateful connectors (FrameStack) can reset per-env state."""

    def __call__(self, obs: np.ndarray, dones=None) -> np.ndarray:
        raise NotImplementedError

    def output_shape(self, in_shape: tuple) -> tuple:
        return tuple(in_shape)

    def reset(self, num_envs: int):
        """Called once when the vector env is (re)built."""


class ConnectorPipeline(ConnectorV2):
    """Reference: ConnectorPipelineV2 — connectors applied in order."""

    def __init__(self, connectors):
        self.connectors = list(connectors)

    def __call__(self, obs, dones=None):
        for c in self.connectors:
            obs = c(obs, dones)
        return obs

    def output_shape(self, in_shape):
        for c in self.connectors:
            in_shape = c.output_shape(in_shape)
        return tuple(in_shape)

    def reset(self, num_envs: int):
        for c in self.connectors:
            c.reset(num_envs)


class NormalizeImage(ConnectorV2):
    """uint8 pixels -> float32 in [0, 1] (the standard Atari prep)."""

    def __call__(self, obs, dones=None):
        return np.asarray(obs, np.float32) / 255.0


class FlattenObs(ConnectorV2):
    def __call__(self, obs, dones=None):
        return np.asarray(obs, np.float32).reshape(obs.shape[0], -1)

    def output_shape(self, in_shape):
        return (int(np.prod(in_shape)),)


class FrameStack(ConnectorV2):
    """Stack the last k frames on the channel axis (reference:
    the frame-stacking connector used by the Atari PPO benchmark,
    rllib/examples/connectors/frame_stacking.py). Per-env state, aware
    of gymnasium's NEXT-STEP autoreset: the step where done=True still
    returns the ending episode's final frame (shifted in normally); the
    fresh reset frame arrives one step later, and THAT is where the done
    env's stack restarts — `dones` is the previous step's done mask, so
    it marks exactly the envs whose current obs is a reset frame."""

    def __init__(self, k: int = 4):
        self.k = k
        self._stacks = None  # (N, H, W, C*k)

    def reset(self, num_envs: int):
        self._stacks = None

    def __call__(self, obs, dones=None):
        obs = np.asarray(obs)
        n, h, w, c = obs.shape
        # frame-major layout [f0|f1|...]: np.tile repeats WHOLE frames,
        # matching the shift path; np.repeat would interleave channels
        # and scramble multi-channel stacks
        if self._stacks is None or self._stacks.shape[0] != n:
            self._stacks = np.tile(obs, (1, 1, 1, self.k))
        else:
            shifted = np.concatenate([self._stacks[..., c:], obs], axis=-1)
            if dones is not None and dones.any():
                # obs[dones] is the new episode's FIRST frame (next-step
                # autoreset): restart those stacks, don't mix episodes
                shifted[dones] = np.tile(obs[dones], (1, 1, 1, self.k))
            self._stacks = shifted
        return self._stacks.copy()

    def output_shape(self, in_shape):
        h, w, c = in_shape
        return (h, w, c * self.k)


def default_env_to_module(obs_shape, framestack: int = 1):
    """Default pipeline by obs space (reference: the default
    env-to-module connector assembly, connector_pipeline_v2.py)."""
    if len(obs_shape) == 3:
        pipe = [NormalizeImage()]
        if framestack > 1:
            pipe.append(FrameStack(framestack))
        return ConnectorPipeline(pipe)
    return ConnectorPipeline([FlattenObs()])


class GeneralAdvantageEstimation:
    """Learner connector: adds advantages/value_targets to a rollout
    sample (reference:
    rllib/connectors/learner/general_advantage_estimation.py)."""

    def __init__(self, gamma: float, lambda_: float):
        self.gamma = gamma
        self.lambda_ = lambda_

    def __call__(self, sample: dict) -> dict:
        from ray_tpu.rllib.learner import compute_gae

        adv, targets = compute_gae(
            sample["rewards"], sample["values"], sample["dones"],
            sample["last_values"], self.gamma, self.lambda_)
        out = dict(sample)
        out["advantages"] = adv
        out["value_targets"] = targets
        return out
