"""Catalog — obs-space-driven encoder construction.

Reference parity: rllib/core/models/catalog.py:33 (Catalog decides the
encoder family from the observation space: CNN for image spaces, MLP for
vectors) and the default Atari conv stack from models/utils.py. Here the
encoder is a pure-functional jax (init, apply) pair: conv layers run as
`lax.conv_general_dilated` in NHWC — channels-last keeps the channel
dim on the TPU lane axis so XLA tiles the implicit GEMMs onto the MXU.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ConvLayer:
    """One conv layer; `stride` is STATIC pytree metadata (it shapes the
    compiled program, it is not a trainable leaf)."""

    w: jax.Array
    b: jax.Array
    stride: int = dataclasses.field(metadata={"static": True})

# (out_channels, kernel, stride)
ATARI_FILTERS = ((32, 8, 4), (64, 4, 2), (64, 3, 1))
SMALL_FILTERS = ((16, 3, 2), (32, 3, 2))


def conv_filters_for(obs_shape) -> tuple:
    """Default filter spec by input resolution (reference:
    catalog._get_encoder_config image branch)."""
    h = obs_shape[0]
    return ATARI_FILTERS if h >= 64 else SMALL_FILTERS


def init_conv_encoder(key, obs_shape, filters=None, out_dim: int = 256):
    """Params for conv stack + dense projection. obs NHWC float32."""
    filters = filters or conv_filters_for(obs_shape)
    h, w, c = obs_shape
    params = {"conv": [], "proj": None}
    for (oc, k, s) in filters:
        key, sub = jax.random.split(key)
        fan_in = k * k * c
        params["conv"].append(ConvLayer(
            w=jax.random.normal(sub, (k, k, c, oc)) * np.sqrt(2.0 / fan_in),
            b=jnp.zeros((oc,)),
            stride=int(s),
        ))
        h = -(-h // s)
        w = -(-w // s)
        c = oc
    flat = h * w * c
    key, sub = jax.random.split(key)
    params["proj"] = {
        "w": jax.random.normal(sub, (flat, out_dim)) * np.sqrt(2.0 / flat),
        "b": jnp.zeros((out_dim,)),
    }
    return params, out_dim


def apply_conv_encoder(params, obs):
    """obs (B, H, W, C) float32 -> features (B, out_dim)."""
    x = obs
    for lyr in params["conv"]:
        x = jax.lax.conv_general_dilated(
            x, lyr.w, window_strides=(lyr.stride, lyr.stride),
            padding="SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
        x = jax.nn.relu(x + lyr.b)
    x = x.reshape(x.shape[0], -1)
    p = params["proj"]
    return jax.nn.relu(x @ p["w"] + p["b"])


def init_mlp_encoder(key, in_dim: int, hidden=(64, 64)):
    sizes = (in_dim, *hidden)
    layers = []
    for fan_in, fan_out in zip(sizes[:-1], sizes[1:]):
        key, sub = jax.random.split(key)
        layers.append({
            "w": jax.random.normal(sub, (fan_in, fan_out)) *
            np.sqrt(2.0 / fan_in),
            "b": jnp.zeros((fan_out,)),
        })
    return {"mlp": layers}, (hidden[-1] if hidden else in_dim)


def apply_mlp_encoder(params, obs):
    x = obs
    for lyr in params["mlp"]:
        x = jnp.tanh(x @ lyr["w"] + lyr["b"])
    return x


def init_head(key, in_dim: int, out_dim: int, scale: float = 0.01):
    return {"w": jax.random.normal(key, (in_dim, out_dim)) * scale,
            "b": jnp.zeros((out_dim,))}


def apply_head(params, x):
    return x @ params["w"] + params["b"]


class Catalog:
    """Encoder/head factory keyed on the observation shape (reference:
    Catalog.build_encoder, core/models/catalog.py:33)."""

    @staticmethod
    def is_image(obs_shape) -> bool:
        return len(obs_shape) == 3

    @staticmethod
    def build_encoder(key, obs_shape, model_config=None):
        """Returns (params, apply_fn, feature_dim)."""
        mc = model_config or {}
        if Catalog.is_image(obs_shape):
            params, dim = init_conv_encoder(
                key, obs_shape, filters=mc.get("conv_filters"),
                out_dim=mc.get("conv_out", 256))
            return params, apply_conv_encoder, dim
        in_dim = int(np.prod(obs_shape))
        params, dim = init_mlp_encoder(key, in_dim,
                                       hidden=mc.get("hidden", (64, 64)))
        return params, apply_mlp_encoder, dim
