"""MetricsLogger — hierarchical, windowed metric reduction.

Reference parity: rllib/utils/metrics/metrics_logger.py (nested key
paths, per-key reduce method + sliding window, lifetime sums via
reduce=sum with window=None) — the structured replacement for flat
per-iteration scalar dicts (VERDICT r2 weak item 9).
"""

from __future__ import annotations

from collections import deque
from typing import Any


class _Stat:
    """window=None keeps O(1) LIFETIME accumulators per reduce kind —
    never an unbounded value list."""

    __slots__ = ("values", "reduce", "lifetime", "count", "windowed")

    def __init__(self, reduce: str, window: int | None):
        self.reduce = reduce
        self.windowed = window is not None
        self.values = deque(maxlen=window) if self.windowed else None
        self.lifetime = (float("inf") if reduce == "min"
                         else float("-inf") if reduce == "max" else 0.0)
        self.count = 0


def _to_path(key) -> tuple:
    if isinstance(key, tuple):
        return key
    if isinstance(key, str) and "/" in key:
        return tuple(key.split("/"))
    return (key,)


class MetricsLogger:
    def __init__(self):
        self._stats: dict[tuple, _Stat] = {}

    def log_value(self, key, value, reduce: str = "mean",
                  window: int | None = 100):
        """reduce in {mean, sum, min, max}; window=None means LIFETIME
        (reference: lifetime stats) — tracked with O(1) accumulators,
        never an unbounded buffer."""
        path = _to_path(key)
        st = self._stats.get(path)
        if st is None:
            st = self._stats[path] = _Stat(reduce, window)
        v = float(value)
        if not st.windowed:
            st.count += 1
            if st.reduce == "min":
                st.lifetime = min(st.lifetime, v)
            elif st.reduce == "max":
                st.lifetime = max(st.lifetime, v)
            else:  # sum and mean both accumulate a running sum
                st.lifetime += v
            return
        st.values.append(v)

    def log_dict(self, metrics: dict, key=None, **kwargs):
        prefix = _to_path(key) if key is not None else ()
        for k, v in metrics.items():
            if isinstance(v, dict):
                self.log_dict(v, key=prefix + _to_path(k), **kwargs)
            else:
                self.log_value(prefix + _to_path(k), v, **kwargs)

    def peek(self, key) -> Any:
        return self._reduce_one(self._stats[_to_path(key)])

    @staticmethod
    def _reduce_one(st: _Stat):
        if not st.windowed:
            if st.count == 0:
                return float("nan")
            if st.reduce == "mean":
                return st.lifetime / st.count
            return st.lifetime
        if st.reduce == "sum":
            return float(sum(st.values))
        if not st.values:
            return float("nan")
        if st.reduce == "mean":
            return float(sum(st.values) / len(st.values))
        if st.reduce == "min":
            return float(min(st.values))
        if st.reduce == "max":
            return float(max(st.values))
        raise ValueError(f"unknown reduce {st.reduce!r}")

    def reduce(self) -> dict:
        """Nested dict of reduced values (the per-iteration result)."""
        out: dict = {}
        for path, st in self._stats.items():
            node = out
            for p in path[:-1]:
                node = node.setdefault(p, {})
            node[path[-1]] = self._reduce_one(st)
        return out
