"""SAC — off-policy continuous control (squashed-Gaussian actor, twin Q).

Reference parity: rllib/algorithms/sac (sac.py SACConfig, the torch
learner's twin-Q + tanh-Gaussian policy + auto-tuned entropy
temperature, default_sac_rl_module). Functional jax: one jitted update
performs the critic, actor and temperature steps; target critics track
by polyak averaging. Continuous action spaces (Box); replay is the
uniform ring or the prioritized buffer.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


def _mlp_init(key, sizes, out_scale=1.0):
    layers = []
    for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, k = jax.random.split(key)
        scale = np.sqrt(2.0 / a) if i < len(sizes) - 2 else out_scale
        layers.append({"w": jax.random.normal(k, (a, b)) * scale,
                       "b": jnp.zeros((b,))})
    return layers


def _mlp(layers, x):
    for i, lyr in enumerate(layers):
        x = x @ lyr["w"] + lyr["b"]
        if i < len(layers) - 1:
            x = jax.nn.relu(x)
    return x


def init_sac_params(key, obs_dim: int, act_dim: int, hidden=(256, 256)):
    kp, k1, k2 = jax.random.split(key, 3)
    return {
        "pi": _mlp_init(kp, (obs_dim, *hidden, 2 * act_dim), 0.01),
        "q1": _mlp_init(k1, (obs_dim + act_dim, *hidden, 1)),
        "q2": _mlp_init(k2, (obs_dim + act_dim, *hidden, 1)),
    }


def sample_action(params, obs, key):
    """Squashed Gaussian: a = tanh(mu + std*eps); returns (a, logp)."""
    out = _mlp(params["pi"], obs)
    mu, log_std = jnp.split(out, 2, axis=-1)
    log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
    std = jnp.exp(log_std)
    eps = jax.random.normal(key, mu.shape)
    pre = mu + std * eps
    a = jnp.tanh(pre)
    logp = jnp.sum(
        -0.5 * (eps ** 2 + 2 * log_std + np.log(2 * np.pi))
        - jnp.log(1 - a ** 2 + 1e-6), axis=-1)
    return a, logp


def q_values(params, obs, act):
    x = jnp.concatenate([obs, act], axis=-1)
    return _mlp(params["q1"], x)[..., 0], _mlp(params["q2"], x)[..., 0]


from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig


@dataclasses.dataclass
class SACConfig(AlgorithmConfig):
    env: str = "Pendulum-v1"
    num_envs: int = 8
    rollout_fragment_length: int = 8
    tau: float = 0.005  # polyak
    buffer_capacity: int = 100_000
    train_batch_size: int = 256
    num_steps_sampled_before_learning: int = 1500
    updates_per_iteration: int = 16
    hidden: tuple = (256, 256)
    initial_alpha: float = 1.0
    target_entropy: float | None = None  # default: -act_dim

    def build(self) -> "SAC":
        return SAC(self)


class SAC(Algorithm):
    config_class = SACConfig
    STATE_COMPONENTS = ("params", "target_q", "log_alpha",
                        "_env_steps", "_iteration", "_timesteps_total")

    def setup(self, config: SACConfig):
        if config.evaluation_interval:
            raise ValueError(
                "SAC has no separate evaluation runner — "
                "episode_return_mean from training IS the "
                "evaluation surface; unset evaluation_interval")
        import gymnasium as gym

        cfg = config
        self.envs = gym.make_vec(cfg.env, num_envs=cfg.num_envs)
        space = self.envs.single_action_space
        self.obs_dim = int(np.prod(self.envs.single_observation_space.shape))
        self.act_dim = int(np.prod(space.shape))
        self._act_low = np.asarray(space.low, np.float32)
        self._act_high = np.asarray(space.high, np.float32)
        target_entropy = (cfg.target_entropy if cfg.target_entropy is not None
                          else -float(self.act_dim))

        key = jax.random.PRNGKey(cfg.seed)
        self.params = init_sac_params(key, self.obs_dim, self.act_dim,
                                      cfg.hidden)
        self.target_q = {"q1": jax.tree.map(jnp.copy, self.params["q1"]),
                         "q2": jax.tree.map(jnp.copy, self.params["q2"])}
        self.log_alpha = jnp.asarray(np.log(cfg.initial_alpha), jnp.float32)
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        self.alpha_tx = optax.adam(cfg.lr)
        self.alpha_opt = self.alpha_tx.init(self.log_alpha)

        from ray_tpu.rllib.replay import PrioritizedReplayBuffer

        self.buffer = PrioritizedReplayBuffer(cfg.buffer_capacity,
                                              alpha=0.0, seed=cfg.seed)

        def critic_loss(params, target_q, log_alpha, batch, key):
            next_a, next_logp = sample_action(params, batch["next_obs"], key)
            tq1 = _mlp(target_q["q1"],
                       jnp.concatenate([batch["next_obs"], next_a], -1))[..., 0]
            tq2 = _mlp(target_q["q2"],
                       jnp.concatenate([batch["next_obs"], next_a], -1))[..., 0]
            alpha = jnp.exp(log_alpha)
            target = batch["rewards"] + cfg.gamma * (1 - batch["dones"]) * (
                jnp.minimum(tq1, tq2) - alpha * next_logp)
            q1, q2 = q_values(params, batch["obs"], batch["actions"])
            target = jax.lax.stop_gradient(target)
            return jnp.mean((q1 - target) ** 2 + (q2 - target) ** 2)

        def actor_loss(params, log_alpha, batch, key):
            a, logp = sample_action(params, batch["obs"], key)
            q1, q2 = q_values(params, batch["obs"], a)
            alpha = jax.lax.stop_gradient(jnp.exp(log_alpha))
            return jnp.mean(alpha * logp - jnp.minimum(q1, q2)), logp

        def update(params, opt_state, target_q, log_alpha, alpha_opt,
                   batch, key):
            kc, ka = jax.random.split(key)
            c_loss, c_grads = jax.value_and_grad(critic_loss)(
                params, target_q, log_alpha, batch, kc)
            (a_loss, logp), a_grads = jax.value_and_grad(
                actor_loss, has_aux=True)(params, log_alpha, batch, ka)
            # actor grads touch only pi; critic grads touch only q1/q2 —
            # merge per-subtree so each step is its textbook update
            grads = {"pi": a_grads["pi"], "q1": c_grads["q1"],
                     "q2": c_grads["q2"]}
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            # temperature: push entropy toward the target
            al_grad = jax.grad(
                lambda la: -jnp.mean(
                    la * jax.lax.stop_gradient(logp + target_entropy))
            )(log_alpha)
            al_up, alpha_opt = self.alpha_tx.update(al_grad, alpha_opt)
            log_alpha = optax.apply_updates(log_alpha, al_up)
            target_q = jax.tree.map(
                lambda t, o: (1 - cfg.tau) * t + cfg.tau * o,
                target_q, {"q1": params["q1"], "q2": params["q2"]})
            return (params, opt_state, target_q, log_alpha, alpha_opt,
                    c_loss, a_loss)

        self._update = jax.jit(update)
        self._sample_fn = jax.jit(sample_action)
        self._key = jax.random.PRNGKey(cfg.seed + 1)
        self.obs, _ = self.envs.reset(seed=cfg.seed)
        # next-step autoreset: the step after done has an ignored action
        # and bridges two episodes — never store it (it would poison the
        # replay buffer with fabricated transitions)
        self._prev_done = np.zeros(cfg.num_envs, np.bool_)
        self._ep_returns = np.zeros(cfg.num_envs)
        self._completed: list[float] = []
        self._env_steps = 0

    def _scale(self, a: np.ndarray) -> np.ndarray:
        return self._act_low + (a + 1.0) * 0.5 * (self._act_high -
                                                  self._act_low)

    def training_step(self) -> dict:
        cfg = self.config
        t0 = time.perf_counter()
        for _ in range(cfg.rollout_fragment_length):
            self._key, k = jax.random.split(self._key)
            a, _ = self._sample_fn(self.params,
                                   self.obs.astype(np.float32), k)
            a = np.asarray(a)
            nxt, rew, term, trunc, _ = self.envs.step(self._scale(a))
            done = np.logical_or(term, trunc)
            valid = ~self._prev_done
            if valid.any():
                self.buffer.add_batch({
                    "obs": self.obs[valid].astype(np.float32),
                    "actions": a[valid],
                    "rewards": np.asarray(rew, np.float32)[valid],
                    "next_obs": nxt[valid].astype(np.float32),
                    # truncation bootstraps
                    "dones": term[valid].astype(np.float32),
                })
            self._prev_done = done
            self._ep_returns += rew
            for i in np.nonzero(done)[0]:
                self._completed.append(float(self._ep_returns[i]))
                self._ep_returns[i] = 0.0
            self.obs = nxt
            self._env_steps += cfg.num_envs

        c_losses, a_losses = [], []
        if len(self.buffer) >= cfg.num_steps_sampled_before_learning:
            for _ in range(cfg.updates_per_iteration):
                batch = self.buffer.sample(cfg.train_batch_size)
                batch.pop("idxs")
                batch.pop("weights")
                batch = {k: jnp.asarray(v) for k, v in batch.items()}
                self._key, k = jax.random.split(self._key)
                (self.params, self.opt_state, self.target_q,
                 self.log_alpha, self.alpha_opt, cl, al) = self._update(
                    self.params, self.opt_state, self.target_q,
                    self.log_alpha, self.alpha_opt, batch, k)
                c_losses.append(float(cl))
                a_losses.append(float(al))

        window = self._completed[-100:]
        self._completed = window
        dt = time.perf_counter() - t0
        return {
            "episode_return_mean": float(np.mean(window)) if window
            else float("nan"),
            "num_env_steps_sampled_lifetime": self._env_steps,
            "env_steps_per_sec": cfg.rollout_fragment_length *
            cfg.num_envs / dt,
            "alpha": float(np.exp(self.log_alpha)),
            "learner/critic_loss": float(np.mean(c_losses)) if c_losses
            else float("nan"),
            "learner/actor_loss": float(np.mean(a_losses)) if a_losses
            else float("nan"),
        }

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def evaluate(self) -> dict:
        # SAC's env loop is continuous-action and lives in the driver —
        # the base's discrete eval runner does not apply
        raise NotImplementedError(
            "SAC evaluation rides episode_return_mean from training")

    def cleanup(self):
        self.envs.close()
