"""ray_tpu.rllib — reinforcement learning on the actor runtime.

Reference parity: rllib/ new API stack — EnvRunner actors sampling
gymnasium vector envs (env/single_agent_env_runner.py:64), a Learner
whose update is a jitted SPMD program over a jax mesh
(core/learner/learner.py:109, torch DDP wrap replaced by GSPMD), and
Algorithm drivers starting with PPO (algorithms/ppo/ppo.py:389).
"""

from ray_tpu.rllib.dqn import DQN, DQNConfig, ReplayBuffer
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig, vtrace
from ray_tpu.rllib.env_runner import EnvRunnerGroup, SingleAgentEnvRunner
from ray_tpu.rllib.learner import PPOLearner, PPOLearnerConfig, compute_gae
from ray_tpu.rllib.ppo import PPO, PPOConfig

__all__ = [
    "DQN",
    "DQNConfig",
    "IMPALA",
    "IMPALAConfig",
    "vtrace",
    "EnvRunnerGroup",
    "PPO",
    "PPOConfig",
    "PPOLearner",
    "PPOLearnerConfig",
    "ReplayBuffer",
    "SingleAgentEnvRunner",
    "compute_gae",
]
