"""ray_tpu.rllib — reinforcement learning on the actor runtime.

Reference parity: rllib/ new API stack — EnvRunner actors sampling
gymnasium vector envs (env/single_agent_env_runner.py:64), connector
pipelines (connectors/connector_v2.py:31), a catalog choosing conv/MLP
encoders from the obs space (core/models/catalog.py:33), a Learner whose
update is a jitted SPMD program over a jax mesh (core/learner/
learner.py:109, torch DDP wrap replaced by GSPMD), prioritized replay
(execution/segment_tree.py), hierarchical metrics
(utils/metrics/metrics_logger.py), offline RL (offline_data.py:22 —
recording, BC, MARWIL), multi-agent (multi_rl_module.py:49 +
MultiAgentEnv), and nine algorithm families: PPO, APPO, IMPALA,
DQN (+PER), SAC, CQL, DreamerV3, BC, MARWIL.

RL for LLMs lives in the `ray_tpu.rllib.llm` subpackage (the
serve.llm-engine-as-rollout-actor flywheel: GRPO learner, streamed
trajectories, drain-free weight hot-swap — see RL.md). It is imported
lazily: ``import ray_tpu.rllib.llm`` pulls in the serving stack, which
plain env-RL users should not pay for.
"""

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.appo import APPO, APPOConfig
from ray_tpu.rllib.catalog import Catalog
from ray_tpu.rllib.cql import CQL, CQLConfig, record_continuous_experiences
from ray_tpu.rllib.connectors import (
    ConnectorPipeline,
    ConnectorV2,
    FlattenObs,
    FrameStack,
    GeneralAdvantageEstimation,
    NormalizeImage,
)
from ray_tpu.rllib.dqn import DQN, DQNConfig, ReplayBuffer
from ray_tpu.rllib.dreamerv3 import DreamerV3, DreamerV3Config
from ray_tpu.rllib.env_runner import EnvRunnerGroup, SingleAgentEnvRunner
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig, vtrace
from ray_tpu.rllib.learner import PPOLearner, PPOLearnerConfig, compute_gae
from ray_tpu.rllib.metrics import MetricsLogger
from ray_tpu.rllib.multi_agent import (
    MultiAgentEnv,
    MultiAgentPPO,
    MultiAgentPPOConfig,
    MultiRLModule,
)
from ray_tpu.rllib.ope import (
    DoublyRobust,
    ImportanceSampling,
    OffPolicyEstimator,
    WeightedImportanceSampling,
)
from ray_tpu.rllib.offline import (
    BC,
    BCConfig,
    MARWILConfig,
    load_offline_dataset,
    record_experiences,
)
from ray_tpu.rllib.ppo import PPO, PPOConfig
from ray_tpu.rllib.replay import PrioritizedReplayBuffer, SumTree
from ray_tpu.rllib.rl_module import (
    DefaultActorCriticModule,
    RLModule,
    RLModuleSpec,
)
from ray_tpu.rllib.sac import SAC, SACConfig

__all__ = [
    "APPO",
    "APPOConfig",
    "Algorithm",
    "AlgorithmConfig",
    "DefaultActorCriticModule",
    "RLModule",
    "RLModuleSpec",
    "BC",
    "BCConfig",
    "MARWILConfig",
    "CQL",
    "CQLConfig",
    "Catalog",
    "ConnectorPipeline",
    "ConnectorV2",
    "DQN",
    "DQNConfig",
    "DoublyRobust",
    "DreamerV3",
    "DreamerV3Config",
    "ImportanceSampling",
    "OffPolicyEstimator",
    "WeightedImportanceSampling",
    "EnvRunnerGroup",
    "FlattenObs",
    "FrameStack",
    "GeneralAdvantageEstimation",
    "IMPALA",
    "IMPALAConfig",
    "MetricsLogger",
    "MultiAgentEnv",
    "MultiAgentPPO",
    "MultiAgentPPOConfig",
    "MultiRLModule",
    "NormalizeImage",
    "PPO",
    "PPOConfig",
    "PPOLearner",
    "PPOLearnerConfig",
    "PrioritizedReplayBuffer",
    "ReplayBuffer",
    "SAC",
    "SACConfig",
    "SingleAgentEnvRunner",
    "SumTree",
    "compute_gae",
    "load_offline_dataset",
    "record_continuous_experiences",
    "record_experiences",
    "vtrace",
]
