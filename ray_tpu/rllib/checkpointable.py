"""Checkpointable — uniform component-state save/restore for algorithms.

Reference parity: rllib/utils/checkpoints.py Checkpointable (get_state /
set_state / save_to_path / restore_from_path as a uniform component
tree). Algorithms expose their state as a nested dict of named
components; the mixin persists it with cloudpickle (jax pytrees of
numpy-converted leaves are plain data).
"""

from __future__ import annotations

import os

import cloudpickle


class Checkpointable:
    """Mixin: subclasses define STATE_COMPONENTS, a tuple of attribute
    names whose values form the component tree. jax arrays are
    host-converted on save so checkpoints are device-independent."""

    STATE_COMPONENTS: tuple[str, ...] = ()

    def get_state(self) -> dict:
        import jax
        import numpy as np

        def host(v):
            try:
                # device arrays -> host numpy; plain scalars stay scalars
                return jax.tree.map(
                    lambda x: np.asarray(x)
                    if isinstance(x, jax.Array) else x, v)
            except Exception:  # noqa: BLE001
                return v

        return {name: host(getattr(self, name))
                for name in self.STATE_COMPONENTS}

    def set_state(self, state: dict):
        import jax
        import jax.numpy as jnp
        import numpy as np

        for name, value in state.items():
            if name not in self.STATE_COMPONENTS:
                continue
            try:
                # only ARRAY leaves go back to device; scalar bookkeeping
                # (iteration counters) must stay plain python ints
                value = jax.tree.map(
                    lambda v: jnp.asarray(v)
                    if isinstance(v, np.ndarray) else v, value)
            except Exception:  # noqa: BLE001
                pass
            setattr(self, name, value)

    def save_to_path(self, path: str) -> str:
        os.makedirs(path, exist_ok=True)
        out = os.path.join(path, "state.pkl")
        with open(out, "wb") as f:
            cloudpickle.dump(
                {"class": type(self).__name__, "state": self.get_state()}, f)
        return path

    def restore_from_path(self, path: str):
        with open(os.path.join(path, "state.pkl"), "rb") as f:
            payload = cloudpickle.load(f)
        self.set_state(payload["state"])
        return self
