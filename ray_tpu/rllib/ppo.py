"""PPO — the first algorithm on the new stack.

Reference parity: PPOConfig/PPO (rllib/algorithms/ppo/ppo.py:60,363,
training_step :389): synchronous sampling from the EnvRunnerGroup →
Learner update → weight sync back to the runners. The Learner update is
one jitted SPMD program (learner.py here) instead of a DDP-wrapped torch
module; `num_learners>1` maps to a bigger learner mesh, not more NCCL
processes."""

from __future__ import annotations

import dataclasses
import time
from typing import Any

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env_runner import EnvRunnerGroup
from ray_tpu.rllib.learner import PPOLearner, PPOLearnerConfig


@dataclasses.dataclass
class PPOConfig(AlgorithmConfig):
    """Fluent builder (reference: PPOConfig over AlgorithmConfig —
    .environment().env_runners().training())."""

    num_env_runners: int = 2
    lambda_: float = 0.95
    clip_param: float = 0.2
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.0
    num_sgd_iter: int = 6
    minibatch_size: int = 128
    num_learners: int = 0  # >1: learner mesh of that many devices
    learner_mesh: Any = None  # or pass an explicit jax Mesh
    # Overlap sampling with the jitted update (reference: the async
    # learner thread, rllib/execution/multi_gpu_learner_thread.py:21,141
    # — sampling continues while the learner consumes the previous
    # batch). Queue depth 1: each batch is exactly one update stale,
    # which PPO's clipped importance ratio absorbs. Pays off when the
    # learner runs on an accelerator while envs step on host CPU.
    pipeline_sampling: bool = False

    def learners(self, num_learners: int = 0) -> "PPOConfig":
        """num_learners>1 maps to a LEARNER MESH of that many devices
        for the one jitted SPMD update (the reference spawns N NCCL
        learner actors via Train's BackendExecutor, learner_group.py:134;
        here GSPMD shards the minibatch over the mesh's data axis and
        inserts the gradient psum DDP would do by hand). The mesh itself
        is built at build() time so the config stays pure picklable data
        and never initializes the jax backend early."""
        self.num_learners = int(num_learners)
        self.learner_mesh = None  # (re)derived at build()
        return self

    def _resolve_learner_mesh(self):
        if self.learner_mesh is not None:
            return self.learner_mesh
        if self.num_learners <= 1:
            return None
        import jax

        from ray_tpu.parallel.mesh import MeshSpec, build_mesh

        devices = jax.devices()[:self.num_learners]
        if len(devices) < self.num_learners:
            raise ValueError(
                f"num_learners={self.num_learners} > {len(jax.devices())} "
                f"devices")
        return build_mesh(MeshSpec(data=self.num_learners), devices=devices)

    def build(self) -> "PPO":
        return PPO(self)


class PPO(Algorithm):
    """Algorithm driver (reference: Algorithm.step → PPO.training_step
    :389 — sample, learn, sync; the shared train/eval/checkpoint
    skeleton lives in the Algorithm base)."""

    config_class = PPOConfig
    STATE_COMPONENTS = ("_iteration", "_timesteps_total",
                        "_env_steps_total")

    def get_state(self) -> dict:
        state = super().get_state()
        state["learner"] = {"params": self.learner.get_weights()}
        return state

    def set_state(self, state: dict):
        super().set_state(state)
        if "learner" in state:
            self.learner.set_weights(state["learner"]["params"])
            self.env_runner_group.sync_weights(self.learner.get_weights())

    def setup(self, config: PPOConfig):
        self.env_runner_group = EnvRunnerGroup(
            num_env_runners=config.num_env_runners,
            remote=config.num_env_runners > 0,
            env=config.env,
            num_envs=config.num_envs_per_env_runner,
            rollout_fragment_length=config.rollout_fragment_length,
            seed=config.seed,
            hidden=config.hidden,
            framestack=config.framestack,
            model_config=config.model_config,
        )
        # probe spaces locally (cheap, no env stepping)
        import gymnasium as gym

        from ray_tpu.rllib import envs as _envs
        from ray_tpu.rllib.connectors import (
            GeneralAdvantageEstimation,
            default_env_to_module,
        )

        _envs.register_envs()
        probe = gym.make(config.env)
        raw_shape = tuple(probe.observation_space.shape)
        n_actions = int(probe.action_space.n)
        probe.close()
        proc_shape = default_env_to_module(
            raw_shape, config.framestack).output_shape(raw_shape)
        obs_spec = (proc_shape if len(proc_shape) == 3
                    else int(np.prod(proc_shape)))
        # learner connector pipeline (reference: GAE lives in the learner
        # connectors, general_advantage_estimation.py)
        self._learner_connector = GeneralAdvantageEstimation(
            config.gamma, config.lambda_)
        self.learner = PPOLearner(
            obs_spec, n_actions,
            PPOLearnerConfig(
                lr=config.lr, clip_param=config.clip_param,
                vf_loss_coeff=config.vf_loss_coeff,
                entropy_coeff=config.entropy_coeff,
                num_sgd_iter=config.num_sgd_iter,
                minibatch_size=config.minibatch_size,
                hidden=config.hidden),
            mesh=config._resolve_learner_mesh(), seed=config.seed,
            model_config=config.model_config)
        self.env_runner_group.sync_weights(self.learner.get_weights())
        self._env_steps_total = 0
        # pipeline_sampling state: the fragment prefetched during the
        # previous iteration's update, and a one-thread executor for the
        # in-flight jitted update
        self._prefetched = None
        self._learn_executor = None

    def _build_batch(self, samples):
        """Fragments → one flat train batch: GAE per fragment (each has
        its own bootstrap values), flatten (T, N) -> (T*N,), drop
        autoreset steps (their action was ignored by the env — next-step
        autoreset — so they are not real experience)."""
        obs, acts, logp, adv, targets = [], [], [], [], []
        ep_returns, n_eps, env_steps = [], 0, 0
        for s in samples:
            s = self._learner_connector(s)
            a, tg = s["advantages"], s["value_targets"]
            valid = ~s["reset_mask"].reshape(-1)
            obs.append(s["obs"].reshape(-1, *s["obs"].shape[2:])[valid])
            acts.append(s["actions"].reshape(-1)[valid])
            logp.append(s["logp"].reshape(-1)[valid])
            adv.append(a.reshape(-1)[valid])
            targets.append(tg.reshape(-1)[valid])
            if s["num_episodes"]:
                ep_returns.append(s["episode_return_mean"])
                n_eps += s["num_episodes"]
            env_steps += s["env_steps"]
        train_batch = {
            "obs": np.concatenate(obs).astype(np.float32),
            "actions": np.concatenate(acts),
            "logp_old": np.concatenate(logp),
            "advantages": np.concatenate(adv),
            "value_targets": np.concatenate(targets),
        }
        return train_batch, ep_returns, n_eps, env_steps

    def _finish_iteration(self, t0, t_sample, t_learn, ep_returns, n_eps,
                          env_steps, learner_metrics) -> dict:
        self._env_steps_total += env_steps
        dt = time.perf_counter() - t0
        if ep_returns:
            self.metrics.log_value(("env_runners", "episode_return_mean"),
                                   float(np.mean(ep_returns)), window=20)
        self.metrics.log_value(("env_runners", "num_env_steps_sampled"),
                               env_steps, reduce="sum", window=None)
        self.metrics.log_dict(learner_metrics, key="learner", window=20)
        return {
            "episode_return_mean": float(np.mean(ep_returns))
            if ep_returns else float("nan"),
            "num_episodes": n_eps,
            "num_env_steps_sampled": env_steps,
            "num_env_steps_sampled_lifetime": self._env_steps_total,
            "env_steps_per_sec": env_steps / dt,
            "time_sample_s": t_sample,
            "time_learn_s": t_learn,
            **{f"learner/{k}": v for k, v in learner_metrics.items()},
        }

    def training_step(self) -> dict:
        """One training iteration (reference: PPO.training_step,
        ppo.py:389 — sample, learn, sync)."""
        if self.config.pipeline_sampling:
            return self._train_pipelined()
        t0 = time.perf_counter()
        samples = self.env_runner_group.sample()
        t_sample = time.perf_counter() - t0
        train_batch, ep_returns, n_eps, env_steps = \
            self._build_batch(samples)
        t1 = time.perf_counter()
        learner_metrics = self.learner.update(train_batch)
        t_learn = time.perf_counter() - t1
        self.env_runner_group.sync_weights(self.learner.get_weights())
        return self._finish_iteration(t0, t_sample, t_learn, ep_returns,
                                      n_eps, env_steps, learner_metrics)

    def _train_pipelined(self) -> dict:
        """Async-learner iteration (reference:
        multi_gpu_learner_thread.py:141 LoaderThread/step overlap): the
        jitted update on fragment k runs while fragment k+1 is sampled.
        The runners hold the pre-update weights during the overlap (sync
        happens after both finish), so each batch is exactly one update
        stale — logp_old matches the sampling policy, and the clipped
        ratio absorbs the staleness."""
        import concurrent.futures as cf

        if self._learn_executor is None:
            self._learn_executor = cf.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ppo-learn")
        t0 = time.perf_counter()
        if self._prefetched is None:
            self._prefetched = self.env_runner_group.sample()
        train_batch, ep_returns, n_eps, env_steps = \
            self._build_batch(self._prefetched)
        t1 = time.perf_counter()
        fut = self._learn_executor.submit(self.learner.update, train_batch)
        # overlap: sample the NEXT fragment while the update executes
        self._prefetched = self.env_runner_group.sample()
        t_sample = time.perf_counter() - t1
        learner_metrics = fut.result()
        t_learn = time.perf_counter() - t1
        self.env_runner_group.sync_weights(self.learner.get_weights())
        return self._finish_iteration(t0, t_sample, t_learn, ep_returns,
                                      n_eps, env_steps, learner_metrics)

    def get_weights(self):
        return self.learner.get_weights()

    def cleanup(self):
        if self._learn_executor is not None:
            self._learn_executor.shutdown(wait=False)
            self._learn_executor = None
        self.env_runner_group.shutdown()
