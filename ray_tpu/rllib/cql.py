"""CQL — Conservative Q-Learning for offline continuous control.

Reference parity: rllib/algorithms/cql/cql.py:1 (CQLConfig extends
SACConfig; the learner adds the conservative regularizer to the SAC
critic loss) and cql/torch/cql_torch_learner.py (logsumexp over
sampled random + policy actions minus dataset-action Q). Built on this
repo's SAC networks (rllib/sac.py) and offline data plumbing
(rllib/offline.py), the TPU way: one jitted update closes over the
whole critic+actor+temperature step; the action-sampling fan-out is a
batched vmap-free broadcast that XLA tiles onto the MXU.

CQL(H) lower-bounds Q under distribution shift: the critic minimizes
  bellman_mse + cql_alpha * (E_s[logsumexp_a Q(s,a)] - E_(s,a)~D[Q(s,a)])
so out-of-distribution actions get pushed DOWN relative to dataset
actions — the property the tests assert directly.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.sac import (_mlp, init_sac_params, q_values,
                               sample_action)


def record_continuous_experiences(env: str, num_steps: int, out_dir: str,
                                  seed: int = 0, fmt: str = "jsonl"):
    """Roll a uniform-random policy through a continuous-action env and
    persist normalized transitions (actions mapped to [-1,1], matching
    the tanh-squashed convention) as a ray_tpu.data dataset
    (reference: offline recording via output_config)."""
    import gymnasium as gym

    from ray_tpu import data as rd

    e = gym.make(env)
    low = np.asarray(e.action_space.low, np.float32)
    high = np.asarray(e.action_space.high, np.float32)
    rng = np.random.default_rng(seed)
    rows = []
    obs, _ = e.reset(seed=seed)
    for _ in range(num_steps):
        a_norm = rng.uniform(-1.0, 1.0, size=low.shape).astype(np.float32)
        a_env = low + (a_norm + 1.0) * 0.5 * (high - low)
        nxt, rew, term, trunc, _ = e.step(a_env)
        rows.append({
            "obs": [float(x) for x in np.reshape(obs, -1)],
            "action": [float(x) for x in a_norm],
            "reward": float(rew),
            "next_obs": [float(x) for x in np.reshape(nxt, -1)],
            "done": bool(term),
        })
        obs = nxt
        if term or trunc:
            obs, _ = e.reset()
    e.close()
    ds = rd.from_items(rows, parallelism=8)
    if fmt == "parquet":
        return ds.write_parquet(out_dir)
    return ds.write_jsonl(out_dir)


@dataclasses.dataclass
class CQLConfig(AlgorithmConfig):
    """Reference: CQLConfig (cql.py) = SACConfig + conservative knobs;
    rides the shared AlgorithmConfig (env = evaluation env)."""

    input_path: str = ""
    env: str = "Pendulum-v1"  # evaluation env
    tau: float = 0.005
    train_batch_size: int = 256
    updates_per_iteration: int = 32
    hidden: tuple = (256, 256)
    initial_alpha: float = 1.0
    target_entropy: float | None = None
    # conservative regularizer (reference: cql.py min_q_weight role)
    cql_alpha: float = 5.0
    n_action_samples: int = 4

    def offline_data(self, input_path: str) -> "CQLConfig":
        self.input_path = input_path
        return self

    def build(self) -> "CQL":
        return CQL(self)


class CQL(Algorithm):
    """Conservative Q-learning on the shared Algorithm base (offline:
    no sampling env; `evaluate(...)` if present takes the env
    explicitly)."""

    config_class = CQLConfig
    STATE_COMPONENTS = ("params", "target_q", "log_alpha", "_iteration",
                        "_timesteps_total")

    def setup(self, config: CQLConfig):
        from ray_tpu.rllib.offline import load_offline_dataset

        cfg = config
        rows = load_offline_dataset(cfg.input_path).take_all()
        if not rows:
            raise ValueError(f"no offline rows at {cfg.input_path!r}")
        self._data = {
            "obs": np.asarray([r["obs"] for r in rows], np.float32),
            "actions": np.asarray([r["action"] for r in rows], np.float32),
            "rewards": np.asarray([r["reward"] for r in rows], np.float32),
            "next_obs": np.asarray([r["next_obs"] for r in rows],
                                   np.float32),
            "dones": np.asarray([float(r["done"]) for r in rows],
                                np.float32),
        }
        self.obs_dim = self._data["obs"].shape[1]
        self.act_dim = self._data["actions"].shape[1]
        target_entropy = (cfg.target_entropy if cfg.target_entropy is not None
                          else -float(self.act_dim))

        key = jax.random.PRNGKey(cfg.seed)
        self.params = init_sac_params(key, self.obs_dim, self.act_dim,
                                      cfg.hidden)
        self.target_q = {"q1": jax.tree.map(jnp.copy, self.params["q1"]),
                         "q2": jax.tree.map(jnp.copy, self.params["q2"])}
        self.log_alpha = jnp.asarray(np.log(cfg.initial_alpha), jnp.float32)
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        self.alpha_tx = optax.adam(cfg.lr)
        self.alpha_opt = self.alpha_tx.init(self.log_alpha)
        N = cfg.n_action_samples

        def _q_fanout_cat(params, obs, actions):
            """Q(s, a_i) for B obs x M sampled actions each: broadcast to
            (B*M, ·) so the critic MLP stays one big MXU matmul."""
            B, M = actions.shape[0], actions.shape[1]
            obs_rep = jnp.repeat(obs, M, axis=0)
            flat = actions.reshape(B * M, -1)
            q1, q2 = q_values(params, obs_rep, flat)
            return q1.reshape(B, M), q2.reshape(B, M)

        def critic_loss(params, target_q, log_alpha, batch, key):
            kn, kr, kp, kp2 = jax.random.split(key, 4)
            # SAC bellman target
            next_a, next_logp = sample_action(params, batch["next_obs"], kn)
            tin = jnp.concatenate([batch["next_obs"], next_a], -1)
            tq = jnp.minimum(_mlp(target_q["q1"], tin)[..., 0],
                             _mlp(target_q["q2"], tin)[..., 0])
            alpha = jnp.exp(log_alpha)
            target = jax.lax.stop_gradient(
                batch["rewards"] + cfg.gamma * (1 - batch["dones"]) *
                (tq - alpha * next_logp))
            q1, q2 = q_values(params, batch["obs"], batch["actions"])
            bellman = jnp.mean((q1 - target) ** 2 + (q2 - target) ** 2)
            # conservative term: logsumexp over random + policy actions
            B = batch["obs"].shape[0]
            rand_a = jax.random.uniform(kr, (B, N, self.act_dim),
                                        minval=-1.0, maxval=1.0)
            pol_a, pol_logp = sample_action(
                params, jnp.repeat(batch["obs"], N, axis=0), kp)
            nxt_a, nxt_logp = sample_action(
                params, jnp.repeat(batch["next_obs"], N, axis=0), kp2)
            pol_a = jax.lax.stop_gradient(pol_a).reshape(B, N, -1)
            nxt_a = jax.lax.stop_gradient(nxt_a).reshape(B, N, -1)
            # importance corrections (reference: cql_torch_learner.py):
            # uniform density 0.5^d for random, detached logp for policy
            log_u = self.act_dim * np.log(0.5)
            corr = jnp.concatenate([
                jnp.full((B, N), log_u),
                jax.lax.stop_gradient(pol_logp).reshape(B, N),
                jax.lax.stop_gradient(nxt_logp).reshape(B, N),
            ], axis=1)
            cat = jnp.concatenate([rand_a, pol_a, nxt_a], axis=1)
            cq1, cq2 = _q_fanout_cat(params, batch["obs"], cat)
            gap1 = jnp.mean(jax.nn.logsumexp(cq1 - corr, axis=1)) - \
                jnp.mean(q1)
            gap2 = jnp.mean(jax.nn.logsumexp(cq2 - corr, axis=1)) - \
                jnp.mean(q2)
            conservative = cfg.cql_alpha * (gap1 + gap2)
            return bellman + conservative, (bellman, gap1 + gap2)

        def actor_loss(params, log_alpha, batch, key):
            a, logp = sample_action(params, batch["obs"], key)
            q1, q2 = q_values(params, batch["obs"], a)
            alpha = jax.lax.stop_gradient(jnp.exp(log_alpha))
            return jnp.mean(alpha * logp - jnp.minimum(q1, q2)), logp

        def update(params, opt_state, target_q, log_alpha, alpha_opt,
                   batch, key):
            kc, ka = jax.random.split(key)
            (c_loss, (bellman, gap)), c_grads = jax.value_and_grad(
                critic_loss, has_aux=True)(params, target_q, log_alpha,
                                           batch, kc)
            (a_loss, logp), a_grads = jax.value_and_grad(
                actor_loss, has_aux=True)(params, log_alpha, batch, ka)
            grads = {"pi": a_grads["pi"], "q1": c_grads["q1"],
                     "q2": c_grads["q2"]}
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            al_grad = jax.grad(
                lambda la: -jnp.mean(
                    la * jax.lax.stop_gradient(logp + target_entropy))
            )(log_alpha)
            al_up, alpha_opt = self.alpha_tx.update(al_grad, alpha_opt)
            log_alpha = optax.apply_updates(log_alpha, al_up)
            target_q = jax.tree.map(
                lambda t, o: (1 - cfg.tau) * t + cfg.tau * o,
                target_q, {"q1": params["q1"], "q2": params["q2"]})
            return (params, opt_state, target_q, log_alpha, alpha_opt,
                    bellman, gap, a_loss)

        self._update = jax.jit(update)
        self._key = jax.random.PRNGKey(cfg.seed + 1)
        self._rng = np.random.default_rng(cfg.seed)

    def _minibatch(self):
        n = len(self._data["rewards"])
        idx = self._rng.integers(0, n, min(self.config.train_batch_size, n))
        return {k: jnp.asarray(v[idx]) for k, v in self._data.items()}

    def training_step(self) -> dict:
        cfg = self.config
        t0 = time.perf_counter()
        bellmans, gaps, a_losses = [], [], []
        for _ in range(cfg.updates_per_iteration):
            self._key, k = jax.random.split(self._key)
            (self.params, self.opt_state, self.target_q, self.log_alpha,
             self.alpha_opt, bell, gap, al) = self._update(
                self.params, self.opt_state, self.target_q,
                self.log_alpha, self.alpha_opt, self._minibatch(), k)
            bellmans.append(float(bell))
            gaps.append(float(gap))
            a_losses.append(float(al))
        return {
            "learner/bellman_loss": float(np.mean(bellmans)),
            "learner/conservative_gap": float(np.mean(gaps)),
            "learner/actor_loss": float(np.mean(a_losses)),
            "alpha": float(np.exp(self.log_alpha)),
            "time_s": time.perf_counter() - t0,
        }

    def ood_gap(self, n: int = 512) -> float:
        """Mean Q advantage of DATASET actions over random (OOD) actions
        — positive once the conservative penalty bites; the defining
        CQL property, asserted by tests."""
        idx = self._rng.integers(0, len(self._data["rewards"]), n)
        obs = jnp.asarray(self._data["obs"][idx])
        acts = jnp.asarray(self._data["actions"][idx])
        rand = jnp.asarray(self._rng.uniform(-1, 1, acts.shape),
                           jnp.float32)
        q_data = jnp.minimum(*q_values(self.params, obs, acts))
        q_rand = jnp.minimum(*q_values(self.params, obs, rand))
        return float(jnp.mean(q_data) - jnp.mean(q_rand))

    def evaluate(self, env: str | None = None,
                 num_episodes: int = 5) -> dict:
        """Deterministic (tanh-mean) policy rollout."""
        import gymnasium as gym

        e = gym.make(env or self.config.env)
        low = np.asarray(e.action_space.low, np.float32)
        high = np.asarray(e.action_space.high, np.float32)

        @jax.jit
        def mean_action(params, obs):
            out = _mlp(params["pi"], obs)
            mu, _ = jnp.split(out, 2, axis=-1)
            return jnp.tanh(mu)

        returns = []
        for ep in range(num_episodes):
            obs, _ = e.reset(seed=2000 + ep)
            total, done = 0.0, False
            while not done:
                a = np.asarray(mean_action(
                    self.params,
                    np.asarray(obs, np.float32).reshape(1, -1)))[0]
                a_env = low + (a + 1.0) * 0.5 * (high - low)
                obs, r, term, trunc, _ = e.step(a_env)
                total += float(r)
                done = term or trunc
            returns.append(total)
        e.close()
        return {"episode_return_mean": float(np.mean(returns)),
                "num_episodes": num_episodes}
