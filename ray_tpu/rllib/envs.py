"""Synthetic pixel environments (Atari-class capability without ALE).

Reference parity: the role of ALE Atari envs in
rllib/benchmarks/ppo/benchmark_atari_ppo.py and the tuned_examples pixel
configs — a conv-input env that requires spatial feature extraction to
solve. ALE is not in this image (zero egress), so PixelCatch is the
MinAtar-style stand-in: a ball falls down a HxW grid; the agent moves a
paddle left/stay/right and is rewarded for catching it. Purely
observational from pixels — an MLP on flattened pixels can solve it too,
but the conv path is what the PPO pixel tests exercise end-to-end.
"""

from __future__ import annotations

import gymnasium as gym
import numpy as np
from gymnasium import spaces


class PixelCatch(gym.Env):
    """10x10x1 uint8 pixel grid; 3 actions (left/stay/right); +1 catch,
    -1 miss; episode = `balls` balls."""

    metadata = {"render_modes": []}

    def __init__(self, size: int = 10, balls: int = 5):
        self.size = size
        self.balls = balls
        self.observation_space = spaces.Box(0, 255, (size, size, 1),
                                            np.uint8)
        self.action_space = spaces.Discrete(3)
        self._rng = np.random.default_rng(0)

    def _obs(self) -> np.ndarray:
        frame = np.zeros((self.size, self.size, 1), np.uint8)
        frame[self.ball_y, self.ball_x, 0] = 255
        frame[self.size - 1, self.paddle_x, 0] = 128
        return frame

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._balls_left = self.balls
        self._new_ball()
        self.paddle_x = self.size // 2
        return self._obs(), {}

    def _new_ball(self):
        self.ball_x = int(self._rng.integers(0, self.size))
        self.ball_y = 0

    def step(self, action):
        self.paddle_x = int(np.clip(self.paddle_x + (int(action) - 1),
                                    0, self.size - 1))
        self.ball_y += 1
        reward = 0.0
        terminated = False
        if self.ball_y >= self.size - 1:
            reward = 1.0 if self.ball_x == self.paddle_x else -1.0
            self._balls_left -= 1
            if self._balls_left <= 0:
                terminated = True
            else:
                self._new_ball()
        return self._obs(), reward, terminated, False, {}


def register_envs():
    """Idempotent gym registration (call before gym.make in any
    process; env runners do this automatically)."""
    if "PixelCatch-v0" not in gym.registry:
        gym.register(id="PixelCatch-v0",
                     entry_point="ray_tpu.rllib.envs:PixelCatch")


register_envs()
