"""IMPALA — asynchronous sampling with a background learner thread.

Reference parity: rllib/algorithms/impala (training_step :592, async
learner wiring :1358-1370) and the MultiGPULearnerThread double-buffer
pipeline (rllib/execution/multi_gpu_learner_thread.py:21, step :141) the
BASELINE names explicitly. TPU shape:

- env-runner actors sample continuously with slightly stale weights:
  the driver keeps one in-flight sample() per runner and requeues it the
  moment it lands (no sync barrier per iteration);
- a host-side queue feeds a background LearnerThread whose update is the
  jitted V-trace actor-critic step — the host thread keeps the jitted
  program fed while sampling proceeds (the double-buffering role of the
  pinned GPU stages in the reference);
- off-policy correction: V-trace (clipped importance weights rho/c) —
  computed host-side per batch like the GAE connector.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import models
from ray_tpu.rllib.env_runner import EnvRunnerGroup


def vtrace(behavior_logp, target_logp, rewards, values, dones, last_values,
           gamma: float, rho_clip: float = 1.0, c_clip: float = 1.0):
    """V-trace targets + pg advantages, (T, N) host arrays (Espeholt et
    al. 2018, eq. 1)."""
    T, N = rewards.shape
    rho = np.minimum(np.exp(target_logp - behavior_logp), rho_clip)
    c = np.minimum(np.exp(target_logp - behavior_logp), c_clip)
    nonterm = 1.0 - dones.astype(np.float32)
    next_values = np.concatenate([values[1:], last_values[None]], axis=0)
    # bootstrap breaks at episode ends
    delta = rho * (rewards + gamma * next_values * nonterm - values)
    vs_minus_v = np.zeros((T + 1, N), np.float32)
    for t in range(T - 1, -1, -1):
        vs_minus_v[t] = delta[t] + gamma * nonterm[t] * c[t] * vs_minus_v[t + 1]
    vs = vs_minus_v[:T] + values
    vs_next = np.concatenate([vs[1:], last_values[None]], axis=0)
    advantages = rho * (rewards + gamma * vs_next * nonterm - values)
    return vs, advantages


from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig


@dataclasses.dataclass
class IMPALAConfig(AlgorithmConfig):
    num_env_runners: int = 2
    lr: float = 5e-4
    entropy_coeff: float = 0.01
    vf_loss_coeff: float = 0.5
    grad_clip: float = 40.0
    queue_capacity: int = 8
    broadcast_interval: int = 1  # learner steps between weight syncs

    def build(self) -> "IMPALA":
        return IMPALA(self)


class _LearnerThread(threading.Thread):
    """Background SGD (reference: LearnerThread.step,
    execution/learner_thread.py / multi_gpu_learner_thread.py:141)."""

    def __init__(self, algo: "IMPALA"):
        super().__init__(daemon=True, name="impala-learner")
        self.algo = algo
        self.stopped = threading.Event()
        self.num_updates = 0
        self.last_loss = float("nan")
        self.error: BaseException | None = None

    def run(self):
        algo = self.algo
        while not self.stopped.is_set():
            try:
                batch = algo._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                new_params, new_opt, loss = algo._update(
                    algo.params, algo.opt_state, batch)
                with algo._params_lock:
                    algo.params, algo.opt_state = new_params, new_opt
                self.num_updates += 1
                self.last_loss = float(loss)
                if self.num_updates % algo.config.broadcast_interval == 0:
                    algo._weights_dirty.set()
            except BaseException as e:  # noqa: BLE001
                # surface instead of dying silently: train() re-raises
                self.error = e
                self.stopped.set()
                return


class IMPALA(Algorithm):
    config_class = IMPALAConfig
    STATE_COMPONENTS = ("_iteration", "_timesteps_total", "_env_steps")

    def get_state(self) -> dict:
        state = super().get_state()
        with self._params_lock:
            state["learner"] = {
                "params": jax.tree.map(np.asarray, self.params)}
        return state

    def set_state(self, state: dict):
        super().set_state(state)
        if "learner" in state:
            with self._params_lock:
                self.params = jax.tree.map(
                    jnp.asarray, state["learner"]["params"])
            self.env_runner_group.sync_weights(
                state["learner"]["params"])

    def setup(self, config: IMPALAConfig):
        import gymnasium as gym

        probe = gym.make(config.env)
        obs_dim = int(np.prod(probe.observation_space.shape))
        n_actions = int(probe.action_space.n)
        probe.close()

        self.params = models.init_mlp_policy(
            jax.random.PRNGKey(config.seed), obs_dim, n_actions,
            config.hidden)
        self.tx = optax.chain(optax.clip_by_global_norm(config.grad_clip),
                              optax.adam(config.lr))
        self.opt_state = self.tx.init(self.params)
        cfg = config

        def loss_fn(params, batch):
            logits, value = models.forward(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=1)[:, 0]
            m = batch["mask"]
            denom = jnp.maximum(jnp.sum(m), 1.0)
            pg = -jnp.sum(m * logp * batch["advantages"]) / denom
            vf = jnp.sum(m * (value - batch["vs"]) ** 2) / denom
            ent = -jnp.sum(m * jnp.sum(
                jnp.exp(logp_all) * logp_all, axis=-1)) / denom
            return pg + cfg.vf_loss_coeff * vf - cfg.entropy_coeff * ent

        def update(params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        # NO buffer donation: params are read concurrently by the
        # driver thread (V-trace target logp, weight broadcast) while
        # the learner thread updates them
        self._update = jax.jit(update)
        self._params_lock = threading.Lock()
        self._logp_fn = jax.jit(
            lambda p, obs, actions: jnp.take_along_axis(
                jax.nn.log_softmax(models.forward(p, obs)[0]),
                actions[:, None], axis=1)[:, 0])

        self._queue: queue.Queue = queue.Queue(maxsize=config.queue_capacity)
        self._weights_dirty = threading.Event()
        self.env_runner_group = EnvRunnerGroup(
            num_env_runners=config.num_env_runners,
            remote=config.num_env_runners > 0,
            env=config.env, num_envs=config.num_envs_per_env_runner,
            rollout_fragment_length=config.rollout_fragment_length,
            seed=config.seed, hidden=config.hidden)
        self.env_runner_group.sync_weights(
            jax.tree.map(np.asarray, self.params))
        self.learner_thread = _LearnerThread(self)
        self.learner_thread.start()
        self._inflight: dict = {}
        self._env_steps = 0
        self._ep_returns: list[float] = []

    # -- async sampling plumbing ----------------------------------------

    def _to_batch(self, s: dict) -> dict:
        """Fragment -> V-trace learner batch (host-side, flattened)."""
        cfg = self.config
        T, N = s["rewards"].shape
        obs_flat = s["obs"].reshape(T * N, -1).astype(np.float32)
        with self._params_lock:
            params = self.params
        target_logp = np.asarray(self._logp_fn(
            params, obs_flat, s["actions"].reshape(-1))
        ).reshape(T, N)
        vs, adv = vtrace(s["logp"], target_logp, s["rewards"], s["values"],
                         s["dones"], s["last_values"], cfg.gamma)
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        # loss MASK (not row-drop) for autoreset steps: keeps the jitted
        # update's shapes static — row dropping would recompile per
        # unique valid-count
        mask = (~s["reset_mask"].reshape(-1)).astype(np.float32)
        return {
            "obs": jnp.asarray(obs_flat),
            "actions": jnp.asarray(s["actions"].reshape(-1)),
            "vs": jnp.asarray(vs.reshape(-1)),
            "advantages": jnp.asarray(adv.reshape(-1)),
            # behavior logp: APPO's clipped surrogate needs it
            "logp_old": jnp.asarray(s["logp"].reshape(-1)),
            "mask": jnp.asarray(mask),
        }

    def training_step(self) -> dict:
        """One driver iteration: harvest landed samples, keep one
        in-flight per runner, feed the learner queue (reference:
        IMPALA.training_step's async path)."""
        import ray_tpu

        cfg = self.config
        if self.learner_thread.error is not None:
            raise RuntimeError(
                "IMPALA learner thread failed") from self.learner_thread.error
        t0 = time.perf_counter()
        group = self.env_runner_group
        env_steps = 0

        if not group.remote:
            # inline mode: synchronous but still through the queue+thread
            s = group.local.sample()
            env_steps += s["env_steps"]
            if s["num_episodes"]:
                self._ep_returns.append(s["episode_return_mean"])
            self._queue.put(self._to_batch(s), timeout=30)
        else:
            for r in group.runners:
                if r not in self._inflight:
                    self._inflight[r] = r.sample.remote()
            deadline = time.monotonic() + 5
            harvested = 0
            while harvested == 0 and time.monotonic() < deadline:
                ready, _ = ray_tpu.wait(
                    list(self._inflight.values()),
                    num_returns=1, timeout=2.0)
                for ref in ready:
                    runner = next(r for r, v in self._inflight.items()
                                  if v == ref)
                    s = ray_tpu.get(ref, timeout=60)
                    self._inflight[runner] = runner.sample.remote()
                    env_steps += s["env_steps"]
                    if s["num_episodes"]:
                        self._ep_returns.append(s["episode_return_mean"])
                    try:
                        self._queue.put_nowait(self._to_batch(s))
                    except queue.Full:
                        pass  # backpressure: drop (reference drops too)
                    harvested += 1

        if self._weights_dirty.is_set():
            self._weights_dirty.clear()
            with self._params_lock:
                params = self.params
            group.sync_weights(jax.tree.map(np.asarray, params))

        self._env_steps += env_steps
        dt = time.perf_counter() - t0
        window = self._ep_returns[-100:]
        self._ep_returns = window
        return {
            "episode_return_mean": float(np.mean(window)) if window
            else float("nan"),
            "num_env_steps_sampled_lifetime": self._env_steps,
            "env_steps_per_sec": env_steps / dt,
            "learner_updates": self.learner_thread.num_updates,
            "learner/loss": self.learner_thread.last_loss,
            "learner_queue_size": self._queue.qsize(),
        }

    def get_weights(self):
        with self._params_lock:
            return jax.tree.map(np.asarray, self.params)

    def cleanup(self):
        self.learner_thread.stopped.set()
        self.env_runner_group.shutdown()
