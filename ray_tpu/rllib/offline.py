"""Offline RL — experience recording, offline datasets, BC and MARWIL.

Reference parity: rllib/offline/offline_data.py:22 (OfflineData wraps a
ray.data dataset of experiences feeding learners),
rllib/algorithms/bc (behavior cloning from logged episodes) and
rllib/algorithms/marwil (advantage-weighted BC). TPU shape: experiences
are recorded by env runners into jsonl/parquet via ray_tpu.data; the
offline learner is the same jitted SPMD update machinery, fed by
dataset iter_batches instead of live sampling.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import models
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig


def record_experiences(env: str, num_episodes: int, out_dir: str,
                       seed: int = 0, hidden=(64, 64), params=None,
                       fmt: str = "jsonl"):
    """Roll out a (random or given) policy and persist experiences as a
    ray_tpu.data-readable dataset (reference: offline recording via
    EnvRunner output_config -> ray.data write)."""
    from ray_tpu import data as rd
    from ray_tpu.rllib.env_runner import SingleAgentEnvRunner

    runner = SingleAgentEnvRunner(env=env, num_envs=4,
                                  rollout_fragment_length=128, seed=seed,
                                  hidden=hidden)
    if params is not None:
        runner.set_weights(params)
    rows = []
    episodes_done = 0
    while episodes_done < num_episodes:
        s = runner.sample()
        T, N = s["rewards"].shape
        # ENV-MAJOR row order: each env's steps are contiguous and
        # time-ordered so downstream return scans chain within one
        # trajectory only. The last row of each env's fragment segment
        # carries an explicit TRUNCATED flag (distinct from `done`, like
        # gymnasium's terminated/truncated split) so return scans stop at
        # the boundary without mistaking it for a real terminal.
        for n in range(N):
            seg_rows = []
            for t in range(T):
                if s["reset_mask"][t, n]:
                    continue
                seg_rows.append({
                    "obs": [float(x) for x in s["obs"][t, n].reshape(-1)],
                    "action": int(s["actions"][t, n]),
                    "reward": float(s["rewards"][t, n]),
                    "done": bool(s["dones"][t, n]),
                    "truncated": False,
                    "logp": float(s["logp"][t, n]),
                })
            if seg_rows and not seg_rows[-1]["done"]:
                seg_rows[-1]["truncated"] = True
            rows.extend(seg_rows)
        episodes_done += s["num_episodes"]
    ds = rd.from_items(rows, parallelism=8)
    if fmt == "parquet":
        return ds.write_parquet(out_dir)
    return ds.write_jsonl(out_dir)


def load_offline_dataset(path: str):
    """OfflineData role (offline_data.py:22): a Dataset of experience
    rows for offline training. Format is sniffed from the files on disk
    (reads are LAZY, so a wrong-format guess would only explode later
    inside a map task)."""
    import glob as _glob
    import os as _os

    from ray_tpu import data as rd

    names = (_glob.glob(_os.path.join(path, "*"))
             if _os.path.isdir(path) else [path])
    if any(n.endswith((".parquet", ".pq")) for n in names):
        return rd.read_parquet(path)
    return rd.read_json(path)


@dataclasses.dataclass
class BCConfig(AlgorithmConfig):
    """Reference: rllib/algorithms/bc/bc.py — supervised action
    cloning on logged states; rides the shared AlgorithmConfig so BC
    runs as a Tune trial like the online families."""

    input_path: str = ""
    lr: float = 1e-3
    train_batch_size: int = 256
    # MARWIL generalization (marwil.py): beta > 0 weights the cloning
    # loss by exp(beta * advantage) where advantage is the discounted
    # return minus a learned value baseline; beta = 0 is plain BC.
    beta: float = 0.0
    vf_coeff: float = 1.0

    def offline_data(self, input_path: str) -> "BCConfig":
        self.input_path = input_path
        return self

    def build(self) -> "BC":
        return BC(self)


@dataclasses.dataclass
class MARWILConfig(BCConfig):
    beta: float = 1.0

    def build(self) -> "BC":
        return BC(self)


class BC(Algorithm):
    """Behavior cloning / MARWIL driver on the shared Algorithm base:
    one jitted supervised update per minibatch over the offline
    dataset. `evaluate(env, ...)` takes the env EXPLICITLY (offline
    algos carry no sampling env in the config)."""

    config_class = BCConfig
    STATE_COMPONENTS = ("params", "opt_state", "_iteration",
                        "_timesteps_total")

    def setup(self, config: BCConfig):
        rows = load_offline_dataset(config.input_path).take_all()
        if not rows:
            raise ValueError(f"no offline rows at {config.input_path!r}")
        obs = np.asarray([r["obs"] for r in rows], np.float32)
        acts = np.asarray([r["action"] for r in rows], np.int64)
        rews = np.asarray([r["reward"] for r in rows], np.float32)
        # return chains break at real terminals AND at recording
        # truncations (fragment boundaries) — a truncated chain's return
        # is a known underestimate, never a cross-trajectory mix
        dones = np.asarray([r["done"] or r.get("truncated", False)
                            for r in rows], np.bool_)
        # Monte-Carlo returns per (recorded) trajectory for MARWIL's
        # advantage weighting
        returns = np.zeros(len(rows), np.float32)
        g = 0.0
        for i in range(len(rows) - 1, -1, -1):
            g = 0.0 if dones[i] else g
            g = rews[i] + config.gamma * g
            returns[i] = g
        self._data = {"obs": obs, "actions": acts, "returns": returns}
        self.obs_dim = obs.shape[1]
        self.n_actions = int(acts.max()) + 1

        self.params = models.init_mlp_policy(
            jax.random.PRNGKey(config.seed), self.obs_dim, self.n_actions,
            config.hidden)
        self.tx = optax.adam(config.lr)
        self.opt_state = self.tx.init(self.params)
        cfg = config

        def loss_fn(params, batch):
            logits, value = models.forward(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=1)[:, 0]
            if cfg.beta > 0.0:
                adv = batch["returns"] - value
                w = jnp.exp(cfg.beta * jax.lax.stop_gradient(
                    adv / (jnp.abs(adv).mean() + 1e-8)))
                bc = -jnp.mean(w * logp)
                vf = jnp.mean(adv ** 2)
                return bc + cfg.vf_coeff * vf, (bc, vf)
            return -jnp.mean(logp), (-jnp.mean(logp), 0.0)

        def update(params, opt_state, batch):
            (total, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, total

        self._update = jax.jit(update, donate_argnums=(0, 1))
        self._rng = np.random.RandomState(config.seed)

    def training_step(self) -> dict:
        cfg = self.config
        n = len(self._data["actions"])
        t0 = time.perf_counter()
        losses = []
        perm = self._rng.permutation(n)
        mb = min(cfg.train_batch_size, n)
        for i in range(max(1, n // mb)):
            idx = perm[i * mb:(i + 1) * mb]
            batch = {k: jnp.asarray(v[idx])
                     for k, v in self._data.items()}
            self.params, self.opt_state, loss = self._update(
                self.params, self.opt_state, batch)
            losses.append(float(loss))
        return {
            "learner/loss": float(np.mean(losses)),
            "num_samples": n,
            "time_s": time.perf_counter() - t0,
        }

    def evaluate(self, env: str | None = None,
                 num_episodes: int = 20) -> dict:
        """Greedy rollout of the cloned policy (reference: BC eval via
        evaluation env runners). `env` defaults to config.env so the
        base Algorithm.step() evaluation hook works too."""
        env = env or self.config.env
        import gymnasium as gym

        from ray_tpu.rllib import envs as _envs

        _envs.register_envs()
        e = gym.make(env)
        fwd = jax.jit(models.forward)
        returns = []
        for ep in range(num_episodes):
            obs, _ = e.reset(seed=1000 + ep)
            total, done = 0.0, False
            while not done:
                logits, _ = fwd(self.params,
                                np.asarray(obs, np.float32).reshape(1, -1))
                action = int(np.argmax(np.asarray(logits)[0]))
                obs, r, term, trunc, _ = e.step(action)
                total += float(r)
                done = term or trunc
            returns.append(total)
        e.close()
        return {"episode_return_mean": float(np.mean(returns)),
                "num_episodes": num_episodes}

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)
