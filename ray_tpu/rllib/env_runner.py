"""SingleAgentEnvRunner — vectorized env sampling with policy inference.

Reference parity: rllib/env/single_agent_env_runner.py:64 (`sample`
:139, hot loop `_sample` :243): gymnasium vector envs stepped against
the current RLModule; here inference is a jitted CPU forward inside the
actor process. Collected rollouts come back as flat numpy arrays (the
connector-pipeline role of env→module/module→env formatting is inlined:
CartPole-class observation spaces need no preprocessing)."""

from __future__ import annotations

import numpy as np


class SingleAgentEnvRunner:
    """Runs as an actor (one per `num_env_runners`), or inline."""

    def __init__(self, env: str = "CartPole-v1", num_envs: int = 1,
                 rollout_fragment_length: int = 200, seed: int = 0,
                 hidden=(64, 64), framestack: int = 1,
                 model_config: dict | None = None,
                 module_spec=None):
        import gymnasium as gym
        import jax

        from ray_tpu.rllib import envs as _envs  # registers PixelCatch etc.

        _envs.register_envs()
        self._jax = jax
        self.envs = gym.make_vec(env, num_envs=num_envs)
        self.num_envs = num_envs
        self.T = rollout_fragment_length
        raw_shape = tuple(self.envs.single_observation_space.shape)
        self.n_actions = int(self.envs.single_action_space.n)
        from ray_tpu.rllib import models
        from ray_tpu.rllib.connectors import default_env_to_module

        # env→module connector pipeline (reference: connector_v2.py:31);
        # image obs get normalize(+framestack), vectors get flatten —
        # the module sees the PROCESSED shape everywhere (buffers, nets)
        self.pipeline = default_env_to_module(raw_shape, framestack)
        self.pipeline.reset(num_envs)
        self.obs_shape = self.pipeline.output_shape(raw_shape)
        self.obs_dim = int(np.prod(self.obs_shape))  # legacy vector algos
        self._image = len(self.obs_shape) == 3

        self._models = models
        mc = dict(model_config or {})
        mc.setdefault("hidden", tuple(hidden))
        # RLModule seam (reference: the runner builds its module from an
        # RLModuleSpec, single_agent_env_runner.py make_module): default
        # is the catalog actor-critic; algorithms may ship a custom spec
        if module_spec is None:
            from ray_tpu.rllib.rl_module import RLModuleSpec

            module_spec = RLModuleSpec(
                obs_spec=self.obs_shape if self._image else self.obs_dim,
                n_actions=self.n_actions, model_config=mc)
        self.module = module_spec.build()
        self.params = self.module.init(jax.random.PRNGKey(seed))
        self._sample_fn = jax.jit(self.module.explore)
        self._key = jax.random.PRNGKey(seed + 1)
        raw_obs, _ = self.envs.reset(seed=seed)
        self.obs = self.pipeline(raw_obs)
        self._ep_returns = np.zeros(num_envs)
        self._completed_returns: list[float] = []
        self._env_steps_total = 0
        # gymnasium NEXT-STEP autoreset: the obs returned on the step
        # AFTER done is a reset frame (and that step's action is
        # ignored). Carried across fragments for reset_mask correctness.
        self._last_done = np.zeros(num_envs, np.bool_)

    # -- weights ---------------------------------------------------------

    def set_weights(self, weights) -> bool:
        """Weights arrive as host numpy pytrees (reference:
        EnvRunnerGroup.sync_weights broadcast)."""
        self.params = self._jax.tree.map(np.asarray, weights)
        return True

    def get_weights(self):
        return self._jax.tree.map(np.asarray, self.params)

    # -- sampling --------------------------------------------------------

    def sample(self) -> dict:
        """One rollout fragment of T steps across all envs. Returns flat
        (T*num_envs, ...) arrays plus bootstrap values, and episode-return
        stats for completed episodes."""
        jax = self._jax
        T, N = self.T, self.num_envs
        obs_buf = np.empty((T, N, *self.obs_shape), np.float32)
        act_buf = np.empty((T, N), np.int64)
        logp_buf = np.empty((T, N), np.float32)
        val_buf = np.empty((T, N), np.float32)
        rew_buf = np.empty((T, N), np.float32)
        done_buf = np.empty((T, N), np.bool_)
        # reset_mask[t]: the obs at step t is an autoreset frame — the
        # env IGNORED that step's action (next-step autoreset), so the
        # transition is not real experience and learners must drop it
        reset_buf = np.empty((T, N), np.bool_)

        obs = self.obs
        # ONE split for the whole fragment: a per-step eager
        # jax.random.split costs ~0.5ms of dispatch each — at T=128 that
        # was ~40% of sampling time (the r3 PPO bench regression); numpy
        # indexing into the presplit batch is free
        keys = np.asarray(jax.random.split(self._key, T + 1))
        self._key = jax.numpy.asarray(keys[0])
        for t in range(T):
            k = keys[t + 1]
            action, logp, value = self._sample_fn(
                self.params, obs.astype(np.float32), k)
            action = np.asarray(action)
            raw_next, reward, term, trunc, _ = self.envs.step(action)
            done = np.logical_or(term, trunc)
            obs_buf[t] = obs
            act_buf[t] = action
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(value)
            rew_buf[t] = reward
            done_buf[t] = done
            reset_buf[t] = self._last_done
            self._ep_returns += reward
            for i in np.nonzero(done)[0]:
                self._completed_returns.append(float(self._ep_returns[i]))
                self._ep_returns[i] = 0.0
            # next-step autoreset timeline: the done step returns the
            # FINAL frame (shift it in — it belongs to the old episode);
            # the RESET frame arrives one iteration later, i.e. raw_next
            # is a fresh frame exactly where the PREVIOUS step was done.
            obs = self.pipeline(raw_next, dones=self._last_done)
            self._last_done = done
        self.obs = obs
        self._env_steps_total += T * N
        # bootstrap value for the final observation of each env
        _, _, last_val = self._sample_fn(
            self.params, obs.astype(np.float32), self._key)
        completed = self._completed_returns[-100:]
        self._completed_returns = completed  # keep a sliding window
        return {
            "obs": obs_buf,
            "actions": act_buf,
            "logp": logp_buf,
            "values": val_buf,
            "rewards": rew_buf,
            "dones": done_buf,
            "reset_mask": reset_buf,
            "last_values": np.asarray(last_val),
            "episode_return_mean": float(np.mean(completed)) if completed
            else float("nan"),
            "num_episodes": len(completed),
            "env_steps": T * N,
        }

    def ping(self) -> str:
        return "pong"


class EnvRunnerGroup:
    """Actor pool of env runners (reference:
    rllib/env/env_runner_group.py:71 — foreach/weight sync)."""

    def __init__(self, num_env_runners: int = 1, remote: bool = True,
                 **runner_kwargs):
        self.remote = remote and num_env_runners > 0
        if not self.remote:
            self.local = SingleAgentEnvRunner(**runner_kwargs)
            self.runners = []
            return
        import ray_tpu

        cls = ray_tpu.remote(num_cpus=1)(SingleAgentEnvRunner)
        seed0 = runner_kwargs.pop("seed", 0)
        self.runners = [
            cls.remote(seed=seed0 + 1000 * i, **runner_kwargs)
            for i in range(num_env_runners)
        ]

    def sample(self, timeout: float = 300.0) -> list[dict]:
        if not self.remote:
            return [self.local.sample()]
        import ray_tpu

        return ray_tpu.get([r.sample.remote() for r in self.runners],
                           timeout=timeout)

    def sync_weights(self, weights, timeout: float = 120.0):
        """Broadcast learner weights (reference: weights ride the object
        store once, not per-runner — ppo.py:455)."""
        if not self.remote:
            self.local.set_weights(weights)
            return
        import ray_tpu

        ref = ray_tpu.put(weights)
        ray_tpu.get([r.set_weights.remote(ref) for r in self.runners],
                    timeout=timeout)

    def shutdown(self):
        import ray_tpu

        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass
        self.runners = []
