"""Replay buffers — uniform ring + prioritized (segment tree).

Reference parity: rllib/utils/replay_buffers/prioritized_episode_buffer
and the classic proportional PER machinery
(rllib/execution/segment_tree.py): O(log n) sum-tree sampling with
importance weights w_i = (N * P(i))^-beta / max_w, priorities updated
from TD errors after each learner step. Vectorized numpy tree (one
array, level arithmetic) instead of a node-object tree.
"""

from __future__ import annotations

import numpy as np


class SumTree:
    """Flat binary sum tree over `capacity` leaves (power-of-two padded).
    tree[1] is the total mass; leaf i lives at `self._leaf0 + i`."""

    def __init__(self, capacity: int):
        self._leaf0 = 1
        while self._leaf0 < capacity:
            self._leaf0 *= 2
        self.tree = np.zeros(2 * self._leaf0, np.float64)
        self.capacity = capacity

    def set(self, idx, value):
        idx = np.atleast_1d(np.asarray(idx, np.int64)) + self._leaf0
        self.tree[idx] = np.asarray(value, np.float64)
        parents = np.unique(idx // 2)
        while parents.size:
            self.tree[parents] = (self.tree[2 * parents] +
                                  self.tree[2 * parents + 1])
            parents = np.unique(parents // 2)
            parents = parents[parents >= 1]

    def total(self) -> float:
        return float(self.tree[1])

    def sample(self, prefix_sums: np.ndarray) -> np.ndarray:
        """Vector of prefix sums -> leaf indices (proportional)."""
        idx = np.ones(len(prefix_sums), np.int64)
        mass = np.asarray(prefix_sums, np.float64).copy()
        while idx[0] < self._leaf0:
            left = self.tree[2 * idx]
            go_right = mass > left
            mass = np.where(go_right, mass - left, mass)
            idx = 2 * idx + go_right
        return idx - self._leaf0


class PrioritizedReplayBuffer:
    """Proportional PER over transition dicts (reference:
    prioritized_episode_buffer.py / segment_tree.py)."""

    def __init__(self, capacity: int, alpha: float = 0.6,
                 beta: float = 0.4, eps: float = 1e-6, seed: int = 0):
        self.capacity = capacity
        self.alpha = alpha
        self.beta = beta
        self.eps = eps
        self._tree = SumTree(capacity)
        self._storage: dict[str, np.ndarray] = {}
        self._next = 0
        self._size = 0
        self._max_priority = 1.0
        self._rng = np.random.default_rng(seed)

    def __len__(self):
        return self._size

    def add_batch(self, batch: dict[str, np.ndarray]):
        n = len(next(iter(batch.values())))
        if not self._storage:
            for k, v in batch.items():
                v = np.asarray(v)
                self._storage[k] = np.zeros((self.capacity, *v.shape[1:]),
                                            v.dtype)
        idxs = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._storage[k][idxs] = v
        # new transitions get max priority so they are seen at least once
        self._tree.set(idxs, self._max_priority ** self.alpha)
        self._next = int((self._next + n) % self.capacity)
        self._size = min(self.capacity, self._size + n)

    def sample(self, batch_size: int) -> dict[str, np.ndarray]:
        total = self._tree.total()
        seg = total / batch_size
        prefix = (np.arange(batch_size) + self._rng.random(batch_size)) * seg
        idxs = self._tree.sample(np.minimum(prefix, total - 1e-9))
        idxs = np.minimum(idxs, self._size - 1)
        probs = self._tree.tree[self._tree._leaf0 + idxs] / total
        weights = (self._size * probs) ** (-self.beta)
        weights = weights / weights.max()
        out = {k: v[idxs] for k, v in self._storage.items()}
        out["weights"] = weights.astype(np.float32)
        out["idxs"] = idxs
        return out

    def update_priorities(self, idxs: np.ndarray, td_errors: np.ndarray):
        prio = np.abs(np.asarray(td_errors, np.float64)) + self.eps
        self._max_priority = max(self._max_priority, float(prio.max()))
        self._tree.set(np.asarray(idxs, np.int64), prio ** self.alpha)
