"""Algorithm / AlgorithmConfig — the unified driver every family shares.

Reference parity: rllib/algorithms/algorithm.py:241
(`Algorithm(Checkpointable, Trainable)`; `step()` :959 = one
training_step + periodic evaluation + metrics reduction) and
algorithm_config.py (fluent `.environment().env_runners().training()
.evaluation()` builder, `build_algo()`). The family subclasses implement
`setup()` + `training_step()`; the base owns:

- the Trainable contract (train/step/save_checkpoint/load_checkpoint) —
  so any algorithm runs as a Tune trial with checkpointed pause/resume;
- periodic evaluation on a dedicated local env runner;
- iteration/timestep bookkeeping and the shared MetricsLogger;
- Checkpointable state save/restore.

Tune integration: config fields may hold search markers
(`tune.grid_search([...])` or Domain objects); `Tuner(config)` extracts
them as the param space and runs `config.build()` per trial (reference:
Tuner("PPO", param_space=config)).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ray_tpu.rllib.checkpointable import Checkpointable
from ray_tpu.rllib.metrics import MetricsLogger
from ray_tpu.tune.trainable import Trainable


def _is_search_marker(v) -> bool:
    from ray_tpu.tune.search import Domain, _is_grid

    return isinstance(v, Domain) or _is_grid(v)


@dataclasses.dataclass
class AlgorithmConfig:
    """Fluent config base (reference: AlgorithmConfig — the same object
    carries env, env-runner, training, and evaluation settings and is
    the single source the algorithm builds from)."""

    env: str = "CartPole-v1"
    num_env_runners: int = 0
    num_envs_per_env_runner: int = 8
    rollout_fragment_length: int = 64
    gamma: float = 0.99
    lr: float = 3e-4
    hidden: tuple = (64, 64)
    framestack: int = 1
    model_config: dict | None = None
    seed: int = 0
    evaluation_interval: int = 0  # iterations between evals; 0 = never
    evaluation_duration: int = 3  # fragments sampled per eval

    def environment(self, env: str):
        self.env = env
        return self

    def env_runners(self, **kw):
        return self._apply(kw)

    def training(self, **kw):
        return self._apply(kw)

    def evaluation(self, **kw):
        return self._apply(kw)

    def _apply(self, kw: dict):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown option {k!r}")
            setattr(self, k, v)
        return self

    def copy(self) -> "AlgorithmConfig":
        import copy as _copy

        return _copy.deepcopy(self)

    def to_dict(self) -> dict:
        return {f.name: getattr(self, f.name)
                for f in dataclasses.fields(self)}

    def update_from_dict(self, d: dict):
        return self._apply(d)

    # -- tune integration -------------------------------------------------

    def extract_param_space(self) -> dict:
        """Fields holding search markers (grid_search dicts / Domain
        samplers) — the Tuner sweeps exactly these."""
        return {f.name: getattr(self, f.name)
                for f in dataclasses.fields(self)
                if _is_search_marker(getattr(self, f.name))}

    def validate(self):
        markers = self.extract_param_space()
        if markers:
            raise ValueError(
                f"config fields {sorted(markers)} still hold search "
                "markers — pass the config to Tuner, or set concrete "
                "values before build()")

    def build(self) -> "Algorithm":
        raise NotImplementedError


class Algorithm(Checkpointable, Trainable):
    """Shared driver skeleton. Subclasses implement `setup(config)` and
    `training_step()`; `train()` (inherited from Trainable) wraps one
    `step()` with iteration/time bookkeeping."""

    config_class: type = AlgorithmConfig
    STATE_COMPONENTS = ("_iteration", "_timesteps_total")

    def __init__(self, config=None):
        if config is None:
            config = self.config_class()
        elif isinstance(config, dict):
            config = self.config_class().update_from_dict(config)
        config.validate()
        # Trainable fields set inline (not via Trainable.__init__, which
        # would rebind self.config to a plain dict): the Trainable
        # contract here is only _iteration/_time_total + train()
        self.config = config
        self.metrics = MetricsLogger()
        self._iteration = 0
        self._time_total = 0.0
        self._timesteps_total = 0
        self._eval_group = None
        self.setup(config)

    def setup(self, config: "AlgorithmConfig"):
        raise NotImplementedError

    def training_step(self) -> dict:
        """One family-specific iteration: sample, learn, sync
        (reference: Algorithm.training_step — THE method families
        override)."""
        raise NotImplementedError

    def step(self) -> dict:
        """training_step + periodic evaluation (reference:
        Algorithm.step :959 — evaluate() interleaved by
        evaluation_interval)."""
        result = self.training_step() or {}
        sampled = result.get("num_env_steps_sampled")
        if sampled is not None:
            self._timesteps_total += int(sampled)
        else:
            # families reporting only the lifetime counter (DQN, IMPALA,
            # SAC) still advance the shared clock
            lifetime = result.get("num_env_steps_sampled_lifetime")
            if lifetime is not None:
                self._timesteps_total = int(lifetime)
        cfg = self.config
        if cfg.evaluation_interval and \
                (self._iteration + 1) % cfg.evaluation_interval == 0:
            result["evaluation"] = self.evaluate()
        return result

    # -- evaluation -------------------------------------------------------

    def evaluate(self) -> dict:
        """Sample evaluation episodes on a dedicated local runner with
        the current weights (reference: Algorithm.evaluate :1100 over the
        eval EnvRunnerGroup)."""
        from ray_tpu.rllib.env_runner import EnvRunnerGroup

        cfg = self.config
        if self._eval_group is None:
            self._eval_group = EnvRunnerGroup(
                num_env_runners=0, remote=False, env=cfg.env,
                num_envs=cfg.num_envs_per_env_runner,
                rollout_fragment_length=cfg.rollout_fragment_length,
                seed=cfg.seed + 100_000, hidden=cfg.hidden,
                framestack=cfg.framestack, model_config=cfg.model_config)
        self._eval_group.sync_weights(self.get_weights())
        returns, n_eps = [], 0
        for _ in range(max(1, cfg.evaluation_duration)):
            s = self._eval_group.sample()[0]
            if s["num_episodes"]:
                returns.append(s["episode_return_mean"])
                n_eps += s["num_episodes"]
        return {
            "episode_return_mean": float(np.mean(returns)) if returns
            else float("nan"),
            "num_episodes": n_eps,
        }

    # -- weights / checkpoint ---------------------------------------------

    def get_weights(self):
        raise NotImplementedError

    def save_checkpoint(self) -> dict:
        return self.get_state()

    def load_checkpoint(self, state: dict):
        self.set_state(state)

    # -- lifecycle --------------------------------------------------------

    def stop(self):
        if self._eval_group is not None:
            self._eval_group.shutdown()
            self._eval_group = None
        self.cleanup()
