"""Streaming executor — resource-managed, backpressured block execution.

Reference parity: the StreamingExecutor + ResourceManager +
backpressure policies (python/ray/data/_internal/execution/
streaming_executor.py:48, execution/resource_manager.py,
backpressure_policy.py:11 ConcurrencyCapBackpressurePolicy). The
executor admits new block tasks only while every policy allows it:
a concurrency cap bounds in-flight tasks, and a memory budget bounds
the BYTES of produced-but-unconsumed blocks (sizes read from the
owner's object metadata after task_done) so ingestion cannot crowd
training out of host RAM.
"""

from __future__ import annotations

import os
from typing import Iterator

# dashboard "data" view key ring: bounded records per driver process
_RUN_SEQ = 0
_MAX_RUN_RECORDS = 20


class ExecutionStats:
    __slots__ = ("in_flight", "buffered_bytes", "submitted", "yielded",
                 "backpressure_waits", "peak_buffered_bytes")

    def __init__(self):
        self.in_flight = 0
        self.buffered_bytes = 0
        self.submitted = 0
        self.yielded = 0
        self.backpressure_waits = 0
        self.peak_buffered_bytes = 0


class BackpressurePolicy:
    """Admission policy: may a new block task be submitted now?
    (reference: backpressure_policy.py:11)."""

    def can_add_input(self, stats: ExecutionStats) -> bool:
        raise NotImplementedError


class ConcurrencyCapBackpressurePolicy(BackpressurePolicy):
    def __init__(self, cap: int):
        self.cap = max(1, cap)

    def can_add_input(self, stats: ExecutionStats) -> bool:
        return stats.in_flight < self.cap


class MemoryBudgetBackpressurePolicy(BackpressurePolicy):
    """Bounds bytes of completed-but-unconsumed output blocks (the
    ResourceManager's object-store budget role). Always admits when
    nothing is in flight so execution cannot deadlock on one oversized
    block."""

    def __init__(self, budget_bytes: int):
        self.budget = max(1, budget_bytes)

    def can_add_input(self, stats: ExecutionStats) -> bool:
        return (stats.in_flight == 0
                or stats.buffered_bytes < self.budget)


def default_policies(max_in_flight: int | None = None,
                     memory_budget: int | None = None):
    import ray_tpu
    from ray_tpu.core import config as cfg

    cap = max_in_flight or max(
        2, int(ray_tpu.cluster_resources().get("CPU", 4)))
    budget = memory_budget or cfg.get("OBJECT_STORE_BYTES") // 4
    return [ConcurrencyCapBackpressurePolicy(cap),
            MemoryBudgetBackpressurePolicy(budget)]


def _ref_size(ref) -> int:
    """Serialized size of a completed driver-owned output (0 while
    pending/unknown) from the ownership table."""
    from ray_tpu.core.api import _global_runtime

    rt = _global_runtime()
    owned = getattr(rt, "_owned", None)
    if owned is None:
        # local-mode runtime has no ownership table: sizes unknown, the
        # memory policy degrades to the pure concurrency cap
        return 0
    st = owned.get(ref.id.binary())
    if st is not None and st.event.is_set():
        return int(st.size or 0)
    return 0


class StreamingExecutor:
    """Order-preserving streamed map of `submit(block_ref) -> ref` over
    input refs, gated by the policies. The consumer's iteration drives
    admission: blocks buffered ahead of the consumer count against the
    memory budget until yielded."""

    def __init__(self, policies=None):
        self.policies = policies
        self.stats = ExecutionStats()

    def run(self, input_refs, submit) -> Iterator:
        """`input_refs` may be a list, a lazy iterator, or an object
        with `poll(timeout) -> ("item", ref) | ("pending", None) |
        ("end", None)` (streaming read sources produce block refs
        incrementally via ObjectRefGenerator — reference: streaming read
        tasks feed the executor as blocks appear, not after the read
        completes). Polling keeps completed window results flowing to
        the consumer while the next input block is still being read."""
        import time as _t

        import ray_tpu

        policies = self.policies or default_policies()
        stats = self.stats
        window: list = []  # submitted, not yet yielded (input order)
        poll = getattr(input_refs, "poll", None)
        it = iter(input_refs) if poll is None else None
        exhausted = False
        # dashboard data view: one record per execution, refreshed as
        # blocks flow (reference: dashboard/modules/data). Keys rotate
        # through a bounded per-process ring so a long-lived driver
        # looping over dataset executions cannot grow head KV unbounded.
        global _RUN_SEQ
        _RUN_SEQ += 1
        run_id = f"exec_{os.getpid()}_{_RUN_SEQ % _MAX_RUN_RECORDS}"
        last_pub = 0.0

        def _pub(status):
            from ray_tpu import dashboard as _dash

            _dash.publish_view("data", run_id, {
                "status": status, "submitted": stats.submitted,
                "yielded": stats.yielded, "in_flight": stats.in_flight,
                "buffered_bytes": stats.buffered_bytes,
                "backpressure_waits": stats.backpressure_waits})

        try:
            yield from self._run_loop(input_refs, submit, policies, stats,
                                      window, poll, it, exhausted, _pub,
                                      last_pub)
        finally:
            # abandoned iteration (limit(), break, task error) must not
            # leave a forever-RUNNING record in the dashboard view
            _pub("FINISHED")

    def _run_loop(self, input_refs, submit, policies, stats, window, poll,
                  it, exhausted, _pub, last_pub):
        import time as _t

        import ray_tpu

        while not exhausted or window:
            if _t.monotonic() - last_pub > 2.0:
                last_pub = _t.monotonic()
                _pub("RUNNING")
            # account completed-but-unconsumed bytes
            stats.buffered_bytes = sum(_ref_size(r) for r in window)
            stats.peak_buffered_bytes = max(stats.peak_buffered_bytes,
                                            stats.buffered_bytes)
            done = [r for r in window if _ref_size(r) > 0]
            stats.in_flight = len(window) - len(done)
            if not exhausted:
                if all(p.can_add_input(stats) for p in policies):
                    if poll is not None:
                        kind, ref = poll(0.25)
                        if kind == "item":
                            window.append(submit(ref))
                            stats.submitted += 1
                            continue
                        if kind == "end":
                            exhausted = True
                            continue
                        # pending: fall through and drain the window
                    else:
                        try:
                            nxt = next(it)
                        except StopIteration:
                            exhausted = True
                        else:
                            window.append(submit(nxt))
                            stats.submitted += 1
                        continue
                else:
                    stats.backpressure_waits += 1  # admission deferred
            if window:
                head = window[0]
                ready, _ = ray_tpu.wait([head], num_returns=1, timeout=0.5)
                if ready:
                    window.pop(0)
                    stats.yielded += 1
                    yield head
                    continue
                _t.sleep(0.01)
            else:
                _t.sleep(0.005)
