"""Dataset — lazy plan + streaming execution over the task runtime.

Reference parity: ray.data (python/ray/data/dataset.py:147): a Dataset
is a lazy chain of operators over blocks; execution streams blocks
through remote tasks with bounded in-flight work (the StreamingExecutor
role, data/_internal/execution/streaming_executor.py:48), fusing
consecutive map-like operators into one task per block the way the
physical planner does. `compute="actors"` runs map_batches on a reusable
actor pool (actor_pool_map_operator.py) for stateful/expensive-setup
UDFs."""

from __future__ import annotations

import builtins
from typing import Any, Callable, Iterable, Iterator

import numpy as np

from ray_tpu.data.block import (
    batch_to_rows,
    rows_to_batch,
    split_blocks,
)

from ray_tpu.data.plan import (
    FilterRows,
    FlatMapRows,
    Limit,
    LogicalOperator,
    LogicalPlan,
    MapBatches as _MapBatchesOp,
    MapRows,
    Read as _ReadOp,
)

_DEFAULT_PARALLELISM = 8


def _fuse(ops: list[LogicalOperator]) -> Callable[[list], list]:
    """Optimized physical form of the operator chain (rule-based: limit
    pushdown, limit collapse, map fusion — see data/plan.py)."""
    return LogicalPlan(list(ops)).compile()


def _read_stream_impl(thunk):
    yield from thunk()


_READ_STREAM = None


def _read_stream_remote():
    """Module-level streaming read task (ONE stable function object, so
    the runtime's identity-keyed export cache ships it once per
    process, not once per iteration)."""
    global _READ_STREAM
    if _READ_STREAM is None:
        import ray_tpu

        _READ_STREAM = ray_tpu.remote(num_cpus=1)(_read_stream_impl)
    return _READ_STREAM


class _StreamingInput:
    """Pollable block-ref source over streaming read tasks, drained in
    task order (producers all run concurrently; items buffer at the
    owner). The StreamingExecutor polls so already-transformed blocks
    keep flowing while the next read block is still being produced."""

    def __init__(self, gens):
        self._gens = gens
        self._i = 0

    def poll(self, timeout: float):
        from ray_tpu.core import exceptions as _exc

        while self._i < len(self._gens):
            try:
                return ("item", self._gens[self._i]._next_sync(timeout))
            except StopIteration:
                self._i += 1
                continue
            except _exc.GetTimeoutError:
                return ("pending", None)
        return ("end", None)

    def __iter__(self):
        while True:
            kind, ref = self.poll(30.0)
            if kind == "end":
                return
            if kind == "item":
                yield ref


class Dataset:
    def __init__(self, block_refs: list,
                 ops: list[LogicalOperator] | None = None,
                 stream_thunks: list | None = None):
        self._block_refs = block_refs  # ObjectRefs of input blocks
        self._ops = ops or []
        # streaming read source: generator thunks run as
        # num_returns="streaming" tasks; block refs materialize DURING
        # iteration (read_datasource(streaming=True))
        self._stream_thunks = stream_thunks

    def _input_blocks(self):
        """Input block refs: the eager list, or a pollable source pulling
        from streaming read tasks as the producers yield blocks."""
        if self._stream_thunks is None:
            return list(self._block_refs)
        gens = [_read_stream_remote().options(
            num_returns="streaming").remote(t)
            for t in self._stream_thunks]
        return _StreamingInput(gens)

    def _is_plain_stream(self) -> bool:
        """No side stages outside the op list (actor map stage or
        streaming source) — the parts an op-chain consumer can't see."""
        return self._stream_thunks is None and \
            getattr(self, "_actor_stage", None) is None

    def _is_plain_blocks(self) -> bool:
        """True when _block_refs already IS the dataset: no pending
        ops, no actor map stage, no streaming source."""
        return not self._ops and self._is_plain_stream()

    def _require_eager(self, what: str):
        if self._stream_thunks is not None:
            raise ValueError(
                f"{what} needs a known block list; call materialize() on "
                f"this streaming dataset first")

    # ------------------------------------------------------------ create

    @staticmethod
    def from_items(items: Iterable, parallelism: int = _DEFAULT_PARALLELISM
                   ) -> "Dataset":
        """Eager in-memory blocks (items are already resident in the
        driver). For deferred materialization of generated data use
        read_datasource(ItemsDatasource(...)) — same seam as range()."""
        import ray_tpu

        blocks = split_blocks(items, parallelism)
        return Dataset([ray_tpu.put(b) for b in blocks])

    @staticmethod
    def range(n: int, parallelism: int = _DEFAULT_PARALLELISM) -> "Dataset":
        """Lazy integer range THROUGH the datasource seam: blocks
        materialize inside read tasks, never on the driver (reference:
        ray.data.range is a Datasource read)."""
        from ray_tpu.data.datasource import RangeDatasource

        return read_datasource(RangeDatasource(n), parallelism=parallelism)

    # ------------------------------------------------------------ transforms

    def _with(self, op: LogicalOperator) -> "Dataset":
        return Dataset(self._block_refs, self._ops + [op],
                       stream_thunks=self._stream_thunks)

    def map(self, fn: Callable) -> "Dataset":
        return self._with(MapRows(fn))

    def filter(self, fn: Callable) -> "Dataset":
        return self._with(FilterRows(fn))

    def flat_map(self, fn: Callable) -> "Dataset":
        return self._with(FlatMapRows(fn))

    def limit(self, n: int) -> "Dataset":
        """GLOBAL row cap (reference: Dataset.limit). As a plan suffix
        (possibly under 1:1 maps, which the optimizer pushes it past)
        the consuming iterator stops the stream at n rows; when a
        non-1:1 operator FOLLOWS the limit, execution materializes the
        capped rows first (`_split_at_mid_limit`) so downstream sees
        exactly n rows, not n per block."""
        return self._with(Limit(n))

    def _split_at_mid_limit(self) -> "Dataset | None":
        """If the plan has a Limit followed by any non-1:1 operator,
        return an equivalent dataset with everything up to (and incl.)
        that limit MATERIALIZED — per-block limiting alone would leak
        n rows per block into the downstream operator."""
        last = None
        for i, op in enumerate(self._ops):
            if isinstance(op, Limit) and any(
                    not o.one_to_one and not isinstance(o, Limit)
                    for o in self._ops[i + 1:]):
                last = i
        if last is None:
            return None
        prefix = Dataset(self._block_refs, self._ops[:last + 1],
                         stream_thunks=self._stream_thunks)
        rows = prefix.take_all()  # iterator cap enforces the global n
        out = Dataset.from_items(rows, max(1, len(self._block_refs)))
        return Dataset(out._block_refs, self._ops[last + 1:])

    def map_batches(self, fn: Callable, *, batch_format: str = "numpy",
                    compute: str | None = None, num_actors: int = 2
                    ) -> "Dataset":
        def apply(block: list) -> list:
            from ray_tpu.data.block import (
                block_num_rows,
                is_columnar,
                to_batch,
                to_rows,
            )

            if not block_num_rows(block):
                return block
            if batch_format == "numpy":
                # columnar in, columnar out: a dict-of-numpy (or bare
                # ndarray) result STAYS columnar — the block moves
                # through the store with out-of-band buffers and the
                # next numpy stage consumes it without row conversion
                # (reference: Arrow blocks flowing between map stages)
                out = fn(to_batch(block))
                if is_columnar(out):
                    return out
                return batch_to_rows(out) if isinstance(out, dict) \
                    else list(out)
            if batch_format == "pyarrow":
                import pyarrow as pa

                rows = [r if isinstance(r, dict) else {"value": r}
                        for r in to_rows(block)]
                out = fn(pa.Table.from_pylist(rows))
                return out.to_pylist()
            out = fn(to_rows(block))
            return list(out)

        if compute == "actors":
            ds = Dataset(self._block_refs, self._ops,
                         stream_thunks=self._stream_thunks)
            ds._actor_stage = (apply, num_actors)  # type: ignore[attr-defined]
            return ds
        return self._with(_MapBatchesOp(apply))

    def repartition(self, num_blocks: int) -> "Dataset":
        """Rebalance into `num_blocks` blocks (reference:
        Dataset.repartition). Columnar outputs stay columnar — the
        blocks are concatenated and re-split as column views, never as
        rows."""
        import ray_tpu

        from ray_tpu.data.block import (
            columnar_kinds_compatible,
            concat_batches,
            is_columnar,
            split_columnar,
        )

        blocks = list(self._iter_output_blocks())
        if blocks and all(is_columnar(b) for b in blocks) and \
                columnar_kinds_compatible(blocks):
            whole = concat_batches(blocks)
            return Dataset([ray_tpu.put(b)
                            for b in split_columnar(whole, num_blocks)])
        rows = [r for b in blocks for r in _to_rows(b)]
        return Dataset.from_items(rows, num_blocks)

    def join(self, other: "Dataset", on: str, how: str = "inner",
             num_blocks: int | None = None) -> "Dataset":
        """Hash join on a key column (reference: Dataset.join — hash
        shuffle co-partitioning both sides, then per-partition probe).
        `how`: "inner" or "left"; right-side duplicate columns get a
        "_1" suffix."""
        if how not in ("inner", "left"):
            raise ValueError(f"how must be 'inner' or 'left', got {how!r}")
        from ray_tpu.data.exchange import join_exchange

        lrefs, lops = self._exchange_input()
        rrefs, rops = other._exchange_input()
        refs = join_exchange(lrefs, _fuse(lops), rrefs, _fuse(rops),
                             self._out_partitions(num_blocks), on, how)
        return Dataset(refs)

    def union(self, *others: "Dataset") -> "Dataset":
        """Concatenate datasets block-wise (reference: Dataset.union —
        no driver materialization of rows; pending plans execute into
        blocks first)."""
        refs = []
        for ds in (self, *others):
            if not ds._is_plain_blocks():
                ds = ds.materialize()
            refs.extend(ds._block_refs)
        return Dataset(refs)

    def zip(self, other: "Dataset") -> "Dataset":
        """Merge two datasets column-wise, row for row (reference:
        Dataset.zip — equal row counts required; duplicate column names
        from the right side get a "_1" suffix; non-dict rows pair into
        tuples). Runs as one remote zip task per left block, with the
        right side re-sliced to align — columnar blocks merge as column
        dicts without row conversion."""
        import ray_tpu

        left = self if self._is_plain_blocks() else self.materialize()
        right = other if other._is_plain_blocks() else other.materialize()

        @ray_tpu.remote(num_cpus=1)
        def _nrows(b):
            from ray_tpu.data.block import block_num_rows

            return block_num_rows(b)

        lc = ray_tpu.get([_nrows.remote(r) for r in left._block_refs],
                         timeout=600)
        rc = ray_tpu.get([_nrows.remote(r) for r in right._block_refs],
                         timeout=600)
        if sum(lc) != sum(rc):
            raise ValueError(
                f"zip: datasets must have equal row counts "
                f"({sum(lc)} vs {sum(rc)})")

        # right-block spans covering each left block's row range
        r_starts = []
        acc = 0
        for c in rc:
            r_starts.append(acc)
            acc += c
        out_refs = []
        pos = 0
        for li, lref in enumerate(left._block_refs):
            lo, hi = pos, pos + lc[li]
            pos = hi
            spans, rrefs = [], []
            for ri, (rs, c) in enumerate(zip(r_starts, rc)):
                re_ = rs + c
                if re_ <= lo or rs >= hi or c == 0:
                    continue
                spans.append((len(rrefs), max(lo, rs) - rs,
                              min(hi, re_) - rs))
                rrefs.append(right._block_refs[ri])
            out_refs.append(ray_tpu.remote(num_cpus=1)(
                _zip_blocks_fn).remote(lref, spans, *rrefs))
        return Dataset(out_refs)

    # ---------------------------------------------------------- all-to-all

    def _out_partitions(self, num_blocks: int | None) -> int:
        return max(1, num_blocks or len(self._block_refs))

    def _exchange_input(self) -> tuple[list, list]:
        """(block_refs, ops) to feed an all-to-all exchange. A plan
        containing a Limit must be materialized first — the exchange's
        map stage is per-block, so a per-block limit would leak n rows
        PER BLOCK into the shuffle instead of n total."""
        if any(isinstance(o, Limit) for o in self._ops) or \
                not self._is_plain_stream():
            rows = self.take_all()
            ds = Dataset.from_items(rows, max(1, len(self._block_refs)))
            return ds._block_refs, []
        return self._block_refs, self._ops

    def random_shuffle(self, *, seed: int | None = None,
                       num_blocks: int | None = None) -> "Dataset":
        """Global row shuffle via a map/partition/reduce exchange
        (reference: Dataset.random_shuffle, data/dataset.py:1374)."""
        from ray_tpu.data.exchange import shuffle_exchange

        refs, ops = self._exchange_input()
        refs = shuffle_exchange(refs, _fuse(ops),
                                self._out_partitions(num_blocks), seed)
        return Dataset(refs)

    def sort(self, key=None, descending: bool = False,
             num_blocks: int | None = None) -> "Dataset":
        """Distributed sample-partitioned sort (reference: Dataset.sort,
        data/dataset.py:2472). `key` is a column name, a callable, or
        None for the row itself."""
        from ray_tpu.data.exchange import sort_exchange

        refs, ops = self._exchange_input()
        refs = sort_exchange(refs, _fuse(ops),
                             self._out_partitions(num_blocks), key,
                             descending)
        ds = Dataset(refs)
        ds._sorted_desc = descending  # type: ignore[attr-defined]
        return ds

    def groupby(self, key) -> "GroupedData":
        """Hash-partitioned groupby (reference: Dataset.groupby,
        data/dataset.py:2099 -> GroupedData)."""
        return GroupedData(self, key)

    def unique(self, key=None) -> list:
        from ray_tpu.data.exchange import groupby_exchange

        refs, ops = self._exchange_input()
        refs = groupby_exchange(refs, _fuse(ops),
                                self._out_partitions(None), key,
                                lambda k, rows: k)
        return [v for r in Dataset(refs).iter_rows() for v in [r]]

    def shard(self, num_shards: int, index: int) -> "Dataset":
        """Deterministic block-wise shard (per-host Train ingestion)."""
        self._require_eager("shard()")
        refs = [r for i, r in enumerate(self._block_refs)
                if i % num_shards == index]
        return Dataset(refs or [], list(self._ops))

    def split(self, n: int) -> list["Dataset"]:
        return [self.shard(n, i) for i in builtins.range(n)]

    # ------------------------------------------------------------ execution

    def _execute(self, max_in_flight: int | None = None,
                 memory_budget: int | None = None) -> Iterator:
        """Stream result block refs in input order under the resource-
        managed streaming executor: a concurrency cap on in-flight tasks
        plus a MEMORY budget on produced-but-unconsumed block bytes
        (reference: streaming_executor.py:48 + resource_manager.py +
        backpressure_policy.py:11)."""
        import ray_tpu

        actor_stage = getattr(self, "_actor_stage", None)
        if not self._ops and actor_stage is None:
            yield from self._input_blocks()
            return
        if actor_stage is None:
            split = self._split_at_mid_limit()
            if split is not None:
                yield from split._execute(max_in_flight, memory_budget)
                return
        fused = _fuse(self._ops)
        from ray_tpu.data.executor import StreamingExecutor, default_policies

        if actor_stage is None:
            @ray_tpu.remote(num_cpus=1)
            def _apply_block(block):
                return fused(block)

            executor = StreamingExecutor(default_policies(
                max_in_flight=max_in_flight, memory_budget=memory_budget))
            self._last_executor = executor  # observability / tests
            yield from executor.run(self._input_blocks(),
                                    lambda ref: _apply_block.remote(ref))
            return

        apply_fn, num_actors = actor_stage

        import ray_tpu as rt

        class _PoolWorker:
            def ready(self):
                return True

            def apply(self, block):
                return apply_fn(fused(block))

        cls = rt.remote(num_cpus=1)(_PoolWorker)
        actors = [cls.remote() for _ in builtins.range(num_actors)]
        # wait for the pool to come up with a generous budget: worker
        # spawn under load can exceed the per-call actor-ready timeout,
        # and a half-started pool surfaces as ActorUnavailableError mid-
        # stream (reference: ActorPool waits on ready refs)
        rt.get([a.ready.remote() for a in actors], timeout=180)
        try:
            # same resource-managed executor as the task path: the actor
            # pool must not outrun the consumer's memory budget either
            executor = StreamingExecutor(default_policies(
                max_in_flight=max_in_flight, memory_budget=memory_budget))
            self._last_executor = executor
            counter = iter(builtins.range(1 << 62))

            def submit(ref):
                return actors[next(counter) % num_actors].apply.remote(ref)

            yield from executor.run(self._input_blocks(), submit)
        finally:
            for a in actors:
                try:
                    rt.kill(a)
                except Exception:  # noqa: BLE001
                    pass

    def materialize(self) -> "Dataset":
        import ray_tpu

        if LogicalPlan(self._ops).global_limit() is not None:
            # a suffix limit is a GLOBAL cap enforced by the row
            # iterator; raw _execute blocks would carry n rows per block
            return Dataset.from_items(self.take_all(),
                                      max(1, len(self._block_refs)))
        refs = list(self._execute())
        # re-put to pin materialized blocks under driver ownership
        blocks = ray_tpu.get(refs, timeout=600)
        return Dataset([ray_tpu.put(b) for b in blocks])

    # ------------------------------------------------------------ consume

    def _iter_output_blocks(self) -> Iterator:
        """Executed blocks in their native format (rows or columnar),
        sliced to the plan's global Limit."""
        import ray_tpu

        from ray_tpu.data.block import block_num_rows, slice_block

        # a plan-suffix Limit caps the GLOBAL row count: stop the stream
        # (and its in-flight work) as soon as it is met
        cap = LogicalPlan(self._ops).global_limit()
        n = 0
        for ref in self._execute():
            block = ray_tpu.get(ref, timeout=600)
            rows = block_num_rows(block)
            if cap is not None and n + rows > cap:
                block = slice_block(block, 0, cap - n)
                rows = cap - n
            if rows:
                n += rows
                yield block
            if cap is not None and n >= cap:
                return

    def iter_rows(self) -> Iterator:
        from ray_tpu.data.block import to_rows

        for block in self._iter_output_blocks():
            yield from to_rows(block)

    def explain(self) -> str:
        """The optimized logical plan (reference: Dataset plan repr)."""
        return LogicalPlan(self._ops).optimized().describe()

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy") -> Iterator:
        """Re-batch across block boundaries (reference:
        data/_internal/iterator/). The numpy path is COLUMNAR end to
        end: blocks are consumed as dict-of-numpy batches and re-cut by
        slicing/concatenating column arrays — rows are never
        materialized, and a batch fully inside one block is a numpy
        VIEW of the shm-backed columns (zero copy)."""
        if batch_format == "numpy":
            from ray_tpu.data.block import (
                block_num_rows,
                concat_batches,
                slice_block,
                to_batch,
            )

            pieces: list = []
            have = 0
            for block in self._iter_output_blocks():
                batch = to_batch(block)
                start = 0
                n = block_num_rows(batch)
                while n - start >= batch_size - have:
                    take = batch_size - have
                    pieces.append(slice_block(batch, start, start + take))
                    start += take
                    yield concat_batches(pieces)
                    pieces, have = [], 0
                if start < n:
                    pieces.append(slice_block(batch, start, n))
                    have += n - start
            if have:
                yield concat_batches(pieces)
            return

        def fmt(rows):
            if batch_format == "pyarrow":
                import pyarrow as pa

                return pa.Table.from_pylist(
                    [r if isinstance(r, dict) else {"value": r}
                     for r in rows])
            return rows

        buf: list = []
        for row in self.iter_rows():
            buf.append(row)
            if len(buf) >= batch_size:
                yield fmt(buf)
                buf = []
        if buf:
            yield fmt(buf)

    def iter_jax_batches(self, *, batch_size: int = 256,
                         sharding=None, mesh=None,
                         drop_last: bool = True) -> Iterator:
        """Device-feed iterator (reference role: iter_torch_batches,
        data/_internal/iterator/iter_batches.py — host block →
        device-resident training batch). Each fixed-size numpy batch is
        `jax.device_put` onto the mesh with a NamedSharding whose batch
        dim spans the replica axes, so the ingest pipeline hands the
        train step GLOBAL arrays ready for a pjit'd step.

        Pass either `sharding` (any jax Sharding, applied to every leaf)
        or `mesh` (batch dim sharded over the mesh's replica-ish axes,
        same rule as train.spmd.batch_shardings). With neither, batches
        land on the default device. Overlap comes from XLA's async
        dispatch: device_put returns immediately, so the next host
        batch's prep runs while the previous transfer is in flight.
        `drop_last=True` keeps every yielded batch shape-identical —
        required under jit (no recompiles) and for even sharding."""
        import jax

        if sharding is None and mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            from ray_tpu.parallel.mesh import BATCH_AXES

            axes = tuple(a for a in BATCH_AXES
                         if dict(mesh.shape).get(a, 1) > 1)
            sharding = NamedSharding(mesh,
                                     PartitionSpec(axes if axes else None))
        if sharding is not None and not drop_last:
            # a partial last batch's row count need not divide the shard
            # count — device_put would explode mid-iteration; fail early
            raise ValueError(
                "iter_jax_batches: drop_last=False cannot be combined "
                "with a sharding/mesh (the final partial batch may not "
                "divide evenly across shards)")

        def put(batch):
            if sharding is None:
                return jax.tree.map(jax.device_put, batch)
            return jax.tree.map(lambda a: jax.device_put(a, sharding),
                                batch)

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy"):
            leaves = jax.tree.leaves(batch)
            if not leaves:
                continue
            if drop_last and len(leaves[0]) < batch_size:
                continue
            yield put(batch)

    def take(self, n: int = 20) -> list:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> list:
        return list(self.iter_rows())

    def count(self) -> int:
        import ray_tpu

        from ray_tpu.data.block import block_num_rows

        if self._is_plain_blocks():
            return sum(block_num_rows(b) for b in
                       ray_tpu.get(list(self._block_refs), timeout=600))
        return sum(1 for _ in self.iter_rows())

    def num_blocks(self) -> int:
        return len(self._block_refs)

    def sum(self) -> Any:
        return sum(self.iter_rows())

    def write_parquet(self, directory: str) -> list[str]:
        """One parquet file per block via Arrow (reference:
        Dataset.write_parquet)."""
        import os as _os

        import pyarrow as pa
        import pyarrow.parquet as pq

        import ray_tpu

        if LogicalPlan(self._ops).global_limit() is not None:
            # enforce the GLOBAL cap before writing (per-block slices
            # would write n rows per block)
            return self.materialize().write_parquet(directory)
        _os.makedirs(directory, exist_ok=True)
        paths = []
        for i, ref in enumerate(self._execute()):
            block = ray_tpu.get(ref, timeout=600)
            path = _os.path.join(directory, f"part-{i:05d}.parquet")
            rows = [r if isinstance(r, dict) else {"value": r}
                    for r in _to_rows(block)]
            pq.write_table(pa.Table.from_pylist(rows), path)
            paths.append(path)
        return paths

    def write_jsonl(self, directory: str) -> list[str]:
        """One output file per block (reference: write_* produce one
        file per block/task)."""
        import json
        import os as _os

        import ray_tpu

        if LogicalPlan(self._ops).global_limit() is not None:
            return self.materialize().write_jsonl(directory)
        _os.makedirs(directory, exist_ok=True)
        paths = []
        for i, ref in enumerate(self._execute()):
            block = ray_tpu.get(ref, timeout=600)
            path = _os.path.join(directory, f"part-{i:05d}.jsonl")
            with open(path, "w") as f:
                for row in _to_rows(block):
                    # numpy values serialize as numbers/lists, not strs
                    f.write(json.dumps(row, default=_json_default) + "\n")
            paths.append(path)
        return paths

    def __repr__(self):
        ops = "->".join(o.name for o in self._ops) or "source"
        return f"Dataset(blocks={len(self._block_refs)}, plan={ops})"


class AggregateFn:
    """A named aggregation over a group's rows (reference:
    ray.data.aggregate.AggregateFn — here list-at-once instead of
    accumulate/merge, proportionate to block-resident groups)."""

    def __init__(self, name: str, fn: Callable[[list], Any]):
        self.name = name
        self.fn = fn


def Count() -> AggregateFn:  # noqa: N802 — reference-parity naming
    return AggregateFn("count", len)


def Sum(col=None) -> AggregateFn:  # noqa: N802
    return AggregateFn(f"sum({col})" if col else "sum",
                       lambda rows: sum(_col(rows, col)))


def Mean(col=None) -> AggregateFn:  # noqa: N802
    return AggregateFn(f"mean({col})" if col else "mean",
                       lambda rows: sum(_col(rows, col)) / len(rows))


def Min(col=None) -> AggregateFn:  # noqa: N802
    return AggregateFn(f"min({col})" if col else "min",
                       lambda rows: min(_col(rows, col)))


def Max(col=None) -> AggregateFn:  # noqa: N802
    return AggregateFn(f"max({col})" if col else "max",
                       lambda rows: max(_col(rows, col)))


def Std(col=None) -> AggregateFn:  # noqa: N802
    def std(rows):
        vals = list(_col(rows, col))
        m = sum(vals) / len(vals)
        return (sum((v - m) ** 2 for v in vals) / max(1, len(vals) - 1)) ** 0.5

    return AggregateFn(f"std({col})" if col else "std", std)


def _col(rows, col):
    return (r[col] for r in rows) if col is not None else rows


class GroupedData:
    """Reference parity: ray.data.grouped_data.GroupedData — the result
    of Dataset.groupby; aggregations run as the reduce side of a hash
    exchange."""

    def __init__(self, ds: Dataset, key):
        self._ds = ds
        self._key = key

    def _exchange(self, group_reducer) -> Dataset:
        from ray_tpu.data.exchange import groupby_exchange

        refs, ops = self._ds._exchange_input()
        refs = groupby_exchange(
            refs, _fuse(ops),
            self._ds._out_partitions(None), self._key, group_reducer)
        return Dataset(refs)

    def aggregate(self, *aggs: AggregateFn) -> Dataset:
        key_name = self._key if isinstance(self._key, str) else "key"
        names = [a.name for a in aggs]
        fns = [a.fn for a in aggs]

        def reduce_group(k, rows):
            out = {key_name: k}
            for name, fn in zip(names, fns):
                out[name] = fn(rows)
            return out

        return self._exchange(reduce_group)

    def count(self) -> Dataset:
        return self.aggregate(Count())

    def sum(self, col=None) -> Dataset:
        return self.aggregate(Sum(col))

    def mean(self, col=None) -> Dataset:
        return self.aggregate(Mean(col))

    def min(self, col=None) -> Dataset:
        return self.aggregate(Min(col))

    def max(self, col=None) -> Dataset:
        return self.aggregate(Max(col))

    def std(self, col=None) -> Dataset:
        return self.aggregate(Std(col))

    def map_groups(self, fn: Callable[[list], Any]) -> Dataset:
        """fn(rows_of_one_group) -> output row(s); lists are flattened
        (reference: GroupedData.map_groups)."""
        ds = self._exchange(lambda k, rows: fn(rows))
        return ds.flat_map(lambda r: r if isinstance(r, list) else [r])


def _to_rows(block):
    from ray_tpu.data.block import to_rows

    return to_rows(block)


def _json_default(o):
    if isinstance(o, np.ndarray):
        return o.tolist()
    if hasattr(o, "item"):
        try:
            return o.item()  # numpy scalar
        except ValueError:
            pass
    return str(o)


def _zip_blocks_fn(lb, spans, *rbs):
    """Zip one left block with the right-side slices covering its row
    range. Columnar x columnar merges column dicts; otherwise rows pair
    into merged dicts / tuples."""
    from ray_tpu.data.block import (
        concat_batches,
        is_columnar,
        slice_block,
        to_rows,
    )

    pieces = [slice_block(rbs[i], s, e) for i, s, e in spans]
    if is_columnar(lb) and isinstance(lb, dict) and pieces and \
            all(isinstance(p, dict) and is_columnar(p) for p in pieces):
        rbat = concat_batches(pieces)
        out = dict(lb)
        for k, v in rbat.items():
            out[k if k not in out else k + "_1"] = v
        return out
    lr = to_rows(lb)
    rr = [r for p in pieces for r in to_rows(p)]
    out = []
    for a, b in zip(lr, rr):
        if isinstance(a, dict) and isinstance(b, dict):
            m = dict(a)
            for k, v in b.items():
                m[k if k not in m else k + "_1"] = v
            out.append(m)
        else:
            out.append((a, b))
    return out


def from_items(items, parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    return Dataset.from_items(items, parallelism)


def range(n: int, parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:  # noqa: A001
    return Dataset.range(n, parallelism)


def from_numpy(arr, parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    """Columnar blocks straight from ndarray(s) — a dict maps column
    names to arrays (reference: from_numpy building Arrow blocks). The
    splits are views; ray_tpu.put ships them with out-of-band buffers,
    so neither split nor store pays a row conversion."""
    import ray_tpu

    from ray_tpu.data.block import split_columnar

    if not isinstance(arr, (dict, np.ndarray)):
        arr = np.asarray(arr)
    return Dataset([ray_tpu.put(b)
                    for b in split_columnar(arr, parallelism)])


def read_datasource(datasource, *,
                    parallelism: int = _DEFAULT_PARALLELISM,
                    streaming: bool = False) -> Dataset:
    """Lazy Dataset over any Datasource (reference:
    ray.data.read_datasource; data/datasource/datasource.py contract).
    Each ReadTask materializes its block INSIDE a remote task — the
    driver only ships the thunks.

    With streaming=True, the read runs as num_returns="streaming" tasks
    over `get_block_streams`: each producer yields blocks incrementally
    (e.g. one per file in a group) and downstream consumes block 0 while
    block k is still being read (reference: streaming read tasks under
    ray.data's streaming execution)."""
    import ray_tpu

    if streaming:
        thunks = datasource.get_block_streams(parallelism)
        if not thunks:
            raise ValueError(f"{datasource.name} produced no block streams")
        return Dataset([], stream_thunks=thunks)
    tasks = datasource.get_read_tasks(parallelism)
    if not tasks:
        raise ValueError(f"{datasource.name} produced no read tasks")
    refs = [ray_tpu.put([t]) for t in tasks]
    return Dataset(refs, [_ReadOp(lambda block: block[0]())])


def _read_files(source_cls, paths, parallelism, *args, streaming=False):
    """File read_* share one recipe: default parallelism is ONE task
    per file (the natural split unit — a 1000-file directory must not
    collapse to 8 serial readers); an explicit value groups files."""
    ds = source_cls(paths, *args)
    return read_datasource(
        ds, parallelism=parallelism if parallelism is not None
        else max(1, len(ds.paths)), streaming=streaming)


def read_text(paths, *, parallelism: int | None = None,
              streaming: bool = False) -> Dataset:
    """One row per line (reference: ray.data.read_text). The line
    splitting runs in the native mmap scanner (data/lineio.py ->
    _native/lineio.cc) inside the read task."""
    from ray_tpu.data.datasource import TextDatasource

    return _read_files(TextDatasource, paths, parallelism,
                       streaming=streaming)


def read_csv(paths, *, parallelism: int | None = None,
             streaming: bool = False) -> Dataset:
    """Dict rows from CSV with a header (reference: ray.data.read_csv;
    stdlib csv instead of Arrow)."""
    from ray_tpu.data.datasource import CSVDatasource

    return _read_files(CSVDatasource, paths, parallelism,
                       streaming=streaming)


def read_json(paths, *, parallelism: int | None = None,
              streaming: bool = False) -> Dataset:
    """JSONL rows (reference: ray.data.read_json)."""
    from ray_tpu.data.datasource import JSONLDatasource

    return _read_files(JSONLDatasource, paths, parallelism,
                       streaming=streaming)


def read_parquet(paths, columns: list[str] | None = None, *,
                 parallelism: int | None = None) -> Dataset:
    """Columnar parquet read — one Arrow table per file, read inside
    tasks (reference: ray.data.read_parquet backed by
    data/_internal/arrow_block.py). Rows surface as dicts; use
    map_batches(batch_format="pyarrow") to stay columnar."""
    from ray_tpu.data.datasource import ParquetDatasource

    return _read_files(ParquetDatasource, paths, parallelism, columns)


def from_arrow(table, parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    """Dataset from a pyarrow Table (reference: ray.data.from_arrow)."""
    return Dataset.from_items(table.to_pylist(), parallelism)
