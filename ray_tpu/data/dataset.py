"""Dataset — lazy plan + streaming execution over the task runtime.

Reference parity: ray.data (python/ray/data/dataset.py:147): a Dataset
is a lazy chain of operators over blocks; execution streams blocks
through remote tasks with bounded in-flight work (the StreamingExecutor
role, data/_internal/execution/streaming_executor.py:48), fusing
consecutive map-like operators into one task per block the way the
physical planner does. `compute="actors"` runs map_batches on a reusable
actor pool (actor_pool_map_operator.py) for stateful/expensive-setup
UDFs."""

from __future__ import annotations

import builtins
from typing import Any, Callable, Iterable, Iterator

import numpy as np

from ray_tpu.data.block import (
    batch_to_rows,
    rows_to_batch,
    split_blocks,
)

_DEFAULT_PARALLELISM = 8


class _Op:
    """One logical operator: fn maps a block (list of rows) -> block."""

    def __init__(self, kind: str, fn: Callable[[list], list]):
        self.kind = kind
        self.fn = fn


def _fuse(ops: list[_Op]) -> Callable[[list], list]:
    def fused(block: list) -> list:
        for op in ops:
            block = op.fn(block)
        return block

    return fused


class Dataset:
    def __init__(self, block_refs: list, ops: list[_Op] | None = None):
        self._block_refs = block_refs  # ObjectRefs of input blocks
        self._ops = ops or []

    # ------------------------------------------------------------ create

    @staticmethod
    def from_items(items: Iterable, parallelism: int = _DEFAULT_PARALLELISM
                   ) -> "Dataset":
        import ray_tpu

        blocks = split_blocks(items, parallelism)
        return Dataset([ray_tpu.put(b) for b in blocks])

    @staticmethod
    def range(n: int, parallelism: int = _DEFAULT_PARALLELISM) -> "Dataset":
        return Dataset.from_items(builtins.range(n), parallelism)

    # ------------------------------------------------------------ transforms

    def _with(self, op: _Op) -> "Dataset":
        return Dataset(self._block_refs, self._ops + [op])

    def map(self, fn: Callable) -> "Dataset":
        return self._with(_Op("map", lambda b: [fn(r) for r in b]))

    def filter(self, fn: Callable) -> "Dataset":
        return self._with(_Op("filter", lambda b: [r for r in b if fn(r)]))

    def flat_map(self, fn: Callable) -> "Dataset":
        return self._with(
            _Op("flat_map", lambda b: [o for r in b for o in fn(r)]))

    def map_batches(self, fn: Callable, *, batch_format: str = "numpy",
                    compute: str | None = None, num_actors: int = 2
                    ) -> "Dataset":
        def apply(block: list) -> list:
            if not block:
                return block
            if batch_format == "numpy":
                out = fn(rows_to_batch(block))
                return batch_to_rows(out)
            if batch_format == "pyarrow":
                import pyarrow as pa

                rows = [r if isinstance(r, dict) else {"value": r}
                        for r in block]
                out = fn(pa.Table.from_pylist(rows))
                return out.to_pylist()
            out = fn(block)
            return list(out)

        if compute == "actors":
            ds = Dataset(self._block_refs, self._ops)
            ds._actor_stage = (apply, num_actors)  # type: ignore[attr-defined]
            return ds
        return self._with(_Op("map_batches", apply))

    def repartition(self, num_blocks: int) -> "Dataset":
        rows = self.take_all()
        return Dataset.from_items(rows, num_blocks)

    # ---------------------------------------------------------- all-to-all

    def _out_partitions(self, num_blocks: int | None) -> int:
        return max(1, num_blocks or len(self._block_refs))

    def random_shuffle(self, *, seed: int | None = None,
                       num_blocks: int | None = None) -> "Dataset":
        """Global row shuffle via a map/partition/reduce exchange
        (reference: Dataset.random_shuffle, data/dataset.py:1374)."""
        from ray_tpu.data.exchange import shuffle_exchange

        refs = shuffle_exchange(self._block_refs, _fuse(self._ops),
                                self._out_partitions(num_blocks), seed)
        return Dataset(refs)

    def sort(self, key=None, descending: bool = False,
             num_blocks: int | None = None) -> "Dataset":
        """Distributed sample-partitioned sort (reference: Dataset.sort,
        data/dataset.py:2472). `key` is a column name, a callable, or
        None for the row itself."""
        from ray_tpu.data.exchange import sort_exchange

        refs = sort_exchange(self._block_refs, _fuse(self._ops),
                             self._out_partitions(num_blocks), key,
                             descending)
        ds = Dataset(refs)
        ds._sorted_desc = descending  # type: ignore[attr-defined]
        return ds

    def groupby(self, key) -> "GroupedData":
        """Hash-partitioned groupby (reference: Dataset.groupby,
        data/dataset.py:2099 -> GroupedData)."""
        return GroupedData(self, key)

    def unique(self, key=None) -> list:
        from ray_tpu.data.exchange import groupby_exchange

        refs = groupby_exchange(self._block_refs, _fuse(self._ops),
                                self._out_partitions(None), key,
                                lambda k, rows: k)
        return [v for r in Dataset(refs).iter_rows() for v in [r]]

    def shard(self, num_shards: int, index: int) -> "Dataset":
        """Deterministic block-wise shard (per-host Train ingestion)."""
        refs = [r for i, r in enumerate(self._block_refs)
                if i % num_shards == index]
        return Dataset(refs or [], list(self._ops))

    def split(self, n: int) -> list["Dataset"]:
        return [self.shard(n, i) for i in builtins.range(n)]

    # ------------------------------------------------------------ execution

    def _execute(self, max_in_flight: int | None = None,
                 memory_budget: int | None = None) -> Iterator:
        """Stream result block refs in input order under the resource-
        managed streaming executor: a concurrency cap on in-flight tasks
        plus a MEMORY budget on produced-but-unconsumed block bytes
        (reference: streaming_executor.py:48 + resource_manager.py +
        backpressure_policy.py:11)."""
        import ray_tpu

        actor_stage = getattr(self, "_actor_stage", None)
        if not self._ops and actor_stage is None:
            yield from self._block_refs
            return
        fused = _fuse(self._ops)
        from ray_tpu.data.executor import StreamingExecutor, default_policies

        if actor_stage is None:
            @ray_tpu.remote(num_cpus=1)
            def _apply_block(block):
                return fused(block)

            executor = StreamingExecutor(default_policies(
                max_in_flight=max_in_flight, memory_budget=memory_budget))
            self._last_executor = executor  # observability / tests
            yield from executor.run(list(self._block_refs),
                                    lambda ref: _apply_block.remote(ref))
            return

        apply_fn, num_actors = actor_stage

        import ray_tpu as rt

        class _PoolWorker:
            def apply(self, block):
                return apply_fn(fused(block))

        cls = rt.remote(num_cpus=1)(_PoolWorker)
        actors = [cls.remote() for _ in builtins.range(num_actors)]
        try:
            # same resource-managed executor as the task path: the actor
            # pool must not outrun the consumer's memory budget either
            executor = StreamingExecutor(default_policies(
                max_in_flight=max_in_flight, memory_budget=memory_budget))
            self._last_executor = executor
            counter = iter(builtins.range(1 << 62))

            def submit(ref):
                return actors[next(counter) % num_actors].apply.remote(ref)

            yield from executor.run(list(self._block_refs), submit)
        finally:
            for a in actors:
                try:
                    rt.kill(a)
                except Exception:  # noqa: BLE001
                    pass

    def materialize(self) -> "Dataset":
        import ray_tpu

        refs = list(self._execute())
        # re-put to pin materialized blocks under driver ownership
        blocks = ray_tpu.get(refs, timeout=600)
        return Dataset([ray_tpu.put(b) for b in blocks])

    # ------------------------------------------------------------ consume

    def iter_rows(self) -> Iterator:
        import ray_tpu

        for ref in self._execute():
            yield from ray_tpu.get(ref, timeout=600)

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy") -> Iterator:
        """Re-batch across block boundaries (reference:
        data/_internal/iterator/)."""
        def fmt(rows):
            if batch_format == "numpy":
                return rows_to_batch(rows)
            if batch_format == "pyarrow":
                import pyarrow as pa

                return pa.Table.from_pylist(
                    [r if isinstance(r, dict) else {"value": r}
                     for r in rows])
            return rows

        buf: list = []
        for row in self.iter_rows():
            buf.append(row)
            if len(buf) >= batch_size:
                yield fmt(buf)
                buf = []
        if buf:
            yield fmt(buf)

    def take(self, n: int = 20) -> list:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> list:
        return list(self.iter_rows())

    def count(self) -> int:
        import ray_tpu

        if not self._ops and getattr(self, "_actor_stage", None) is None:
            return sum(len(b) for b in
                       ray_tpu.get(list(self._block_refs), timeout=600))
        return sum(1 for _ in self.iter_rows())

    def num_blocks(self) -> int:
        return len(self._block_refs)

    def sum(self) -> Any:
        return sum(self.iter_rows())

    def write_parquet(self, directory: str) -> list[str]:
        """One parquet file per block via Arrow (reference:
        Dataset.write_parquet)."""
        import os as _os

        import pyarrow as pa
        import pyarrow.parquet as pq

        import ray_tpu

        _os.makedirs(directory, exist_ok=True)
        paths = []
        for i, ref in enumerate(self._execute()):
            block = ray_tpu.get(ref, timeout=600)
            path = _os.path.join(directory, f"part-{i:05d}.parquet")
            rows = [r if isinstance(r, dict) else {"value": r}
                    for r in block]
            pq.write_table(pa.Table.from_pylist(rows), path)
            paths.append(path)
        return paths

    def write_jsonl(self, directory: str) -> list[str]:
        """One output file per block (reference: write_* produce one
        file per block/task)."""
        import json
        import os as _os

        import ray_tpu

        _os.makedirs(directory, exist_ok=True)
        paths = []
        for i, ref in enumerate(self._execute()):
            block = ray_tpu.get(ref, timeout=600)
            path = _os.path.join(directory, f"part-{i:05d}.jsonl")
            with open(path, "w") as f:
                for row in block:
                    f.write(json.dumps(row, default=str) + "\n")
            paths.append(path)
        return paths

    def __repr__(self):
        ops = "->".join(o.kind for o in self._ops) or "source"
        return f"Dataset(blocks={len(self._block_refs)}, plan={ops})"


class AggregateFn:
    """A named aggregation over a group's rows (reference:
    ray.data.aggregate.AggregateFn — here list-at-once instead of
    accumulate/merge, proportionate to block-resident groups)."""

    def __init__(self, name: str, fn: Callable[[list], Any]):
        self.name = name
        self.fn = fn


def Count() -> AggregateFn:  # noqa: N802 — reference-parity naming
    return AggregateFn("count", len)


def Sum(col=None) -> AggregateFn:  # noqa: N802
    return AggregateFn(f"sum({col})" if col else "sum",
                       lambda rows: sum(_col(rows, col)))


def Mean(col=None) -> AggregateFn:  # noqa: N802
    return AggregateFn(f"mean({col})" if col else "mean",
                       lambda rows: sum(_col(rows, col)) / len(rows))


def Min(col=None) -> AggregateFn:  # noqa: N802
    return AggregateFn(f"min({col})" if col else "min",
                       lambda rows: min(_col(rows, col)))


def Max(col=None) -> AggregateFn:  # noqa: N802
    return AggregateFn(f"max({col})" if col else "max",
                       lambda rows: max(_col(rows, col)))


def Std(col=None) -> AggregateFn:  # noqa: N802
    def std(rows):
        vals = list(_col(rows, col))
        m = sum(vals) / len(vals)
        return (sum((v - m) ** 2 for v in vals) / max(1, len(vals) - 1)) ** 0.5

    return AggregateFn(f"std({col})" if col else "std", std)


def _col(rows, col):
    return (r[col] for r in rows) if col is not None else rows


class GroupedData:
    """Reference parity: ray.data.grouped_data.GroupedData — the result
    of Dataset.groupby; aggregations run as the reduce side of a hash
    exchange."""

    def __init__(self, ds: Dataset, key):
        self._ds = ds
        self._key = key

    def _exchange(self, group_reducer) -> Dataset:
        from ray_tpu.data.exchange import groupby_exchange

        refs = groupby_exchange(
            self._ds._block_refs, _fuse(self._ds._ops),
            self._ds._out_partitions(None), self._key, group_reducer)
        return Dataset(refs)

    def aggregate(self, *aggs: AggregateFn) -> Dataset:
        key_name = self._key if isinstance(self._key, str) else "key"
        names = [a.name for a in aggs]
        fns = [a.fn for a in aggs]

        def reduce_group(k, rows):
            out = {key_name: k}
            for name, fn in zip(names, fns):
                out[name] = fn(rows)
            return out

        return self._exchange(reduce_group)

    def count(self) -> Dataset:
        return self.aggregate(Count())

    def sum(self, col=None) -> Dataset:
        return self.aggregate(Sum(col))

    def mean(self, col=None) -> Dataset:
        return self.aggregate(Mean(col))

    def min(self, col=None) -> Dataset:
        return self.aggregate(Min(col))

    def max(self, col=None) -> Dataset:
        return self.aggregate(Max(col))

    def std(self, col=None) -> Dataset:
        return self.aggregate(Std(col))

    def map_groups(self, fn: Callable[[list], Any]) -> Dataset:
        """fn(rows_of_one_group) -> output row(s); lists are flattened
        (reference: GroupedData.map_groups)."""
        ds = self._exchange(lambda k, rows: fn(rows))
        return ds.flat_map(lambda r: r if isinstance(r, list) else [r])


def from_items(items, parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    return Dataset.from_items(items, parallelism)


def range(n: int, parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:  # noqa: A001
    return Dataset.range(n, parallelism)


def from_numpy(arr: np.ndarray, parallelism: int = _DEFAULT_PARALLELISM
               ) -> Dataset:
    return Dataset.from_items(list(arr), parallelism)


def _paths_of(paths) -> list[str]:
    import glob as _glob
    import os as _os

    out = []
    for p in [paths] if isinstance(paths, str) else list(paths):
        if _os.path.isdir(p):
            out.extend(sorted(
                _os.path.join(p, f) for f in _os.listdir(p)
                if _os.path.isfile(_os.path.join(p, f))))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files match {paths!r}")
    return out


def _read_source(paths, read_block) -> Dataset:
    """One block per file, read INSIDE tasks (lazy/streaming — the
    datasource pattern, data/datasource/)."""
    import ray_tpu

    refs = [ray_tpu.put([p]) for p in _paths_of(paths)]
    return Dataset(refs, [_Op("read", read_block)])


def read_text(paths) -> Dataset:
    """One row per line (reference: ray.data.read_text). The line
    splitting runs in the native mmap scanner (data/lineio.py ->
    _native/lineio.cc) inside the read task."""

    def rd(block):
        from ray_tpu.data.lineio import read_lines

        out = []
        for path in block:
            out.extend(read_lines(path))
        return out

    return _read_source(paths, rd)


def read_csv(paths) -> Dataset:
    """Dict rows from CSV with a header (reference: ray.data.read_csv;
    stdlib csv instead of Arrow)."""

    def rd(block):
        import csv

        out = []
        for path in block:
            with open(path, newline="") as f:
                out.extend(dict(r) for r in csv.DictReader(f))
        return out

    return _read_source(paths, rd)


def read_json(paths) -> Dataset:
    """JSONL rows (reference: ray.data.read_json)."""

    def rd(block):
        import json

        from ray_tpu.data.lineio import read_lines

        out = []
        for path in block:
            out.extend(json.loads(line) for line in read_lines(path)
                       if line.strip())
        return out

    return _read_source(paths, rd)


def read_parquet(paths, columns: list[str] | None = None) -> Dataset:
    """Columnar parquet read — one Arrow table per file, read inside
    tasks (reference: ray.data.read_parquet backed by
    data/_internal/arrow_block.py). Rows surface as dicts; use
    map_batches(batch_format="pyarrow") to stay columnar."""

    def rd(block):
        import pyarrow.parquet as pq

        out = []
        for path in block:
            out.extend(pq.read_table(path, columns=columns).to_pylist())
        return out

    return _read_source(paths, rd)


def from_arrow(table, parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    """Dataset from a pyarrow Table (reference: ray.data.from_arrow)."""
    return Dataset.from_items(table.to_pylist(), parallelism)
