"""Logical plan + rule-based optimizer for Datasets.

Reference parity: data/_internal/logical/interfaces/logical_operator.py:10
(LogicalOperator tree), logical/optimizers.py (rule-based LogicalPlan
optimization) and the physical planner's map-fusion
(data/_internal/planner/plan_udf_map_op.py — consecutive map-like
operators fuse into ONE task per block). Redesign: operators are small
dataclasses exposing a per-block callable; the optimizer is a list of
`Rule`s applied to fixpoint; "physical" compilation composes the final
operator chain into one fused block function that the streaming
executor ships per block.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

Block = list


@dataclasses.dataclass(frozen=True)
class LogicalOperator:
    """Base logical operator. `one_to_one` marks row-count-preserving
    operators (safe to swap with Limit)."""

    name: str = dataclasses.field(init=False, default="op")
    one_to_one = False

    def block_fn(self) -> Callable[[Block], Block]:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class Read(LogicalOperator):
    """Materialize a block from its read task (the block holds the
    pending ReadTask; see datasource.py)."""

    fn: Callable[[Block], Block] = None
    name = "Read"

    def block_fn(self):
        return self.fn


@dataclasses.dataclass(frozen=True)
class MapRows(LogicalOperator):
    fn: Callable[[Any], Any] = None
    name = "MapRows"
    one_to_one = True

    def block_fn(self):
        from ray_tpu.data.block import to_rows

        f = self.fn
        return lambda b: [f(r) for r in to_rows(b)]


@dataclasses.dataclass(frozen=True)
class FilterRows(LogicalOperator):
    fn: Callable[[Any], bool] = None
    name = "Filter"

    def block_fn(self):
        from ray_tpu.data.block import to_rows

        f = self.fn
        return lambda b: [r for r in to_rows(b) if f(r)]


@dataclasses.dataclass(frozen=True)
class FlatMapRows(LogicalOperator):
    fn: Callable[[Any], list] = None
    name = "FlatMap"

    def block_fn(self):
        from ray_tpu.data.block import to_rows

        f = self.fn
        return lambda b: [o for r in to_rows(b) for o in f(r)]


@dataclasses.dataclass(frozen=True)
class MapBatches(LogicalOperator):
    """Whole-block UDF (already adapted to block form upstream)."""

    fn: Callable[[Block], Block] = None
    name = "MapBatches"

    def block_fn(self):
        return self.fn


@dataclasses.dataclass(frozen=True)
class Limit(LogicalOperator):
    """Per-block row cap; the consuming iterator enforces the GLOBAL
    cap (reference: logical Limit + per-block slicing)."""

    n: int = 0
    name = "Limit"

    def block_fn(self):
        from ray_tpu.data.block import slice_block

        n = self.n
        return lambda b: slice_block(b, 0, n)


@dataclasses.dataclass(frozen=True)
class Fused(LogicalOperator):
    """Result of map-fusion: one composed block function, its inputs
    kept for describe()."""

    parts: tuple = ()
    name = "Fused"

    def block_fn(self):
        fns = [p.block_fn() for p in self.parts]

        def fused(b):
            for f in fns:
                b = f(b)
            return b

        return fused


# ------------------------------------------------------------ optimizer


class Rule:
    """One rewrite over the operator chain (reference:
    logical/interfaces/optimizer.py Rule)."""

    def apply(self, ops: list[LogicalOperator]) -> list[LogicalOperator]:
        raise NotImplementedError


class LimitPushdown(Rule):
    """Move Limit before row-count-preserving operators so the capped
    rows skip upstream per-row work (reference:
    logical/rules/limit_pushdown.py). `limit∘map == map∘limit` only
    when the map is 1:1 — Filter/FlatMap/MapBatches block the push."""

    def apply(self, ops):
        ops = list(ops)
        changed = True
        while changed:
            changed = False
            for i in range(1, len(ops)):
                if isinstance(ops[i], Limit) and ops[i - 1].one_to_one:
                    ops[i - 1], ops[i] = ops[i], ops[i - 1]
                    changed = True
        return ops


class RedundantLimitElimination(Rule):
    """Adjacent limits collapse to the smaller one."""

    def apply(self, ops):
        out: list[LogicalOperator] = []
        for op in ops:
            if isinstance(op, Limit) and out and isinstance(out[-1], Limit):
                out[-1] = Limit(min(out[-1].n, op.n))
            else:
                out.append(op)
        return out


class MapFusion(Rule):
    """Fuse every run of consecutive block-local operators into one
    Fused operator — one task per block regardless of chain length
    (reference: the physical planner's map fusion)."""

    def apply(self, ops):
        if len(ops) <= 1:
            return list(ops)
        return [Fused(tuple(ops))]


DEFAULT_RULES: list[Rule] = [LimitPushdown(), RedundantLimitElimination(),
                             MapFusion()]


@dataclasses.dataclass
class LogicalPlan:
    ops: list[LogicalOperator]

    def describe(self) -> str:
        def nm(op):
            if isinstance(op, Fused):
                return "Fused[" + "->".join(nm(p) for p in op.parts) + "]"
            return op.name

        return " -> ".join(nm(op) for op in self.ops) or "Scan"

    def optimized(self, rules: list[Rule] | None = None) -> "LogicalPlan":
        ops = list(self.ops)
        for rule in (rules if rules is not None else DEFAULT_RULES):
            ops = rule.apply(ops)
        return LogicalPlan(ops)

    def compile(self) -> Callable[[Block], Block]:
        """Physical form: one fused per-block callable. (With the
        default rules MapFusion already collapsed chains; Fused covers
        any custom rule set that leaves several operators.)"""
        ops = self.optimized().ops
        if not ops:
            return lambda b: b
        if len(ops) == 1:
            return ops[0].block_fn()
        return Fused(tuple(ops)).block_fn()

    def global_limit(self) -> int | None:
        """The plan's overall row cap, if its SUFFIX is only limits and
        1:1 ops (the iterator stops the stream there)."""
        n = None
        for op in reversed(self.ops):
            if isinstance(op, Limit):
                n = op.n if n is None else min(n, op.n)
            elif not op.one_to_one:
                break
        return n
