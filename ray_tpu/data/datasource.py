"""Datasource ABC — pluggable lazy readers behind read_*().

Reference parity: data/datasource/datasource.py (Datasource +
ReadTask: `get_read_tasks(parallelism)` returns serializable thunks
that materialize blocks INSIDE read tasks, never on the driver) and
read_api.py's `read_datasource`. The built-in text/csv/jsonl/parquet
readers are FileDatasource instances; users plug custom sources by
subclassing Datasource.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Iterable


class ReadTask:
    """A serializable thunk producing one block, plus metadata the
    planner can use (reference: datasource.py ReadTask)."""

    def __init__(self, read_fn: Callable[[], list],
                 input_files: list[str] | None = None,
                 size_bytes: int | None = None):
        self._read_fn = read_fn
        self.input_files = input_files or []
        self.size_bytes = size_bytes

    def __call__(self) -> list:
        return self._read_fn()


class Datasource:
    """ABC. Implement `get_read_tasks`; optionally estimate size so
    the planner can choose parallelism."""

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        raise NotImplementedError

    def get_block_streams(self, parallelism: int) -> list[Callable]:
        """Streaming form: a list of thunks, each a GENERATOR yielding
        blocks incrementally. Runs under num_returns="streaming" read
        tasks so downstream consumes block 0 while the task is still
        producing block k (reference: streaming read tasks feeding the
        StreamingExecutor). Default adapts get_read_tasks: one yield per
        task."""
        tasks = self.get_read_tasks(parallelism)

        def make(t):
            def gen():
                yield t()

            return gen

        return [make(t) for t in tasks]

    def estimate_inmemory_data_size(self) -> int | None:
        return None

    @property
    def name(self) -> str:
        return type(self).__name__


class RangeDatasource(Datasource):
    def __init__(self, n: int):
        self.n = n

    def estimate_inmemory_data_size(self):
        return self.n * 8

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        if self.n <= 0:
            return [ReadTask(lambda: [], size_bytes=0)]
        parallelism = max(1, min(parallelism, self.n or 1))
        per = -(-self.n // parallelism)
        tasks = []
        for lo in range(0, self.n, per):
            hi = min(self.n, lo + per)
            tasks.append(ReadTask(
                lambda lo=lo, hi=hi: list(range(lo, hi)),
                size_bytes=(hi - lo) * 8))
        return tasks


def _expand_paths(paths) -> list[str]:
    import glob as _glob

    out: list[str] = []
    for p in [paths] if isinstance(paths, str) else list(paths):
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if os.path.isfile(os.path.join(p, f))))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files match {paths!r}")
    return out


class FileDatasource(Datasource):
    """One read task per file; subclasses define `read_file`."""

    def __init__(self, paths):
        self.paths = _expand_paths(paths)

    def read_file(self, path: str) -> list:
        raise NotImplementedError

    def estimate_inmemory_data_size(self):
        try:
            return sum(os.path.getsize(p) for p in self.paths)
        except OSError:
            return None

    def _groups(self, parallelism: int) -> list[list[str]]:
        groups: list[list[str]] = [[] for _ in
                                   range(min(parallelism, len(self.paths)))]
        for i, p in enumerate(self.paths):
            groups[i % len(groups)].append(p)
        return [g for g in groups if g]

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        # one task per file (files are the natural split unit); the
        # `parallelism` hint can only coarsen by grouping
        read = self.read_file

        def make(group):
            def rd():
                out: list = []
                for p in group:
                    out.extend(read(p))
                return out

            size = None
            try:
                size = sum(os.path.getsize(p) for p in group)
            except OSError:
                pass
            return ReadTask(rd, input_files=group, size_bytes=size)

        return [make(g) for g in self._groups(parallelism)]

    def get_block_streams(self, parallelism: int) -> list[Callable]:
        """One generator per file group, ONE BLOCK PER FILE: with grouped
        files the first file's rows are consumable while the rest of the
        group is still being read."""
        read = self.read_file

        def make(group):
            def gen():
                for p in group:
                    yield read(p)

            return gen

        return [make(g) for g in self._groups(parallelism)]


class TextDatasource(FileDatasource):
    def read_file(self, path: str) -> list:
        from ray_tpu.data.lineio import read_lines

        return read_lines(path)


class CSVDatasource(FileDatasource):
    def read_file(self, path: str) -> list:
        import csv

        with open(path, newline="") as f:
            return [dict(r) for r in csv.DictReader(f)]


class JSONLDatasource(FileDatasource):
    def read_file(self, path: str) -> list:
        import json

        from ray_tpu.data.lineio import read_lines

        return [json.loads(line) for line in read_lines(path)
                if line.strip()]


class ParquetDatasource(FileDatasource):
    def __init__(self, paths, columns: list[str] | None = None):
        super().__init__(paths)
        self.columns = columns

    def read_file(self, path: str) -> list:
        import pyarrow.parquet as pq

        return pq.read_table(path, columns=self.columns).to_pylist()


class ItemsDatasource(Datasource):
    """In-memory items (from_items role) through the same seam."""

    def __init__(self, items: Iterable[Any], parallelism_hint: int = 8):
        self.items = list(items)
        self.hint = parallelism_hint

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        from ray_tpu.data.block import split_blocks

        blocks = split_blocks(self.items, parallelism or self.hint)
        return [ReadTask(lambda b=b: list(b), size_bytes=None)
                for b in blocks]
