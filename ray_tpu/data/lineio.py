"""Native-accelerated line reading for the Data sources.

Reference parity: the datasource hot loops run in native code in the
reference (Arrow C++ readers behind ray.data.read_text/read_json);
here `_native/lineio.cc` mmaps the file and builds the line-offset
index in one C sweep, and Python decodes slices on demand — the
framework's third native component beside the object store and shm
channels. Falls back to pure-Python iteration when no toolchain exists.
"""

from __future__ import annotations

import ctypes
import threading

_lib = None
_lock = threading.Lock()


def _lineio_lib():
    global _lib
    with _lock:
        if _lib is None:
            from ray_tpu import _native

            path = _native.build_library("lineio")
            if path is None:
                _lib = False
            else:
                lib = ctypes.CDLL(path)
                u64 = ctypes.c_uint64
                u64p = ctypes.POINTER(u64)
                lib.lio_open.argtypes = [ctypes.c_char_p,
                                         ctypes.POINTER(ctypes.c_void_p),
                                         u64p]
                lib.lio_open.restype = ctypes.c_int
                lib.lio_index.argtypes = [ctypes.c_void_p, u64, u64p, u64]
                lib.lio_index.restype = u64
                lib.lio_close.argtypes = [ctypes.c_void_p, u64]
                _lib = lib
    return _lib or None


def read_lines(path: str, strip_newline: bool = True) -> list[str]:
    """All lines of a file (the native mmap+index path when available).
    LF and CRLF line endings are handled; lone-CR (classic Mac) files
    are not split by the native path."""
    lib = _lineio_lib()
    if lib is None:
        with open(path) as f:
            if strip_newline:
                return [ln.rstrip("\n") for ln in f]
            return list(f)
    base = ctypes.c_void_p()
    size = ctypes.c_uint64()
    if lib.lio_open(path.encode(), ctypes.byref(base), ctypes.byref(size)):
        raise FileNotFoundError(path)
    try:
        if size.value == 0:
            return []
        n = lib.lio_index(base, size.value, None, 0)
        offs = (ctypes.c_uint64 * n)()
        lib.lio_index(base, size.value, offs, n)
        buf = (ctypes.c_char * size.value).from_address(base.value)
        mem = memoryview(buf)
        out = []
        for i in range(n):
            start = offs[i]
            if i + 1 < n:
                end = offs[i + 1] - 1  # the newline position
            else:
                end = size.value  # final line runs to EOF...
                if end > start and bytes(mem[end - 1:end]) == b"\n":
                    end -= 1  # ...unless the file is newline-terminated
            raw = bytes(mem[start:end])
            if raw.endswith(b"\r"):
                raw = raw[:-1]  # CRLF files: match text-mode translation
            # strict decode: bad encodings must RAISE at the read site
            # like the text-mode fallback, not flow downstream mangled
            line = raw.decode()
            out.append(line if strip_newline else line + "\n")
        del mem, buf
        return out
    finally:
        lib.lio_close(base, size.value)
