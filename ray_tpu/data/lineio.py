"""Native-accelerated line reading for the Data sources.

Reference parity: the datasource hot loops run in native code in the
reference (Arrow C++ readers behind ray.data.read_text/read_json);
here `_native/lineio.cc`'s memchr sweep builds the line-offset index
over the file bytes in one C pass — the framework's third native
component beside the object store and shm channels. The file itself is
read through normal Python I/O so open/permission errors surface
exactly like the pure-Python fallback and a concurrently-truncated
file can never SIGBUS the worker (no mmap is exposed to Python).
Falls back to pure-Python splitting when no toolchain exists.
"""

from __future__ import annotations

import ctypes
import threading

_lib = None
_lock = threading.Lock()


def _lineio_lib():
    global _lib
    with _lock:
        if _lib is None:
            from ray_tpu import _native

            path = _native.build_library("lineio")
            if path is None:
                _lib = False
            else:
                lib = ctypes.CDLL(path)
                u64 = ctypes.c_uint64
                u64p = ctypes.POINTER(u64)
                lib.lio_index.argtypes = [ctypes.c_char_p, u64, u64p, u64]
                lib.lio_index.restype = u64
                _lib = lib
    return _lib or None


def read_lines(path: str, strip_newline: bool = True) -> list[str]:
    """All lines of a file. LF and CRLF endings are handled; lone-CR
    (classic Mac) files are not split by the native path."""
    lib = _lineio_lib()
    if lib is None:
        with open(path) as f:
            if strip_newline:
                return [ln.rstrip("\n") for ln in f]
            return list(f)
    with open(path, "rb") as f:
        data = f.read()
    if not data:
        return []
    n = lib.lio_index(data, len(data), None, 0)
    offs = (ctypes.c_uint64 * n)()
    lib.lio_index(data, len(data), offs, n)
    out = []
    size = len(data)
    for i in range(n):
        start = offs[i]
        if i + 1 < n:
            end = offs[i + 1] - 1  # the newline position
            had_newline = True
        else:
            end = size  # final line runs to EOF...
            had_newline = data.endswith(b"\n")
            if had_newline:
                end -= 1  # ...unless the file is newline-terminated
        raw = data[start:end]
        if raw.endswith(b"\r"):
            raw = raw[:-1]  # CRLF files: match text-mode translation
        # strict decode: bad encodings must RAISE at the read site like
        # the text-mode fallback, not flow downstream mangled
        line = raw.decode()
        if not strip_newline and had_newline:
            line += "\n"
        out.append(line)
    return out
