"""ray_tpu.data — streaming datasets over the task runtime.

Reference parity: ray.data (python/ray/data/) — lazy plans, block-based
streaming execution with bounded in-flight work, map/map_batches/filter
transforms, actor-pool compute, all-to-all exchanges (random_shuffle /
sort / groupby-aggregate), Arrow-backed parquet IO, per-shard Train
ingestion.
"""

from ray_tpu.data.dataset import (
    AggregateFn,
    Count,
    Dataset,
    GroupedData,
    Max,
    Mean,
    Min,
    Std,
    Sum,
    from_arrow,
    from_items,
    from_numpy,
    range,
    read_csv,
    read_datasource,
    read_json,
    read_parquet,
    read_text,
)
from ray_tpu.data.datasource import Datasource, ReadTask

__all__ = ["AggregateFn", "Count", "Dataset", "Datasource", "GroupedData",
           "Max", "Mean", "Min", "ReadTask", "Std", "Sum", "from_arrow",
           "from_items", "from_numpy", "range", "read_csv",
           "read_datasource", "read_json", "read_parquet", "read_text"]
