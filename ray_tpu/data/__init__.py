"""ray_tpu.data — streaming datasets over the task runtime.

Reference parity: ray.data (python/ray/data/) — lazy plans, block-based
streaming execution with bounded in-flight work, map/map_batches/filter
transforms, actor-pool compute, per-shard Train ingestion.
"""

from ray_tpu.data.dataset import (
    Dataset,
    from_items,
    from_numpy,
    range,
    read_csv,
    read_json,
    read_text,
)

__all__ = ["Dataset", "from_items", "from_numpy", "range",
           "read_csv", "read_json", "read_text"]
