"""Blocks — the unit of data movement.

Reference parity: ray.data blocks (Arrow tables in plasma,
data/_internal/arrow_block.py). A block is EITHER

- a list of rows (any python values; commonly dicts) — the row format
  for python-level ops, or
- a COLUMNAR block: dict of numpy column arrays (or one bare ndarray
  for unnamed values) — the Arrow-table role. Columnar blocks pickle
  with out-of-band buffers, so moving one through the shared-memory
  object store copies no payload bytes and `ray_tpu.get` maps the
  columns zero-copy from shm; `map_batches(batch_format="numpy")` and
  `iter_jax_batches` consume them without ever materializing rows.

Row <-> columnar conversion happens lazily at the operator that needs
the other form (row UDFs convert to rows; batch UDFs/iterators convert
to columns).
"""

from __future__ import annotations

from typing import Any, Iterable

import numpy as np

Block = list  # historical alias; see module docstring for the union


def is_columnar(block: Any) -> bool:
    if isinstance(block, np.ndarray):
        return True
    return isinstance(block, dict) and \
        all(isinstance(v, np.ndarray) for v in block.values())


def rows_to_batch(rows: list) -> Any:
    """list of rows -> batch. Dict rows become dict-of-numpy columns;
    scalar/array rows become one numpy array."""
    if not rows:
        return {}
    if isinstance(rows[0], dict):
        keys = rows[0].keys()
        return {k: np.asarray([r[k] for r in rows]) for k in keys}
    return np.asarray(rows)


def batch_to_rows(batch: Any) -> list:
    if isinstance(batch, dict):
        if not batch:
            return []
        n = len(next(iter(batch.values())))
        return [{k: v[i] for k, v in batch.items()} for i in range(n)]
    return list(batch)


def to_batch(block: Any) -> Any:
    """Block -> columnar batch (no-op when already columnar)."""
    return block if is_columnar(block) else rows_to_batch(block)


def to_rows(block: Any) -> list:
    """Block -> row list (no-op when already rows)."""
    return batch_to_rows(block) if is_columnar(block) else block


def block_num_rows(block: Any) -> int:
    if isinstance(block, np.ndarray):
        return len(block)
    if isinstance(block, dict):
        return len(next(iter(block.values()))) if block else 0
    return len(block)


def slice_block(block: Any, start: int, stop: int) -> Any:
    """Row-range slice in the block's own format (columnar slices are
    numpy views — zero copy)."""
    if isinstance(block, dict):
        return {k: v[start:stop] for k, v in block.items()}
    return block[start:stop]


def concat_batches(batches: list) -> Any:
    """Concatenate columnar batches row-wise. Single input passes
    through unconcatenated (a view — the common aligned case). Mixed
    kinds (dict-of-columns vs bare array, or differing column sets)
    raise — the Arrow-table role demands one schema per stream."""
    batches = [b for b in batches if block_num_rows(b)]
    if not batches:
        return {}
    if len(batches) == 1:
        return batches[0]
    if not columnar_kinds_compatible(batches):
        raise ValueError(
            "cannot concatenate columnar blocks with different schemas "
            f"({[sorted(b) if isinstance(b, dict) else type(b).__name__ for b in batches]}); "
            "materialize to rows first (e.g. via a row op)")
    if isinstance(batches[0], dict):
        return {k: np.concatenate([b[k] for b in batches])
                for k in batches[0]}
    return np.concatenate(batches)


def columnar_kinds_compatible(batches: list) -> bool:
    """True when the columnar batches share one schema (all bare arrays,
    or all dicts with the same column names)."""
    if all(isinstance(b, np.ndarray) for b in batches):
        return True
    if all(isinstance(b, dict) for b in batches):
        keys = set(batches[0])
        return all(set(b) == keys for b in batches)
    return False


def block_size_rows(block: Block) -> int:
    return block_num_rows(block)


def split_blocks(items: Iterable, num_blocks: int) -> list[Block]:
    items = list(items)
    n = max(1, num_blocks)
    base, rem = divmod(len(items), n)
    out, i = [], 0
    for b in range(n):
        size = base + (1 if b < rem else 0)
        out.append(items[i:i + size])
        i += size
    return [b for b in out if b] or [[]]


def split_columnar(batch: Any, num_blocks: int) -> list:
    """Split one columnar batch into ~equal columnar blocks (views)."""
    total = block_num_rows(batch)
    n = max(1, num_blocks)
    base, rem = divmod(total, n)
    out, i = [], 0
    for b in range(n):
        size = base + (1 if b < rem else 0)
        if size:
            out.append(slice_block(batch, i, i + size))
        i += size
    return out or [slice_block(batch, 0, 0)]
