"""Blocks — the unit of data movement.

Reference parity: ray.data blocks (Arrow tables in plasma,
data/_internal/arrow_block.py). Here a block is a list of rows (any
python values; commonly dicts) living in the shared-memory object store
as one object; batch formatting converts rows <-> dict-of-numpy columns
on demand (numpy is the TPU-feeding format — jax.device_put consumes it
zero-copy from the store where dtypes allow)."""

from __future__ import annotations

from typing import Any, Iterable

import numpy as np

Block = list  # a block is a list of rows


def rows_to_batch(rows: list) -> Any:
    """list of rows -> batch. Dict rows become dict-of-numpy columns;
    scalar/array rows become one numpy array."""
    if not rows:
        return {}
    if isinstance(rows[0], dict):
        keys = rows[0].keys()
        return {k: np.asarray([r[k] for r in rows]) for k in keys}
    return np.asarray(rows)


def batch_to_rows(batch: Any) -> list:
    if isinstance(batch, dict):
        if not batch:
            return []
        n = len(next(iter(batch.values())))
        return [{k: v[i] for k, v in batch.items()} for i in range(n)]
    return list(batch)


def block_size_rows(block: Block) -> int:
    return len(block)


def split_blocks(items: Iterable, num_blocks: int) -> list[Block]:
    items = list(items)
    n = max(1, num_blocks)
    base, rem = divmod(len(items), n)
    out, i = [], 0
    for b in range(n):
        size = base + (1 if b < rem else 0)
        out.append(items[i:i + size])
        i += size
    return [b for b in out if b] or [[]]
