"""All-to-all block exchange: shuffle / sort / groupby.

Reference parity: ray.data's all-to-all operators —
`random_shuffle` (python/ray/data/dataset.py:1374), `sort` (:2472) and
`groupby` (:2099), executed as the shuffle pattern in
data/_internal/planner/exchange/ (ShuffleTaskSpec / SortTaskSpec:
map tasks partition each block into P sub-blocks, reduce tasks merge
the p-th sub-block of every map output). Here the exchange rides the
task runtime's multi-return objects: every map task returns P
sub-blocks through the shared-memory object store; reduce tasks take
the p-th output of each map as args — arg locality pulls each reduce
to the node holding most of its inputs.

Sort uses sample-based range partitioning (reference:
SortTaskSpec.sample_boundaries) so output blocks are globally ordered.
"""

from __future__ import annotations

import bisect
import pickle
import zlib
from typing import Any, Callable


def _stable_hash(key) -> int:
    """Process-stable hash for partitioning. Python's hash() is salted
    per process (PYTHONHASHSEED) — map tasks run in different worker
    processes, so salted hashes would scatter one group's rows across
    reduce partitions. Numpy scalars normalize to their Python value so
    columnar-sourced keys co-partition with plain ones (np.int64(3) and
    3 must land in the same bucket)."""
    if type(key) not in (str, bytes, int, float, bool) and \
            hasattr(key, "item"):
        # numpy scalars INCLUDING np.str_/np.bytes_ (their pickle bytes
        # differ from the plain value's, so crc32 would diverge)
        try:
            key = key.item()
        except (ValueError, AttributeError):
            pass
    if isinstance(key, int):
        return key
    return zlib.crc32(pickle.dumps(key, protocol=5))


def exchange(block_refs: list, fused: Callable[[list], list],
             num_partitions: int,
             partitioner: Callable[[list, int], list[list]],
             reducer: Callable[[list[list], int], list]) -> list:
    """Run the two-stage exchange; returns refs of P reduced blocks.

    The partitioner receives (rows, block_index) and the reducer
    (parts, partition_index) so randomized exchanges can derive
    DISTINCT per-task rng streams from one user seed (the reference
    derives per-task seeds the same way; a single shared stream makes
    a seeded shuffle collapse to a tiny subset of permutations)."""
    import ray_tpu

    P = max(1, num_partitions)

    @ray_tpu.remote(num_cpus=1, num_returns=P)
    def _map(idx, block):
        from ray_tpu.data.block import to_rows

        # partitioners are row-oriented; columnar blocks convert here
        parts = partitioner(to_rows(fused(block)), idx)
        return tuple(parts) if P > 1 else parts[0]

    @ray_tpu.remote(num_cpus=1)
    def _reduce(p, *parts):
        return reducer(list(parts), p)

    map_outs = [_map.remote(i, ref) for i, ref in enumerate(block_refs)]
    if P == 1:
        map_outs = [[r] for r in map_outs]
    return [_reduce.remote(p, *[m[p] for m in map_outs]) for p in range(P)]


# ---------------------------------------------------------------- shuffle

def shuffle_exchange(block_refs, fused, num_partitions, seed=None):
    import numpy as _np

    # namespaced per-task streams: mappers draw from [seed, 0, idx] and
    # reducers from [seed, 1, p] so the two families can never collide
    # (with [seed, idx] vs [seed, P+p], block idx == P+p reused a stream)
    def partitioner(rows, idx):
        rng = _np.random.default_rng(
            None if seed is None else [seed, 0, idx])
        buckets: list[list] = [[] for _ in range(num_partitions)]
        if rows:
            for row, b in zip(rows, rng.integers(0, num_partitions,
                                                 len(rows))):
                buckets[int(b)].append(row)
        return buckets

    def reducer(parts, p):
        rows = [r for part in parts for r in part]
        rng = _np.random.default_rng(
            None if seed is None else [seed, 1, p])
        rng.shuffle(rows)
        return rows

    return exchange(block_refs, fused, num_partitions, partitioner, reducer)


# ---------------------------------------------------------------- sort

def _key_fn(key) -> Callable[[Any], Any]:
    if key is None:
        return lambda r: r
    if callable(key):
        return key
    return lambda r: r[key]


def sort_exchange(block_refs, fused, num_partitions, key=None,
                  descending=False):
    """Range-partitioned sort: sample keys -> boundaries -> partition ->
    per-partition local sort. Emitting partitions in boundary order makes
    the concatenation globally sorted."""
    import ray_tpu

    kf = _key_fn(key)

    @ray_tpu.remote(num_cpus=1)
    def _sample(block):
        rows = fused(block)
        step = max(1, len(rows) // 64)
        return [kf(r) for r in rows[::step]]

    samples = sorted(
        s for out in ray_tpu.get([_sample.remote(r) for r in block_refs],
                                 timeout=600)
        for s in out)
    P = max(1, min(num_partitions, len(samples) or 1))
    boundaries = [samples[int(len(samples) * (i + 1) / P)]
                  for i in range(P - 1)] if samples else []

    def partitioner(rows, _idx):
        buckets: list[list] = [[] for _ in range(P)]
        for r in rows:
            buckets[bisect.bisect_right(boundaries, kf(r))].append(r)
        return buckets

    def reducer(parts, _p):
        rows = [r for part in parts for r in part]
        rows.sort(key=kf, reverse=descending)
        return rows

    refs = exchange(block_refs, fused, P, partitioner, reducer)
    return list(reversed(refs)) if descending else refs


# ---------------------------------------------------------------- groupby

def groupby_exchange(block_refs, fused, num_partitions, key,
                     group_reducer: Callable[[Any, list], Any]):
    """Hash-partition rows by key; apply `group_reducer(key, rows)` to
    each group. Output rows ordered by key within each block."""
    kf = _key_fn(key)

    def partitioner(rows, _idx):
        buckets: list[list] = [[] for _ in range(num_partitions)]
        for r in rows:
            buckets[_stable_hash(kf(r)) % num_partitions].append(r)
        return buckets

    def reducer(parts, _p):
        groups: dict = {}
        for part in parts:
            for r in part:
                groups.setdefault(kf(r), []).append(r)
        return [group_reducer(k, rows)
                for k, rows in sorted(groups.items(), key=lambda kv: kv[0])]

    return exchange(block_refs, fused, num_partitions, partitioner, reducer)


# ------------------------------------------------------------------ join


def join_exchange(left_refs, left_fused, right_refs, right_fused,
                  num_partitions: int, on: str, how: str = "inner"):
    """Hash join: both sides co-partition rows by key hash, one reduce
    task per partition builds a hash table on the right side and probes
    with the left (reference role: ray.data joins via hash shuffle,
    _internal/planner/exchange + Dataset.join). `how`: "inner" or
    "left". Duplicate non-key columns from the right get a "_1"
    suffix."""
    import ray_tpu

    P = max(1, num_partitions)

    def make_map(fused):
        @ray_tpu.remote(num_cpus=1, num_returns=P)
        def _map(block):
            from ray_tpu.data.block import to_rows

            buckets: list[list] = [[] for _ in range(P)]
            for r in to_rows(fused(block)):
                buckets[_stable_hash(r[on]) % P].append(r)
            return tuple(buckets) if P > 1 else buckets[0]

        return _map

    @ray_tpu.remote(num_cpus=1)
    def _join(p, n_left, *parts):
        left_rows = [r for part in parts[:n_left] for r in part]
        right_by_key: dict = {}
        for part in parts[n_left:]:
            for r in part:
                right_by_key.setdefault(r[on], []).append(r)
        out = []
        for lr in left_rows:
            matches = right_by_key.get(lr[on])
            if matches:
                for rr in matches:
                    merged = dict(lr)
                    for k, v in rr.items():
                        if k == on:
                            continue
                        merged[k if k not in merged else k + "_1"] = v
                    out.append(merged)
            elif how == "left":
                out.append(dict(lr))
        return out

    lmap, rmap = make_map(left_fused), make_map(right_fused)
    louts = [lmap.remote(ref) for ref in left_refs]
    routs = [rmap.remote(ref) for ref in right_refs]
    if P == 1:
        louts = [[r] for r in louts]
        routs = [[r] for r in routs]
    return [
        _join.remote(p, len(louts),
                     *[m[p] for m in louts], *[m[p] for m in routs])
        for p in range(P)
    ]
