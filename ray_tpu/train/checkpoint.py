"""Checkpoints: directory-backed artifacts + top-k retention.

Reference parity: ray.train.Checkpoint (python/ray/train/_checkpoint.py
— a directory + filesystem abstraction), CheckpointManager top-k
retention (train/_internal/checkpoint_manager.py), CheckpointConfig
(air/config.py). Filesystem scope this round: local/shared paths (the
reference reaches s3/gcs through pyarrow.fs; the seam here is the same —
`Checkpoint.path` is opaque to everything above it).
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import tempfile
from contextlib import contextmanager


class Checkpoint:
    """A directory of training artifacts. Cheap value object: holds a
    path, never reads it eagerly."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise ValueError(f"not a directory: {path}")
        return cls(path)

    def to_directory(self, dest: str | None = None) -> str:
        """Materialize into `dest` (copy); default a fresh temp dir."""
        dest = dest or tempfile.mkdtemp(prefix="ckpt_")
        os.makedirs(dest, exist_ok=True)
        for name in os.listdir(self.path):
            src = os.path.join(self.path, name)
            dst = os.path.join(dest, name)
            if os.path.isdir(src):
                shutil.copytree(src, dst, dirs_exist_ok=True)
            else:
                shutil.copy2(src, dst)
        return dest

    @contextmanager
    def as_directory(self):
        """Read-only view; local checkpoints are yielded in place."""
        yield self.path

    def __repr__(self):
        return f"Checkpoint({self.path})"


@dataclasses.dataclass
class CheckpointConfig:
    """Reference: ray.train.CheckpointConfig (air/config.py)."""

    num_to_keep: int | None = None  # None = keep all
    checkpoint_score_attribute: str | None = None
    checkpoint_score_order: str = "max"  # "max" | "min"
    # Tune class-trainable driver: ship a checkpoint every N iterations
    # (reference: CheckpointConfig.checkpoint_frequency) — large states
    # need not ride the session queue + disk every step
    checkpoint_frequency: int = 1


@dataclasses.dataclass
class _Tracked:
    checkpoint: Checkpoint
    metrics: dict
    index: int

    def score(self, attr: str | None):
        if attr is None:
            return self.index  # recency
        v = self.metrics.get(attr)
        return self.index if v is None else v


class CheckpointManager:
    """Registers reported checkpoints into `experiment_dir`, keeps the
    top-k by score (or the k most recent), deletes the rest.

    Reference: train/_internal/checkpoint_manager.py."""

    def __init__(self, experiment_dir: str,
                 config: CheckpointConfig | None = None):
        self.dir = os.path.abspath(experiment_dir)
        os.makedirs(self.dir, exist_ok=True)
        self.config = config or CheckpointConfig()
        self._tracked: list[_Tracked] = []
        self._index = self._restore_index()

    def _restore_index(self) -> int:
        mx = -1
        for name in os.listdir(self.dir):
            if name.startswith("checkpoint_"):
                try:
                    idx = int(name.split("_")[1])
                except (IndexError, ValueError):
                    continue
                mx = max(mx, idx)
                meta = os.path.join(self.dir, name, ".metrics.json")
                metrics = {}
                if os.path.exists(meta):
                    with open(meta) as f:
                        metrics = json.load(f)
                self._tracked.append(_Tracked(
                    Checkpoint(os.path.join(self.dir, name)), metrics, idx))
        self._tracked.sort(key=lambda t: t.index)
        return mx + 1

    def register(self, checkpoint: Checkpoint, metrics: dict | None = None
                 ) -> Checkpoint:
        """Move/copy a reported checkpoint into the experiment dir and
        apply the retention policy. Returns the persisted Checkpoint."""
        metrics = dict(metrics or {})
        idx = self._index
        self._index += 1
        dest = os.path.join(self.dir, f"checkpoint_{idx:06d}")
        if os.path.abspath(checkpoint.path) != dest:
            # same-filesystem move when possible, copy otherwise
            try:
                os.rename(checkpoint.path, dest)
            except OSError:
                checkpoint.to_directory(dest)
        with open(os.path.join(dest, ".metrics.json"), "w") as f:
            json.dump(_json_safe(metrics), f)
        persisted = Checkpoint(dest)
        self._tracked.append(_Tracked(persisted, metrics, idx))
        self._enforce_retention()
        return persisted

    def _enforce_retention(self):
        k = self.config.num_to_keep
        if k is None or len(self._tracked) <= k:
            return
        attr = self.config.checkpoint_score_attribute
        reverse = self.config.checkpoint_score_order == "max"
        ranked = sorted(self._tracked, key=lambda t: t.score(attr),
                        reverse=reverse)
        keep = set(id(t) for t in ranked[:k])
        # never delete the most recent (resume anchor), reference keeps it
        keep.add(id(self._tracked[-1]))
        for t in list(self._tracked):
            if id(t) not in keep:
                shutil.rmtree(t.checkpoint.path, ignore_errors=True)
                self._tracked.remove(t)

    def latest(self) -> Checkpoint | None:
        return self._tracked[-1].checkpoint if self._tracked else None

    def best(self) -> Checkpoint | None:
        if not self._tracked:
            return None
        attr = self.config.checkpoint_score_attribute
        reverse = self.config.checkpoint_score_order == "max"
        return sorted(self._tracked, key=lambda t: t.score(attr),
                      reverse=reverse)[0].checkpoint


def _json_safe(d: dict) -> dict:
    out = {}
    for k, v in d.items():
        try:
            json.dumps(v)
            out[k] = v
        except (TypeError, ValueError):
            out[k] = repr(v)
    return out
