"""Per-worker training session: context + report().

Reference parity: _TrainSession (train/_internal/session.py:112,
report :405) and the public ray.train.get_context()/report API. The
session lives inside each train-worker actor; `report` hands
(metrics, checkpoint) to the driver's result loop and blocks until the
driver has consumed the previous report, keeping workers in lockstep the
way the reference's continue-lock does."""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Any, Optional

from ray_tpu.train.checkpoint import Checkpoint

_session: "TrainSession | None" = None
_session_lock = threading.Lock()


@dataclasses.dataclass
class TrainContext:
    """What user code can ask about its place in the world (reference:
    ray.train.get_context() — train/context.py)."""

    world_size: int
    world_rank: int
    local_rank: int
    local_world_size: int
    node_rank: int
    experiment_name: str
    trial_dir: str
    coordinator_address: str | None

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_local_world_size(self) -> int:
        return self.local_world_size

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_trial_dir(self) -> str:
        return self.trial_dir

    def get_experiment_name(self) -> str:
        return self.experiment_name


@dataclasses.dataclass
class _Report:
    metrics: dict
    checkpoint_dir: str | None


class TrainSession:
    def __init__(self, context: TrainContext,
                 resume_checkpoint: Checkpoint | None = None,
                 dataset_shards: dict | None = None):
        self.context = context
        self.resume_checkpoint = resume_checkpoint
        self.dataset_shards = dataset_shards or {}
        # maxsize=1: report() blocks until the driver drains the previous
        # round — workers advance in lockstep with the driver loop
        self.results: queue.Queue[_Report] = queue.Queue(maxsize=1)
        self.finished = threading.Event()
        self.error: BaseException | None = None
        self.error_tb: str = ""
        self.final: Any = None

    def report(self, metrics: dict, checkpoint: Checkpoint | None = None):
        self.results.put(
            _Report(dict(metrics), checkpoint.path if checkpoint else None))

    def next_result(self, timeout: float = 0.0) -> dict | None:
        try:
            r = self.results.get(timeout=timeout) if timeout else \
                self.results.get_nowait()
        except queue.Empty:
            return None
        return {"metrics": r.metrics, "checkpoint_dir": r.checkpoint_dir}


def init_session(context: TrainContext,
                 resume_checkpoint: Checkpoint | None = None,
                 dataset_shards: dict | None = None) -> TrainSession:
    global _session
    with _session_lock:
        _session = TrainSession(context, resume_checkpoint, dataset_shards)
        return _session


def shutdown_session():
    global _session
    with _session_lock:
        _session = None


def get_session() -> Optional[TrainSession]:
    return _session


def get_context() -> TrainContext:
    s = get_session()
    if s is None:
        raise RuntimeError("ray_tpu.train.get_context() outside a train "
                           "worker session")
    return s.context


def report(metrics: dict, checkpoint: Checkpoint | None = None):
    """Report metrics (and optionally a checkpoint) to the driver
    (reference: ray.train.report, session.py:405)."""
    s = get_session()
    if s is None:
        raise RuntimeError("ray_tpu.train.report() outside a train worker")
    s.report(metrics, checkpoint)


def get_checkpoint() -> Checkpoint | None:
    """The checkpoint to resume from, if the run was restored."""
    s = get_session()
    return s.resume_checkpoint if s else None


def get_dataset_shard(name: str = "train"):
    """This worker's shard of a Dataset passed to JaxTrainer(datasets=...)
    (reference: ray.train.get_dataset_shard — the prepare_data_loader
    role: per-worker streaming ingestion)."""
    s = get_session()
    if s is None:
        raise RuntimeError("get_dataset_shard() outside a train worker")
    shard = s.dataset_shards.get(name)
    if shard is None:
        raise KeyError(
            f"no dataset {name!r} was passed to the trainer "
            f"(have: {sorted(s.dataset_shards)})")
    return shard
