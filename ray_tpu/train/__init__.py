"""ray_tpu.train — distributed training library.

TPU-native counterpart of ray.train (python/ray/train/): instead of N
one-GPU workers forming an NCCL world via `dist.init_process_group`
(train/torch/config.py:66-124), a training job is one SPMD program jitted
over a device mesh; the worker group exists for multi-host process
orchestration, data loading, and fault handling.
"""

from ray_tpu.train.spmd import TrainState, make_train_step, batch_shardings

__all__ = ["TrainState", "make_train_step", "batch_shardings"]
