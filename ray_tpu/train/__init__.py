"""ray_tpu.train — distributed training library.

TPU-native counterpart of ray.train (python/ray/train/): instead of N
one-GPU workers forming an NCCL world via `dist.init_process_group`
(train/torch/config.py:66-124), a training job is one SPMD program jitted
over a device mesh spanning the worker gang (one jax process per host,
jax.distributed rendezvous through the WorkerGroup); the worker group
exists for multi-host process orchestration, data loading, checkpointing
and fault handling.
"""

from ray_tpu.train.checkpoint import (
    Checkpoint,
    CheckpointConfig,
    CheckpointManager,
)
from ray_tpu.train.session import (
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
)
from ray_tpu.train.pipeline_strategy import PipelineStrategy
from ray_tpu.train.spmd import TrainState, batch_shardings, make_train_step
from ray_tpu.train.trainer import (
    FailureConfig,
    JaxTrainer,
    Result,
    RunConfig,
    ScalingConfig,
    TrainingFailedError,
)

__all__ = [
    "Checkpoint",
    "CheckpointConfig",
    "CheckpointManager",
    "FailureConfig",
    "JaxTrainer",
    "PipelineStrategy",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TrainState",
    "TrainingFailedError",
    "batch_shardings",
    "get_checkpoint",
    "get_context",
    "get_dataset_shard",
    "make_train_step",
    "report",
]
