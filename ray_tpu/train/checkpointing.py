"""Saving/restoring sharded jax pytrees to a checkpoint directory.

Reference parity: the role of torch.save/load inside Train user loops
plus the storage layer (train/_internal/storage.py). TPU-native shape:
state lives as sharded jax.Arrays across a process gang, so

- `save_pytree`: every process participates (allgather of its shards via
  jax.experimental.multihost_utils), rank 0 writes one .npz + a pickled
  treedef. Simple and correct at test/GPT-2 scale; swap in per-shard
  writes (orbax-style) for models that don't fit one host's RAM — the
  directory format is versioned for that.
- `load_pytree`: every process reads the (shared-fs) file and
  re-device_puts with the target shardings, materializing only its own
  shards (jax.make_array_from_callback).
"""

from __future__ import annotations

import os
import pickle

import jax
import numpy as np

_STATE_FILE = "state.npz"
_TREE_FILE = "treedef.pkl"
_FORMAT = 1


def save_pytree(tree, directory: str, *, process_index: int | None = None):
    """Collectively save a pytree of (possibly sharded) jax.Arrays.

    Every process in the jax world MUST call this (the allgather of
    non-addressable shards is collective). Only process 0 writes."""
    from jax.experimental import multihost_utils

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    pid = jax.process_index() if process_index is None else process_index
    multiproc = jax.process_count() > 1

    host_leaves = []
    for leaf in leaves:
        if isinstance(leaf, jax.Array) and multiproc and \
                not leaf.is_fully_addressable:
            leaf = multihost_utils.process_allgather(leaf, tiled=True)
        host_leaves.append(np.asarray(leaf))

    if pid == 0:
        os.makedirs(directory, exist_ok=True)
        tmp = os.path.join(directory, _STATE_FILE + ".tmp")
        np.savez(tmp, **{f"leaf_{i}": a for i, a in enumerate(host_leaves)})
        # np.savez appends .npz to a name without it
        if not os.path.exists(tmp) and os.path.exists(tmp + ".npz"):
            tmp = tmp + ".npz"
        os.replace(tmp, os.path.join(directory, _STATE_FILE))
        with open(os.path.join(directory, _TREE_FILE), "wb") as f:
            pickle.dump({"format": _FORMAT, "treedef": treedef,
                         "n_leaves": len(host_leaves)}, f)
    if multiproc:
        multihost_utils.sync_global_devices("ray_tpu_ckpt_save")


def load_pytree(directory: str, shardings=None):
    """Load a pytree saved by save_pytree. With `shardings` (a pytree of
    NamedSharding matching the saved structure), each process
    materializes only its addressable shards."""
    with open(os.path.join(directory, _TREE_FILE), "rb") as f:
        meta = pickle.load(f)
    treedef = meta["treedef"]
    data = np.load(os.path.join(directory, _STATE_FILE))
    host_leaves = [data[f"leaf_{i}"] for i in range(meta["n_leaves"])]
    if shardings is None:
        return jax.tree_util.tree_unflatten(treedef, host_leaves)
    shard_leaves = jax.tree_util.tree_leaves(shardings)
    out = []
    for arr, sh in zip(host_leaves, shard_leaves):
        out.append(jax.make_array_from_callback(
            arr.shape, sh, lambda idx, a=arr: a[idx]))
    return jax.tree_util.tree_unflatten(treedef, out)


def save_train_state(state, directory: str):
    """Convenience for ray_tpu.train.TrainState."""
    save_pytree({"params": state.params, "opt_state": state.opt_state,
                 "step": state.step}, directory)


def load_train_state(directory: str, state_template):
    """Restore into the shardings of `state_template` (a TrainState whose
    arrays carry the target NamedShardings)."""
    shardings = jax.tree.map(
        lambda x: x.sharding if isinstance(x, jax.Array) else None,
        {"params": state_template.params,
         "opt_state": state_template.opt_state,
         "step": state_template.step})
    loaded = load_pytree(directory, shardings)
    return type(state_template)(
        params=loaded["params"], opt_state=loaded["opt_state"],
        step=loaded["step"])
