"""1F1B pipeline-parallel train strategy over a WorkerGroup.

The in-program pipeline (parallel/pipeline.py schedules inside one SPMD
program) shares one jitted program across every device. This module is
the MPMD promotion ("Scaling Deep Learning Training with MPMD Pipeline
Parallelism"; Megatron schedules.py is the reference order): each
pipeline STAGE is its own worker actor holding only its stage's
parameters, and activations/grad-activations stream stage-to-stage
through the object store — same-node neighbors ride the shm fast path
(the PR 11 channel transport), cross-node neighbors the nodelet pull
path, with no driver byte-copies either way (the driver only wires
ObjectRefs).

Scheduling is deliberately SUBMISSION-ORDER-IS-EXECUTION-ORDER: stage
workers run FIFO (max_concurrency=1), the driver submits each stage's
calls in its exact 1F1B order (`one_f_one_b_schedule`), and every
call's input is an ObjectRef produced by an earlier submission
(`one_f_one_b_submission_order` is topological) — so the gang executes
the textbook one-forward-one-backward interleave with at most (S - s)
live activations on stage s, and the whole schedule is testable as
data.

The bubble is measured, not assumed: each stage reports per-op busy
time and its step window; `train_step` computes
``bubble_ratio = 1 - busy / (S * makespan)`` and surfaces it on the
`train_pipeline_bubble_ratio` gauge (watchtower's
`train-pipeline-bubble` rule pages when a mis-sized microbatch count
wastes chips). The theoretical floor (S-1)/(S-1+M) comes from
`parallel.pipeline.theoretical_bubble`.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

import cloudpickle
import numpy as np

from ray_tpu.parallel.pipeline import (
    one_f_one_b_submission_order,
    theoretical_bubble,
)

_bubble_gauge = None
_micro_counter = None


def _strategy_metrics():
    global _bubble_gauge, _micro_counter
    if _bubble_gauge is None:
        from ray_tpu.util.metrics import Counter, Gauge

        _bubble_gauge = Gauge(
            "train_pipeline_bubble_ratio",
            "Measured 1F1B pipeline bubble fraction of the last step: "
            "1 - stage-busy / (stages * makespan); compare against "
            "(S-1)/(S-1+M)")
        _micro_counter = Counter(
            "train_microbatches_total",
            "Microbatches executed by the pipeline train strategy")
    return _bubble_gauge, _micro_counter


class PipelineStageWorker:
    """Actor owning ONE pipeline stage: its parameter shard, the 1F1B
    forward/backward for each microbatch (residuals kept per in-flight
    microbatch via jax.vjp closures), grad accumulation, and the
    end-of-step SGD update. Methods execute FIFO — the driver's
    submission order is the schedule."""

    def __init__(self, rank: int, world_size: int):
        self.stage = rank
        self.num_stages = world_size
        self.cfg = None
        self.params = None
        self.lr = 0.0
        self.num_microbatches = 1
        self._saved: dict[int, Any] = {}  # mb -> fwd inputs (residual)
        self._jfwd = None
        self._jbwd = None
        self._grads = None
        self._spans: list[tuple[float, float]] = []

    def setup_env(self, env: dict) -> bool:
        import os

        os.environ.update({k: str(v) for k, v in env.items()})
        if "JAX_PLATFORMS" in env:
            # jax is already imported in this process (the actor class
            # pulls it in), so the env var alone cannot steer the
            # backend — the config update can, as long as no jax call
            # has initialized a backend yet (none has: load_stage is
            # the first to touch arrays)
            import jax

            jax.config.update("jax_platforms",
                              str(env["JAX_PLATFORMS"]) or None)
        return True

    def load_stage(self, cfg_kwargs: dict, params_blob: bytes, lr: float,
                   num_microbatches: int) -> int:
        """Install this stage's config + params. Returns the stage's
        parameter count (the driver logs the split)."""
        import jax

        from ray_tpu.models.pipelined import PipelinedConfig

        self.cfg = PipelinedConfig(**cfg_kwargs)
        self.params = jax.tree.map(jax.numpy.asarray,
                                   cloudpickle.loads(params_blob))
        self.lr = float(lr)
        self.num_microbatches = int(num_microbatches)
        self._build_programs()
        return sum(int(np.prod(x.shape))
                   for x in jax.tree.leaves(self.params))

    def _build_programs(self):
        """Jitted forward + jitted REMATERIALIZED backward (the
        backward re-runs the stage forward under vjp instead of keeping
        live residual closures — so both directions hit the XLA compile
        cache across microbatches/steps, and the only per-microbatch
        state parked between fwd(mb) and bwd(mb) is the stage's input
        activation, exactly the 1F1B memory shape)."""
        import jax

        from ray_tpu.models.pipelined import stage_apply

        first = self.stage == 0
        last = self.stage == self.num_stages - 1

        def fn(p, x, t):
            return stage_apply(self.cfg, p, self.stage, self.num_stages,
                               x, targets=t)

        if last:
            self._jfwd = jax.jit(fn)

            def bwd(p, x, t, g):
                _, vjp = jax.vjp(lambda pp, xx: fn(pp, xx, t), p, x)
                return vjp(g) if not first else (vjp(g)[0], None)
        else:
            self._jfwd = jax.jit(lambda p, x: fn(p, x, None))

            def bwd(p, x, g):
                _, vjp = jax.vjp(lambda pp, xx: fn(pp, xx, None), p, x)
                # stage 0's input is int tokens: drop the float0
                # cotangent instead of shipping it
                return vjp(g) if not first else (vjp(g)[0], None)

        self._jbwd = jax.jit(bwd)

    def forward(self, mb: int, payload, targets=None):
        """Forward one microbatch: payload is tokens (stage 0) or the
        previous stage's activation. Returns the activation for the
        next stage, or the microbatch loss on the last stage. The
        inputs park as residuals until `backward(mb)`."""
        import jax
        import jax.numpy as jnp

        t0 = time.perf_counter()
        last = self.stage == self.num_stages - 1
        x = jnp.asarray(payload)
        if last:
            tgt = jnp.asarray(targets)
            out = self._jfwd(self.params, x, tgt)
            self._saved[mb] = (x, tgt)
        else:
            out = self._jfwd(self.params, x)
            self._saved[mb] = (x,)
        out = jax.block_until_ready(out)
        t1 = time.perf_counter()
        self._spans.append((t0, t1))
        self._trace("fwd", t0, t1, mb)
        if last:
            # the driver reads the microbatch loss straight off this
            # call's ObjectRef — no separate loss plumbing
            return float(out)
        return np.asarray(out)

    def backward(self, mb: int, grad=None):
        """Backward one microbatch: grad is the next stage's activation
        cotangent (None on the last stage, which seeds with 1/M so the
        accumulated grads are those of the MEAN loss). Returns the
        cotangent for the previous stage (True from stage 0)."""
        import jax
        import jax.numpy as jnp

        t0 = time.perf_counter()
        saved = self._saved.pop(mb)
        if grad is None:
            seed = jnp.float32(1.0 / self.num_microbatches)
        else:
            seed = jnp.asarray(grad)
        dparams, dx = self._jbwd(self.params, *saved, seed)
        dparams = jax.block_until_ready(dparams)
        if self._grads is None:
            self._grads = dparams
        else:
            self._grads = jax.tree.map(jnp.add, self._grads, dparams)
        t1 = time.perf_counter()
        self._spans.append((t0, t1))
        self._trace("bwd", t0, t1, mb)
        if self.stage == 0:
            return True
        return np.asarray(dx)

    def finish_step(self) -> dict:
        """Apply the accumulated grads (SGD, matching
        `pipelined_train_step`) and report this stage's timing: busy
        seconds and the step window (the driver's bubble inputs)."""
        import jax

        if self._saved:
            raise RuntimeError(
                f"stage {self.stage}: {len(self._saved)} microbatches "
                f"never ran backward — schedule bug")
        if self._grads is not None:
            self.params = jax.tree.map(
                lambda p, g: p - self.lr * g, self.params, self._grads)
            self._grads = None
        spans, self._spans = self._spans, []
        busy = sum(t1 - t0 for t0, t1 in spans)
        window = ((min(t0 for t0, _ in spans),
                   max(t1 for _, t1 in spans)) if spans else (0.0, 0.0))
        return {"stage": self.stage, "busy_s": busy,
                "window_s": window[1] - window[0], "ops": len(spans)}

    def get_params(self) -> bytes:
        """This stage's current params (numpy tree) — checkpointing and
        the parity tests' merge path."""
        import jax

        return cloudpickle.dumps(jax.tree.map(np.asarray, self.params))

    def ping(self) -> str:
        return "pong"

    def _trace(self, kind: str, t0: float, t1: float, mb: int) -> None:
        from ray_tpu.util import tracing

        tracing.record_interval(
            f"pipeline.stage{self.stage}.{kind}.mb{mb}", t0, t1,
            category="train")


@dataclasses.dataclass
class PipelineStepMetrics:
    loss: float
    bubble_ratio: float
    bubble_theoretical: float
    step_seconds: float
    microbatches: int

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class PipelineStrategy:
    """Drive 1F1B pipeline-parallel training of the pipelined
    transformer over `num_stages` stage workers.

    ::

        ps = PipelineStrategy(PipelinedConfig(), num_stages=2,
                              num_microbatches=8)
        for _ in range(steps):
            metrics = ps.train_step({"tokens": ..., "targets": ...})
        ps.shutdown()
    """

    def __init__(self, cfg, num_stages: int,
                 num_microbatches: int | None = None, lr: float = 1e-2,
                 seed: int = 0, params=None,
                 resources_per_worker: dict | None = None,
                 placement_strategy: str = "PACK"):
        import jax

        from ray_tpu.models.pipelined import (
            PipelinedConfig,
            init_pipelined,
            split_pipeline_stages,
        )
        from ray_tpu.train.worker_group import WorkerGroup

        self.cfg = (cfg if isinstance(cfg, PipelinedConfig)
                    else PipelinedConfig(**dict(cfg or {})))
        self.num_stages = num_stages
        self.num_microbatches = int(
            num_microbatches or self.cfg.num_microbatches)
        self.lr = lr
        # FIFO workers: the 1F1B submission order must BE the per-stage
        # execution order (see module docstring)
        self.wg = WorkerGroup(
            num_workers=num_stages,
            resources_per_worker=resources_per_worker,
            placement_strategy=placement_strategy,
            worker_cls=PipelineStageWorker,
            max_concurrency=1,
        )
        try:
            if jax.devices()[0].platform == "cpu":
                # test/laptop path: stage workers must not grab a TPU
                self.wg.execute("setup_env", {"JAX_PLATFORMS": "cpu"})
            if params is None:
                params = init_pipelined(jax.random.PRNGKey(seed),
                                        self.cfg)
            cfg_kwargs = dataclasses.asdict(self.cfg)
            stages = split_pipeline_stages(params, self.cfg, num_stages)
            self.stage_param_counts = [
                self.wg.execute_single(
                    s, "load_stage", cfg_kwargs,
                    cloudpickle.dumps(
                        jax.tree.map(np.asarray, stages[s])),
                    lr, self.num_microbatches)
                for s in range(num_stages)
            ]
        except Exception:
            self.wg.shutdown()
            raise
        self.last_metrics: PipelineStepMetrics | None = None

    # ------------------------------------------------------------------

    def train_step(self, batch: dict) -> dict:
        """One 1F1B step over the whole batch: split into M
        microbatches, stream activations down / cotangents up the stage
        chain, then apply each stage's update. Returns
        {loss, bubble_ratio, bubble_theoretical, step_seconds,
        microbatches}."""
        import ray_tpu
        from ray_tpu.util import tracing

        S, M = self.num_stages, self.num_microbatches
        tokens = np.asarray(batch["tokens"])
        targets = np.asarray(batch["targets"])
        B = tokens.shape[0]
        if B % M:
            raise ValueError(f"batch {B} not divisible by "
                             f"microbatches {M}")
        mb = B // M
        t0 = time.perf_counter()
        with tracing.span("pipeline.train_step", category="train"):
            fwd: dict[tuple[int, int], Any] = {}
            bwd: dict[tuple[int, int], Any] = {}
            for kind, s, m in one_f_one_b_submission_order(S, M):
                w = self.wg.workers[s]
                if kind == "fwd":
                    payload = (tokens[m * mb:(m + 1) * mb] if s == 0
                               else fwd[(s - 1, m)])
                    tgt = (targets[m * mb:(m + 1) * mb]
                           if s == S - 1 else None)
                    fwd[(s, m)] = w.forward.remote(m, payload, tgt)
                else:
                    g = bwd[(s + 1, m)] if s < S - 1 else None
                    bwd[(s, m)] = w.backward.remote(m, g)
            losses = ray_tpu.get([fwd[(S - 1, m)] for m in range(M)],
                                 timeout=300)
            ray_tpu.get([bwd[(0, m)] for m in range(M)], timeout=300)
            stats = self.wg.execute("finish_step")
        dt = time.perf_counter() - t0
        makespan = max(st["window_s"] for st in stats)
        busy = sum(st["busy_s"] for st in stats)
        bubble = (1.0 - busy / (S * makespan)) if makespan > 0 else 0.0
        m_bubble, m_micro = _strategy_metrics()
        m_bubble.set(bubble)
        m_micro.inc(M)
        self.last_metrics = PipelineStepMetrics(
            loss=float(np.mean(losses)),
            bubble_ratio=bubble,
            bubble_theoretical=theoretical_bubble(S, M),
            step_seconds=dt,
            microbatches=M,
        )
        return self.last_metrics.as_dict()

    def full_params(self):
        """Merge every stage's current params back into one tree (the
        single-program layout) — checkpoint/parity surface."""
        from ray_tpu.models.pipelined import merge_pipeline_stages

        blobs = self.wg.execute("get_params")
        return merge_pipeline_stages(
            [cloudpickle.loads(b) for b in blobs])

    def shutdown(self):
        self.wg.shutdown()
