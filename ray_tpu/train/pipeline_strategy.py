"""1F1B pipeline-parallel train strategy over a WorkerGroup — flat and
interleaved schedules, composed with intra-stage ZeRO sharding.

The in-program pipeline (parallel/pipeline.py schedules inside one SPMD
program) shares one jitted program across every device. This module is
the MPMD promotion ("Scaling Deep Learning Training with MPMD Pipeline
Parallelism"; Megatron schedules.py is the reference order): each
pipeline STAGE is its own worker actor holding only its stage's
parameters, and activations/grad-activations stream stage-to-stage
through the object store — same-node neighbors ride the shm fast path
(the PR 11 channel transport), cross-node neighbors the nodelet pull
path, with no driver byte-copies either way (the driver only wires
ObjectRefs).

Scheduling is deliberately SUBMISSION-ORDER-IS-EXECUTION-ORDER: stage
workers run FIFO (max_concurrency=1), the driver submits each stage's
calls in its exact schedule order, and every call's input is an
ObjectRef produced by an earlier submission (the submission orders are
topological) — so the gang executes the textbook interleave and the
whole schedule is testable as data. Two schedules:

- flat 1F1B (`one_f_one_b_submission_order`): bubble (S-1)/(S-1+M);
- interleaved (`num_repeats=R > 1`,
  `interleaved_1f1b_submission_order`): each worker owns R VIRTUAL
  stages placed round-robin (virtual stage v on worker v % S — the MPMD
  face of `pipeline_apply_interleaved`'s circular schedule), each op
  costs ~1/R of a flat-stage op, and the fill/drain bubble drops to
  (S-1)/(R*M + S-1) at the SAME stage and microbatch counts.

ZeRO composes per stage (`zero_stage`, `data_parallel=D`): each stage
worker owns a D-device data-parallel group (one process per host, all
its chips — the TPU-native shape) and runs its stage program under
GSPMD with the train/spmd.py ladder layouts: grads are pinned to the
replicated layout then reduce-scattered 1/D (stage >= 2 keeps the
accumulated grads resident scattered between microbatches), momentum
state lives 1/D (stage >= 1), and resident params live 1/D with a
just-in-time gather inside the stage program (stage 3).

The bubble is measured, not assumed: each stage reports per-op busy
time and its step window; `train_step` computes
``bubble_ratio = 1 - busy / (S * makespan)`` and surfaces it on the
`train_pipeline_bubble_ratio` gauge. Busy is the stage process's CPU
time inside its ops (`time.process_time`), not the wall span: on a
host that timeshares stage workers over fewer cores, wall spans absorb
wait-for-CPU and overstate useful work (schedules with more overlap
read as artificially bubble-free); CPU time counts only compute
actually done, and the two coincide on the deployment shape this
models — one dedicated chip group per stage worker (watchtower's
`train-pipeline-bubble` rule pages when a mis-sized microbatch count
wastes chips), alongside `train_pipeline_virtual_stages` (S*R). The
theoretical floors come from `parallel.pipeline.theoretical_bubble`
and `theoretical_bubble_interleaved`.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any

import cloudpickle
import numpy as np

from ray_tpu.parallel.pipeline import (
    interleaved_1f1b_submission_order,
    one_f_one_b_submission_order,
    theoretical_bubble,
    theoretical_bubble_interleaved,
)

_bubble_gauge = None
_micro_counter = None
_virtual_gauge = None


def _strategy_metrics():
    global _bubble_gauge, _micro_counter, _virtual_gauge
    if _bubble_gauge is None:
        from ray_tpu.util.metrics import Counter, Gauge

        _bubble_gauge = Gauge(
            "train_pipeline_bubble_ratio",
            "Measured 1F1B pipeline bubble fraction of the last step: "
            "1 - stage-busy / (stages * makespan); compare against "
            "(S-1)/(S-1+M) flat or (S-1)/(R*M+S-1) interleaved")
        _micro_counter = Counter(
            "train_microbatches_total",
            "Microbatches executed by the pipeline train strategy")
        _virtual_gauge = Gauge(
            "train_pipeline_virtual_stages",
            "Virtual pipeline stages (stages * repeats) of the running "
            "pipeline strategy — >num_stages means the interleaved "
            "schedule is active")
    return _bubble_gauge, _micro_counter, _virtual_gauge


class PipelineStageWorker:
    """Actor owning ONE pipeline stage: its parameter chunks (R virtual
    stages when interleaved), the 1F1B forward/backward for each
    microbatch (residuals kept per in-flight (repeat, microbatch) via
    rematerialized vjp), grad accumulation in the ZeRO layout, and the
    end-of-step SGD(+momentum) update. Methods execute FIFO — the
    driver's submission order is the schedule."""

    def __init__(self, rank: int, world_size: int):
        self.stage = rank
        self.num_stages = world_size
        self.num_repeats = 1
        self.zero_stage = 0
        self.data_parallel = 1
        self.momentum = 0.0
        self.cfg = None
        self.params = None          # list over repeats of chunk trees
        self.mesh = None            # (data, fsdp) mesh when D > 1
        self.lr = 0.0
        self.num_microbatches = 1
        self._saved: dict[tuple[int, int], Any] = {}  # (r, mb) -> residual
        self._jfwd: dict[int, Any] = {}
        self._jbwd: dict[int, Any] = {}
        self._jupd = None
        self._grads: list[Any] = []      # per repeat, ZeRO layout
        self._vel: list[Any] | None = None
        self._spans: list[tuple[float, float]] = []
        self._cpu_busy = 0.0        # work seconds inside ops (see busy_s)
        self.emulate: tuple[float, float] | None = None
        self._last_state_bytes: dict[str, int] = {}

    def setup_env(self, env: dict) -> bool:
        import os

        os.environ.update({k: str(v) for k, v in env.items()})
        if "JAX_PLATFORMS" in env:
            # jax is already imported in this process (the actor class
            # pulls it in), so the env var alone cannot steer the
            # backend — the config update can, as long as no jax call
            # has initialized a backend yet (none has: load_stage is
            # the first to touch arrays)
            import jax

            jax.config.update("jax_platforms",
                              str(env["JAX_PLATFORMS"]) or None)
        return True

    def ensure_cpu_devices(self, n: int) -> bool:
        """Give this worker >= n virtual CPU devices for its intra-stage
        data-parallel group (the test/laptop stand-in for a worker's
        local TPU chips). Must run before the first array op — the flag
        only counts at backend init, which load_stage triggers."""
        import os

        n = int(n)
        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append(f"--xla_force_host_platform_device_count={n}")
        os.environ["XLA_FLAGS"] = " ".join(flags)
        import jax

        return len(jax.local_devices()) >= n

    # ------------------------------------------------------------------

    def load_stage(self, cfg_kwargs: dict, params_blob: bytes, lr: float,
                   num_microbatches: int, num_repeats: int = 1,
                   zero_stage: int = 0, data_parallel: int = 1,
                   momentum: float = 0.0,
                   emulate_ms: tuple | None = None) -> int:
        """Install this worker's config + its R virtual-stage param
        chunks (params_blob: cloudpickled list, chunk r == virtual
        stage r*S + stage). Returns the worker's parameter count (the
        driver logs the split).

        `emulate_ms=(fwd_ms, bwd_ms)` switches the worker into schedule
        emulation: ops sleep a modeled per-chunk duration (the full
        stage's cost split across R virtual-stage chunks) instead of
        running XLA, while everything else — submission order, FIFO
        execution, activation hand-off through the object store, span
        and busy accounting — stays the real path. Sleeping workers
        overlap even on a single host core, so the measured bubble
        reflects schedule quality plus real dispatch overhead rather
        than host CPU contention (see the pipeline bench)."""
        import jax

        from ray_tpu.models.pipelined import PipelinedConfig

        self.cfg = PipelinedConfig(**cfg_kwargs)
        self.lr = float(lr)
        self.num_microbatches = int(num_microbatches)
        self.num_repeats = int(num_repeats)
        self.zero_stage = int(zero_stage)
        self.data_parallel = int(data_parallel)
        self.momentum = float(momentum)
        self.emulate = (tuple(float(x) / 1e3 for x in emulate_ms)
                        if emulate_ms else None)
        chunks = cloudpickle.loads(params_blob)
        if not isinstance(chunks, list):  # single-chunk (flat) callers
            chunks = [chunks]
        if self.data_parallel > 1:
            from jax.sharding import Mesh

            devs = jax.local_devices()
            if len(devs) < self.data_parallel:
                raise ValueError(
                    f"stage {self.stage}: data_parallel="
                    f"{self.data_parallel} needs that many local "
                    f"devices, have {len(devs)}")
            self.mesh = Mesh(
                np.array(devs[:self.data_parallel]).reshape(-1, 1),
                ("data", "fsdp"))
        # params enter resident in their ZeRO layout: 1/D when stage 3,
        # replicated otherwise
        self.params = [
            jax.device_put(
                jax.tree.map(jax.numpy.asarray, c),
                self._layout(c, sharded=self.zero_stage >= 3))
            for c in chunks
        ]
        self._grads = [None] * self.num_repeats
        if self.momentum:
            self._vel = [
                jax.device_put(
                    jax.tree.map(lambda a: np.zeros_like(np.asarray(a)),
                                 c),
                    self._layout(c, sharded=self.zero_stage >= 1))
                for c in chunks
            ]
        self._build_programs()
        return sum(int(np.prod(x.shape))
                   for c in self.params for x in jax.tree.leaves(c))

    def _layout(self, tree, sharded: bool):
        """NamedShardings for a chunk tree: the +data-axis 1/D ZeRO
        layout when `sharded` (and a data mesh exists), else replicated
        over the stage's device group. Without a mesh: no-op layouts
        (plain single-device placement)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self.mesh is None:
            dev = jax.local_devices()[0]
            return jax.tree.map(lambda _: dev, tree)
        if not sharded:
            return jax.tree.map(
                lambda _: NamedSharding(self.mesh, P()), tree)
        from ray_tpu.parallel.sharding import PartitionRules
        from ray_tpu.train.spmd import zero1_shardings

        # catch-all replicated rules: the stage's base layout is
        # replicated over its data group, so the ZeRO layout is purely
        # the +data axis on the first evenly-divisible dim
        return zero1_shardings(PartitionRules([]), tree, self.mesh,
                               data_axis="data")

    def _constrain(self, tree, layouts):
        import jax

        return jax.tree.map(jax.lax.with_sharding_constraint, tree,
                            layouts)

    def _build_programs(self):
        """Per-repeat jitted forward + jitted REMATERIALIZED backward
        (the backward re-runs the chunk forward under vjp instead of
        keeping live residual closures — so both directions hit the XLA
        compile cache across microbatches/steps, and the only
        per-microbatch state parked between fwd and bwd is the chunk's
        input activation, exactly the 1F1B memory shape). Virtual stage
        v = r*S + stage; chunk 0 embeds, chunk V-1 computes the loss.
        ZeRO composition happens here: stage-3 params are gathered
        just-in-time inside both programs (pinned to the replicated
        layout so partitioning matches the unsharded program), and the
        backward emits dparams pinned replicated then reduce-scattered
        1/D when zero_stage >= 2."""
        import jax

        from ray_tpu.models.pipelined import stage_apply

        S, R = self.num_stages, self.num_repeats
        V = S * R

        for r in range(R):
            v = r * S + self.stage
            first, last = v == 0, v == V - 1

            def fn(p, x, t, _v=v):
                if self.zero_stage >= 3 and self.mesh is not None:
                    p = self._constrain(p, self._layout(p, sharded=False))
                return stage_apply(self.cfg, p, _v, V, x, targets=t,
                                   mesh=self.mesh)

            if last:
                self._jfwd[r] = jax.jit(fn)
            else:
                self._jfwd[r] = jax.jit(
                    lambda p, x, _fn=fn: _fn(p, x, None))

            def bwd(p, x, t, g, _fn=fn, _first=first, _last=last):
                if _last:
                    _, vjp = jax.vjp(
                        lambda pp, xx: _fn(pp, xx, t), p, x)
                else:
                    _, vjp = jax.vjp(
                        lambda pp, xx: _fn(pp, xx, None), p, x)
                dparams, dx = vjp(g)
                if _first:
                    # chunk 0's input is int tokens: drop the float0
                    # cotangent instead of shipping it
                    dx = None
                if self.mesh is not None:
                    # replicated pin, THEN the ZeRO scatter — the same
                    # double constraint that keeps spmd.py parity exact
                    dparams = self._constrain(
                        dparams, self._layout(dparams, sharded=False))
                    if self.zero_stage >= 2:
                        dparams = self._constrain(
                            dparams, self._layout(dparams, sharded=True))
                return dparams, dx

            self._jbwd[r] = jax.jit(bwd)

        def update(p, g, v):
            if v is not None:
                v = jax.tree.map(
                    lambda vv, gg: self.momentum * vv + gg, v, g)
                g_eff = v
            else:
                g_eff = g
            new_p = jax.tree.map(lambda pp, gg: pp - self.lr * gg,
                                 p, g_eff)
            if self.mesh is not None:
                new_p = self._constrain(
                    new_p,
                    self._layout(new_p, sharded=self.zero_stage >= 3))
                if v is not None:
                    v = self._constrain(
                        v, self._layout(v, sharded=self.zero_stage >= 1))
            return new_p, v

        self._jupd = jax.jit(update)

    # ------------------------------------------------------------------

    def _put_batch(self, arr):
        """Device-put an activation/batch leaf sharded over the stage's
        data group (leading dim), or plainly without a mesh."""
        import jax
        import jax.numpy as jnp

        x = jnp.asarray(arr)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            return jax.device_put(x, NamedSharding(self.mesh, P("data")))
        return x

    def forward(self, r: int, mb: int, payload, targets=None):
        """Forward one microbatch through virtual stage r*S + stage:
        payload is tokens (virtual stage 0) or the previous virtual
        stage's activation. Returns the activation for the next virtual
        stage, or the microbatch loss on the last. The inputs park as
        residuals until `backward(r, mb)`."""
        import jax

        t0 = time.perf_counter()
        c0 = time.process_time()
        v = r * self.num_stages + self.stage
        last = v == self.num_stages * self.num_repeats - 1
        if self.emulate is not None:
            dur = self.emulate[0] / self.num_repeats
            time.sleep(dur)
            self._cpu_busy += dur
            self._saved[(r, mb)] = (payload,)
            t1 = time.perf_counter()
            self._spans.append((t0, t1))
            self._trace("fwd", t0, t1, r, mb)
            return 0.0 if last else payload
        x = self._put_batch(payload)
        if last:
            tgt = self._put_batch(targets)
            out = self._jfwd[r](self.params[r], x, tgt)
            self._saved[(r, mb)] = (x, tgt)
        else:
            out = self._jfwd[r](self.params[r], x)
            self._saved[(r, mb)] = (x,)
        out = jax.block_until_ready(out)
        t1 = time.perf_counter()
        self._cpu_busy += time.process_time() - c0
        self._spans.append((t0, t1))
        self._trace("fwd", t0, t1, r, mb)
        if last:
            # the driver reads the microbatch loss straight off this
            # call's ObjectRef — no separate loss plumbing
            return float(out)
        return np.asarray(out)

    def backward(self, r: int, mb: int, grad=None):
        """Backward one microbatch through virtual stage r*S + stage:
        grad is the next virtual stage's activation cotangent (None on
        the last, which seeds with 1/M so the accumulated grads are
        those of the MEAN loss). Returns the cotangent for the previous
        virtual stage (True from virtual stage 0)."""
        import jax
        import jax.numpy as jnp

        t0 = time.perf_counter()
        c0 = time.process_time()
        v = r * self.num_stages + self.stage
        saved = self._saved.pop((r, mb))
        if self.emulate is not None:
            dur = self.emulate[1] / self.num_repeats
            time.sleep(dur)
            self._cpu_busy += dur
            t1 = time.perf_counter()
            self._spans.append((t0, t1))
            self._trace("bwd", t0, t1, r, mb)
            return True if v == 0 else saved[0]
        if grad is None:
            seed = jnp.float32(1.0 / self.num_microbatches)
        else:
            seed = self._put_batch(grad)
        tgt = saved[1] if len(saved) > 1 else None
        dparams, dx = self._jbwd[r](self.params[r], saved[0], tgt, seed)
        dparams = jax.block_until_ready(dparams)
        if self._grads[r] is None:
            self._grads[r] = dparams
        else:
            # accumulate in the resident layout — reduce-scattered 1/D
            # when zero_stage >= 2: this buffer IS the ZeRO-2 grad state
            self._grads[r] = jax.tree.map(jnp.add, self._grads[r],
                                          dparams)
        t1 = time.perf_counter()
        self._cpu_busy += time.process_time() - c0
        self._spans.append((t0, t1))
        self._trace("bwd", t0, t1, r, mb)
        if v == 0:
            return True
        return np.asarray(dx)

    def finish_step(self) -> dict:
        """Apply the accumulated grads per chunk (SGD(+momentum),
        matching `pipelined_train_step` at momentum=0) and report this
        stage's timing — busy seconds and the step window (the driver's
        bubble inputs) — plus the per-device resident bytes of each
        state component, measured at the point the grad state is fully
        accumulated (the honest ZeRO-2 number)."""
        import jax

        from ray_tpu.train.spmd import optimizer_state_bytes

        if self._saved:
            raise RuntimeError(
                f"stage {self.stage}: {len(self._saved)} microbatches "
                f"never ran backward — schedule bug")
        self._last_state_bytes = {
            "param_state_bytes": optimizer_state_bytes(self.params),
            "grad_state_bytes": optimizer_state_bytes(self._grads),
            "velocity_state_bytes": optimizer_state_bytes(self._vel),
        }
        for r in range(self.num_repeats):
            if self._grads[r] is None:
                continue
            vel = self._vel[r] if self._vel is not None else None
            self.params[r], new_vel = self._jupd(
                self.params[r], self._grads[r], vel)
            if self._vel is not None:
                self._vel[r] = new_vel
            self._grads[r] = None
        spans, self._spans = self._spans, []
        busy, self._cpu_busy = self._cpu_busy, 0.0
        busy_wall = sum(t1 - t0 for t0, t1 in spans)
        window = ((min(t0 for t0, _ in spans),
                   max(t1 for _, t1 in spans)) if spans else (0.0, 0.0))
        return {"stage": self.stage, "busy_s": busy,
                "busy_wall_s": busy_wall,
                "window_s": window[1] - window[0], "ops": len(spans),
                **self._last_state_bytes}

    def get_params(self) -> bytes:
        """This worker's current chunk params (numpy trees, list over
        repeats) — checkpoint shards and the parity tests' merge
        path."""
        import jax

        return cloudpickle.dumps(
            [jax.tree.map(np.asarray, c) for c in self.params])

    def ping(self) -> str:
        return "pong"

    def _trace(self, kind: str, t0: float, t1: float, r: int,
               mb: int) -> None:
        from ray_tpu.util import tracing

        v = r * self.num_stages + self.stage
        tracing.record_interval(
            f"pipeline.stage{self.stage}.v{v}.{kind}.mb{mb}", t0, t1,
            category="train")


@dataclasses.dataclass
class PipelineStepMetrics:
    loss: float
    bubble_ratio: float
    bubble_theoretical: float
    step_seconds: float
    microbatches: int
    virtual_stages: int = 0
    num_repeats: int = 1

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class PipelineStrategy:
    """Drive 1F1B pipeline-parallel training of the pipelined
    transformer over `num_stages` stage workers — optionally interleaved
    (`num_repeats=R` virtual stages per worker) and/or composed with
    intra-stage ZeRO data parallelism (`zero_stage`, `data_parallel`).

    ::

        ps = PipelineStrategy(PipelinedConfig(), num_stages=2,
                              num_microbatches=8, num_repeats=2,
                              zero_stage=3, data_parallel=2)
        for _ in range(steps):
            metrics = ps.train_step({"tokens": ..., "targets": ...})
        ps.shutdown()
    """

    def __init__(self, cfg, num_stages: int,
                 num_microbatches: int | None = None, lr: float = 1e-2,
                 seed: int = 0, params=None,
                 resources_per_worker: dict | None = None,
                 placement_strategy: str = "PACK",
                 num_repeats: int = 1, zero_stage: int = 0,
                 data_parallel: int = 1, momentum: float = 0.0,
                 emulate_ms: tuple | None = None):
        import jax

        from ray_tpu.models.pipelined import (
            PipelinedConfig,
            init_pipelined,
            split_pipeline_stages_interleaved,
        )
        from ray_tpu.train.worker_group import WorkerGroup

        self.cfg = (cfg if isinstance(cfg, PipelinedConfig)
                    else PipelinedConfig(**dict(cfg or {})))
        self.num_stages = num_stages
        self.num_repeats = int(num_repeats)
        self.zero_stage = int(zero_stage)
        self.data_parallel = int(data_parallel)
        self.momentum = float(momentum)
        self.emulate_ms = tuple(emulate_ms) if emulate_ms else None
        self.num_microbatches = int(
            num_microbatches or self.cfg.num_microbatches)
        if self.zero_stage not in (0, 1, 2, 3):
            raise ValueError(f"zero_stage must be 0|1|2|3, "
                             f"got {zero_stage}")
        if self.num_repeats > 1 and self.num_microbatches < num_stages:
            raise ValueError(
                f"interleaved schedule needs microbatches "
                f"{self.num_microbatches} >= stages {num_stages}")
        self.lr = lr
        # FIFO workers: the schedule submission order must BE the
        # per-stage execution order (see module docstring)
        self.wg = WorkerGroup(
            num_workers=num_stages,
            resources_per_worker=resources_per_worker,
            placement_strategy=placement_strategy,
            worker_cls=PipelineStageWorker,
            max_concurrency=1,
        )
        try:
            on_cpu = jax.devices()[0].platform == "cpu"
            if on_cpu:
                # test/laptop path: stage workers must not grab a TPU
                self.wg.execute("setup_env", {"JAX_PLATFORMS": "cpu"})
                if self.data_parallel > 1:
                    ok = self.wg.execute("ensure_cpu_devices",
                                         self.data_parallel)
                    if not all(ok):
                        raise RuntimeError(
                            "stage workers could not provision "
                            f"{self.data_parallel} cpu devices")
            if params is None:
                params = init_pipelined(jax.random.PRNGKey(seed),
                                        self.cfg)
            cfg_kwargs = dataclasses.asdict(self.cfg)
            stages = split_pipeline_stages_interleaved(
                params, self.cfg, num_stages, self.num_repeats)
            self.stage_param_counts = [
                self.wg.execute_single(
                    s, "load_stage", cfg_kwargs,
                    cloudpickle.dumps(
                        [jax.tree.map(np.asarray, c) for c in stages[s]]),
                    lr, self.num_microbatches, self.num_repeats,
                    self.zero_stage, self.data_parallel, self.momentum,
                    self.emulate_ms)
                for s in range(num_stages)
            ]
        except Exception:
            self.wg.shutdown()
            raise
        self.last_metrics: PipelineStepMetrics | None = None
        self.last_stage_stats: list[dict] | None = None

    # ------------------------------------------------------------------

    def train_step(self, batch: dict) -> dict:
        """One pipelined step over the whole batch: split into M
        microbatches, stream activations down / cotangents up the
        virtual-stage chain (flat or interleaved order), then apply
        each stage's update. Returns {loss, bubble_ratio,
        bubble_theoretical, step_seconds, microbatches, virtual_stages,
        num_repeats}."""
        import ray_tpu
        from ray_tpu.util import tracing

        S, M, R = self.num_stages, self.num_microbatches, self.num_repeats
        V = S * R
        tokens = np.asarray(batch["tokens"])
        targets = np.asarray(batch["targets"])
        B = tokens.shape[0]
        if B % M:
            raise ValueError(f"batch {B} not divisible by "
                             f"microbatches {M}")
        mb = B // M
        order = (interleaved_1f1b_submission_order(S, M, R) if R > 1
                 else one_f_one_b_submission_order(S, M))
        t0 = time.perf_counter()
        with tracing.span("pipeline.train_step", category="train"):
            fwd: dict[tuple[int, int], Any] = {}
            bwd: dict[tuple[int, int], Any] = {}
            for kind, v, m in order:
                w = self.wg.workers[v % S]
                r = v // S
                if kind == "fwd":
                    payload = (tokens[m * mb:(m + 1) * mb] if v == 0
                               else fwd[(v - 1, m)])
                    tgt = (targets[m * mb:(m + 1) * mb]
                           if v == V - 1 else None)
                    fwd[(v, m)] = w.forward.remote(r, m, payload, tgt)
                else:
                    g = bwd[(v + 1, m)] if v < V - 1 else None
                    bwd[(v, m)] = w.backward.remote(r, m, g)
            losses = ray_tpu.get([fwd[(V - 1, m)] for m in range(M)],
                                 timeout=300)
            ray_tpu.get([bwd[(0, m)] for m in range(M)], timeout=300)
            stats = self.wg.execute("finish_step")
        dt = time.perf_counter() - t0
        makespan = max(st["window_s"] for st in stats)
        busy = sum(st["busy_s"] for st in stats)
        bubble = (1.0 - busy / (S * makespan)) if makespan > 0 else 0.0
        m_bubble, m_micro, m_virtual = _strategy_metrics()
        m_bubble.set(bubble)
        m_micro.inc(M)
        m_virtual.set(float(V))
        self.last_stage_stats = stats
        self.last_metrics = PipelineStepMetrics(
            loss=float(np.mean(losses)),
            bubble_ratio=bubble,
            bubble_theoretical=(
                theoretical_bubble_interleaved(S, M, R) if R > 1
                else theoretical_bubble(S, M)),
            step_seconds=dt,
            microbatches=M,
            virtual_stages=V,
            num_repeats=R,
        )
        return self.last_metrics.as_dict()

    def full_params(self):
        """Merge every worker's current chunk params back into one tree
        (the single-program layout) — checkpoint/parity surface."""
        from ray_tpu.models.pipelined import (
            merge_pipeline_stages_interleaved,
        )

        blobs = self.wg.execute("get_params")
        return merge_pipeline_stages_interleaved(
            [cloudpickle.loads(b) for b in blobs])

    # ------------------------------------------------------------------

    def save_checkpoint(self, directory: str):
        """Write a restore-compatible checkpoint: every stage worker
        reports its param shard (`get_params`), the driver persists one
        shard file per stage plus a meta manifest. Pair with
        `load_pipeline_checkpoint` (reassembles the full single-program
        tree) and `CheckpointManager.register` for retention."""
        from ray_tpu.train.checkpoint import Checkpoint

        os.makedirs(directory, exist_ok=True)
        blobs = self.wg.execute("get_params")
        for s, blob in enumerate(blobs):
            with open(os.path.join(directory, f"stage_{s:04d}.pkl"),
                      "wb") as f:
                f.write(blob)
        meta = {
            "format": "pipeline-stage-shards-v1",
            "num_stages": self.num_stages,
            "num_repeats": self.num_repeats,
            "zero_stage": self.zero_stage,
            "data_parallel": self.data_parallel,
            "model": dataclasses.asdict(self.cfg),
        }
        with open(os.path.join(directory, "meta.json"), "w") as f:
            json.dump(meta, f, indent=1)
        return Checkpoint(directory)

    def shutdown(self):
        self.wg.shutdown()


def load_pipeline_checkpoint(path: str):
    """Reassemble a `PipelineStrategy.save_checkpoint` directory into
    (full_params, meta): per-stage shard files merge back into the
    single-program param tree, restore-compatible with both
    `PipelineStrategy(params=...)` (any stage/repeat split) and the
    in-program `pipelined_train_step`."""
    from ray_tpu.models.pipelined import merge_pipeline_stages_interleaved

    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    chunks = []
    for s in range(int(meta["num_stages"])):
        with open(os.path.join(path, f"stage_{s:04d}.pkl"), "rb") as f:
            chunks.append(cloudpickle.loads(f.read()))
    return merge_pipeline_stages_interleaved(chunks), meta
