"""SPMD train-step machinery.

Replaces the reference's DDP/FSDP wrap (`prepare_model`,
ray/train/torch/train_loop_utils.py:162,179-183) and its NCCL gradient
allreduce with a single jitted program over a mesh: parameters carry
NamedShardings from partition rules (fsdp/tensor axes), the batch is
sharded over (data, fsdp), and GSPMD inserts the reduce-scatter /
all-gather traffic that DDP/ZeRO would do by hand.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.mesh import BATCH_AXES
from ray_tpu.parallel.sharding import PartitionRules

PyTree = Any


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    params: PyTree
    opt_state: PyTree
    step: jax.Array

    @staticmethod
    def create(params: PyTree, tx: optax.GradientTransformation) -> "TrainState":
        return TrainState(
            params=params,
            opt_state=tx.init(params),
            step=jnp.zeros((), jnp.int32),
        )


def batch_shardings(mesh: Mesh, batch_example: PyTree) -> PyTree:
    """Shard the leading (batch) dim of every leaf over (data, fsdp)."""
    axes = tuple(a for a in BATCH_AXES if dict(mesh.shape).get(a, 1) > 1)
    spec = P(axes if axes else None)
    return jax.tree.map(lambda _: NamedSharding(mesh, spec), batch_example)


def state_shardings(
    rules: PartitionRules, state: TrainState, mesh: Mesh
) -> TrainState:
    """NamedShardings for a TrainState. Optimizer moments are param-shaped
    subtrees whose tree paths *end with* the parameter's own path (e.g.
    `0/mu/blocks/attn_qkv/kernel`), so the same partition rules — which
    match with `re.search` — shard them identically to their parameter;
    scalar leaves (step counts) fall through to the replicated catch-all."""
    return TrainState(
        params=rules.shardings(state.params, mesh),
        opt_state=rules.shardings(state.opt_state, mesh),
        step=NamedSharding(mesh, P()),
    )


def make_train_step(
    loss_fn: Callable[[PyTree, PyTree], jax.Array],
    tx: optax.GradientTransformation,
    donate: bool = True,
) -> Callable[[TrainState, PyTree], tuple[TrainState, dict]]:
    """Build a jitted train step `(state, batch) -> (state, metrics)`.

    Sharding is carried by the arrays themselves (state from
    `init_sharded_state`, batch device_put with `batch_shardings`); jit
    propagates it and GSPMD inserts the collectives. Call under
    `with mesh:` so in-model `constrain` calls resolve.
    """

    def step(state: TrainState, batch: PyTree):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new_state = TrainState(
            params=new_params, opt_state=new_opt, step=state.step + 1
        )
        return new_state, {"loss": loss, "grad_norm": gnorm}

    jitted = jax.jit(step, donate_argnums=(0,) if donate else ())

    # Profiling hooks (the Podracer-style breakdown: compile vs. step —
    # a scaling cliff usually shows up first as recompiles or step-time
    # spread). Registry-backed, so worker-process numbers surface on the
    # head's cluster /metrics page tagged by node.
    from ray_tpu.util.metrics import Counter, Histogram

    m_step = Histogram(
        "train_step_seconds",
        "Host-side train-step dispatch time (includes device wait on "
        "synchronous backends)",
        boundaries=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60))
    m_miss = Counter(
        "train_compile_misses_total",
        "Train steps that triggered an XLA compile (new shape/sharding)")
    m_compile = Histogram(
        "train_compile_seconds", "XLA compile time for the train step",
        boundaries=(0.1, 0.5, 1, 5, 10, 30, 60, 120, 300))

    def instrumented(state: TrainState, batch: PyTree):
        from ray_tpu.util import tracing

        before = tracing.jit_cache_size(jitted)
        t0 = time.perf_counter()
        out = jitted(state, batch)
        dt = time.perf_counter() - t0
        if not tracing.note_compile_if_grew(jitted, before, dt, m_miss,
                                            m_compile, "train.compile"):
            m_step.observe(dt)
        return out

    instrumented.jitted = jitted  # AOT access (lower/compile) if needed
    return instrumented


def init_sharded_state(
    init_fn: Callable[[], PyTree],
    tx: optax.GradientTransformation,
    mesh: Mesh,
    rules: PartitionRules,
) -> TrainState:
    """Initialize a TrainState directly into its sharded layout: the init
    is jitted with out_shardings so every shard is materialized on its
    owning device — no host-memory full copy (crucial for models larger
    than one chip's HBM)."""

    def make():
        params = init_fn()
        return TrainState.create(params, tx)

    abstract = jax.eval_shape(make)
    shardings = state_shardings(rules, abstract, mesh)
    with mesh:
        return jax.jit(make, out_shardings=shardings)()
