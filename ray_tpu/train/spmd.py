"""SPMD train-step machinery.

Replaces the reference's DDP/FSDP wrap (`prepare_model`,
ray/train/torch/train_loop_utils.py:162,179-183) and its NCCL gradient
allreduce with a single jitted program over a mesh: parameters carry
NamedShardings from partition rules (fsdp/tensor axes), the batch is
sharded over (data, fsdp), and GSPMD inserts the reduce-scatter /
all-gather traffic that DDP/ZeRO would do by hand.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.mesh import BATCH_AXES
from ray_tpu.parallel.sharding import PartitionRules

PyTree = Any


class StepWaterfall:
    """Per-step latency attribution for the train path (the direction-5
    scoreboard companion: MFU says how fast, this says where the time
    went). OFF by default — the instrumented step checks one bool, so
    attribution costs nothing when disabled; when enabled it adds a
    device sync per step (that is the point: a profiling run, not a
    record run — `bench.py --trace` turns it on).

    Phases per step: ``data_wait`` (caller-reported input fetch, see
    `note_data_wait`), ``h2d`` (host->device transfer of numpy batch
    leaves), ``compile`` (steps that tripped an XLA compile),
    ``compute`` (dispatch + device execution), ``collective``
    (host-side collective wall time observed during the step — the
    in-program collective share is only visible to the device
    profiler). Phases sum to the step's wall time (data_wait + h2d +
    compile-or-compute; collective is carved out of compute)."""

    def __init__(self):
        # "0"/"false"/"" all mean OFF — an operator writing =0 to be
        # explicit must not silently enable per-step device syncs
        self.enabled = os.environ.get(
            "RAY_TPU_STEP_WATERFALL", "").strip().lower() \
            not in ("", "0", "false", "no")
        self._lock = threading.Lock()
        self.phases: dict[str, float] = {}  # guarded_by(_lock)
        self.steps = 0  # guarded_by(_lock)
        self._pending_data_wait = 0.0  # guarded_by(_lock)
        self._last_step_end: float | None = None  # guarded_by(_lock)

    def reset(self) -> None:
        with self._lock:
            self.phases = {}
            self.steps = 0
            self._pending_data_wait = 0.0
            self._last_step_end = None

    def step_gap(self, t_start: float, data_wait: float) -> float:
        """Host time between the previous step's end and this step's
        start not already claimed by data_wait — the python/dispatch
        overhead of the train loop itself (charged to `host`, so a
        loop's phase totals sum wall-to-wall to its elapsed time)."""
        with self._lock:
            last = self._last_step_end
        if last is None:
            return 0.0
        return max(0.0, t_start - last - data_wait)

    def mark_step_end(self, t_end: float) -> None:
        with self._lock:
            self._last_step_end = t_end

    def note_data_wait(self, seconds: float) -> None:
        """Report time spent fetching/waiting for the NEXT batch (data
        pipeline stall); charged to the next instrumented step."""
        with self._lock:
            self._pending_data_wait += max(0.0, seconds)

    def take_data_wait(self) -> float:
        with self._lock:
            dw, self._pending_data_wait = self._pending_data_wait, 0.0
            return dw

    def add(self, step_phases: dict[str, float]) -> None:
        with self._lock:
            for k, v in step_phases.items():
                if v > 0.0:
                    self.phases[k] = self.phases.get(k, 0.0) + v
            self.steps += 1

    def summary(self) -> dict:
        with self._lock:
            phases = dict(self.phases)
            steps = self.steps
        total = sum(phases.values())
        return {"steps": steps, "total_seconds": total,
                "phases": phases,
                "percent": {k: (100.0 * v / total if total else 0.0)
                            for k, v in phases.items()}}

    def table(self) -> str:
        """Human attribution table: percent of step time per phase."""
        s = self.summary()
        lines = [f"# step attribution over {s['steps']} steps "
                 f"({s['total_seconds']:.3f}s attributed)"]
        for k, v in sorted(s["phases"].items(), key=lambda kv: -kv[1]):
            lines.append(f"#   {k:<12} {v:9.4f}s  {s['percent'][k]:5.1f}%")
        return "\n".join(lines)


waterfall = StepWaterfall()


def enable_step_waterfall(on: bool = True) -> None:
    """Turn per-step attribution on/off in THIS process. Worker
    processes inherit it from the RAY_TPU_STEP_WATERFALL env var
    (settable via runtime_env/setup_env), so a WorkerGroup gang can be
    flipped into profiling mode without code changes."""
    waterfall.enabled = on


class data_wait:
    """Context manager charging the enclosed block to the next step's
    ``data_wait`` phase — wrap your batch fetch::

        with spmd.data_wait():
            batch = next(batch_iter)
        state, metrics = step(state, batch)

    No-op (beyond two clock reads) when attribution is disabled."""

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if waterfall.enabled:
            waterfall.note_data_wait(time.perf_counter() - self._t0)
        return False


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    params: PyTree
    opt_state: PyTree
    step: jax.Array

    @staticmethod
    def create(params: PyTree, tx: optax.GradientTransformation) -> "TrainState":
        return TrainState(
            params=params,
            opt_state=tx.init(params),
            step=jnp.zeros((), jnp.int32),
        )


def batch_shardings(mesh: Mesh, batch_example: PyTree) -> PyTree:
    """Shard the leading (batch) dim of every leaf over (data, fsdp)."""
    axes = tuple(a for a in BATCH_AXES if dict(mesh.shape).get(a, 1) > 1)
    spec = P(axes if axes else None)
    return jax.tree.map(lambda _: NamedSharding(mesh, spec), batch_example)


def state_shardings(
    rules: PartitionRules, state: TrainState, mesh: Mesh
) -> TrainState:
    """NamedShardings for a TrainState. Optimizer moments are param-shaped
    subtrees whose tree paths *end with* the parameter's own path (e.g.
    `0/mu/blocks/attn_qkv/kernel`), so the same partition rules — which
    match with `re.search` — shard them identically to their parameter;
    scalar leaves (step counts) fall through to the replicated catch-all."""
    return TrainState(
        params=rules.shardings(state.params, mesh),
        opt_state=rules.shardings(state.opt_state, mesh),
        step=NamedSharding(mesh, P()),
    )


def make_train_step(
    loss_fn: Callable[[PyTree, PyTree], jax.Array],
    tx: optax.GradientTransformation,
    donate: bool = True,
) -> Callable[[TrainState, PyTree], tuple[TrainState, dict]]:
    """Build a jitted train step `(state, batch) -> (state, metrics)`.

    Sharding is carried by the arrays themselves (state from
    `init_sharded_state`, batch device_put with `batch_shardings`); jit
    propagates it and GSPMD inserts the collectives. Call under
    `with mesh:` so in-model `constrain` calls resolve.
    """

    def step(state: TrainState, batch: PyTree):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new_state = TrainState(
            params=new_params, opt_state=new_opt, step=state.step + 1
        )
        return new_state, {"loss": loss, "grad_norm": gnorm}

    jitted = jax.jit(step, donate_argnums=(0,) if donate else ())

    # Profiling hooks (the Podracer-style breakdown: compile vs. step —
    # a scaling cliff usually shows up first as recompiles or step-time
    # spread). Registry-backed, so worker-process numbers surface on the
    # head's cluster /metrics page tagged by node.
    from ray_tpu.util.metrics import Counter, Histogram

    m_step = Histogram(
        "train_step_seconds",
        "Host-side train-step dispatch time (includes device wait on "
        "synchronous backends)",
        boundaries=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60))
    m_miss = Counter(
        "train_compile_misses_total",
        "Train steps that triggered an XLA compile (new shape/sharding)")
    m_compile = Histogram(
        "train_compile_seconds", "XLA compile time for the train step",
        boundaries=(0.1, 0.5, 1, 5, 10, 30, 60, 120, 300))
    m_phase = Histogram(
        "train_step_phase_seconds",
        "Per-step waterfall phases (data_wait/h2d/compile/collective/"
        "compute) — populated only while step attribution is enabled",
        boundaries=(0.0001, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5,
                    30),
        tag_keys=("phase",))

    def _attributed_step(state: TrainState, batch: PyTree):
        """Waterfall-mode step: wall-to-wall phase attribution. Adds a
        device sync per step (a profiling run, not a record run)."""
        from ray_tpu.util import tracing
        from ray_tpu.util.collective import _collective_seconds

        data_wait = waterfall.take_data_wait()
        t0 = time.perf_counter()
        gap = waterfall.step_gap(t0, data_wait)
        leaves = jax.tree_util.tree_leaves(batch)
        if any(not isinstance(x, jax.Array) for x in leaves):
            # numpy/host leaves: the h2d copy jit would do implicitly,
            # made explicit so it is timed as its own phase
            batch = jax.block_until_ready(jax.device_put(batch))
        t1 = time.perf_counter()
        coll0 = _collective_seconds().sum_total()
        before = tracing.jit_cache_size(jitted)
        out = jitted(state, batch)
        # sync on the metrics dict (small leaves), not the new state:
        # blocking on loss/grad_norm means the whole step has executed
        out = (out[0], jax.block_until_ready(out[1]))
        t3 = time.perf_counter()
        dt = t3 - t1
        compiled = tracing.note_compile_if_grew(
            jitted, before, dt, m_miss, m_compile, "train.compile")
        coll = min(max(0.0, _collective_seconds().sum_total() - coll0),
                   dt)
        phases = {"data_wait": data_wait, "h2d": t1 - t0,
                  "collective": coll, "host": gap}
        phases["compile" if compiled else "compute"] = dt - coll
        if not compiled:
            m_step.observe(dt)
        for k, v in phases.items():
            if v > 0.0:
                m_phase.observe(v, tags={"phase": k})
        # laid-out sub-spans: data_wait | h2d | compile-or-compute, at
        # their true monotonic positions (perf_counter IS the monotonic
        # clock on linux; record_interval re-anchors to the epoch)
        if data_wait > 0.0:
            tracing.record_interval("train.step.data_wait",
                                    t0 - data_wait, t0, category="train")
        if t1 - t0 > 0.0:
            tracing.record_interval("train.step.h2d", t0, t1,
                                    category="train")
        tracing.record_interval(
            "train.step.compile" if compiled else "train.step.compute",
            t1, t3, category="train")
        waterfall.add(phases)
        waterfall.mark_step_end(t3)
        return out

    def instrumented(state: TrainState, batch: PyTree):
        if waterfall.enabled:
            return _attributed_step(state, batch)
        from ray_tpu.util import tracing

        before = tracing.jit_cache_size(jitted)
        t0 = time.perf_counter()
        out = jitted(state, batch)
        dt = time.perf_counter() - t0
        if not tracing.note_compile_if_grew(jitted, before, dt, m_miss,
                                            m_compile, "train.compile"):
            m_step.observe(dt)
        return out

    instrumented.jitted = jitted  # AOT access (lower/compile) if needed
    return instrumented


def init_sharded_state(
    init_fn: Callable[[], PyTree],
    tx: optax.GradientTransformation,
    mesh: Mesh,
    rules: PartitionRules,
) -> TrainState:
    """Initialize a TrainState directly into its sharded layout: the init
    is jitted with out_shardings so every shard is materialized on its
    owning device — no host-memory full copy (crucial for models larger
    than one chip's HBM)."""

    def make():
        params = init_fn()
        return TrainState.create(params, tx)

    abstract = jax.eval_shape(make)
    shardings = state_shardings(rules, abstract, mesh)
    with mesh:
        return jax.jit(make, out_shardings=shardings)()
