"""SPMD train-step machinery.

Replaces the reference's DDP/FSDP wrap (`prepare_model`,
ray/train/torch/train_loop_utils.py:162,179-183) and its NCCL gradient
allreduce with a single jitted program over a mesh: parameters carry
NamedShardings from partition rules (fsdp/tensor axes), the batch is
sharded over (data, fsdp), and GSPMD inserts the reduce-scatter /
all-gather traffic that DDP/ZeRO would do by hand.

The ZeRO ladder (`zero_stage=0|1|2|3`; `shard_optimizer=True` is the
back-compat spelling of stage 1): each rung shards one more
param-shaped component 1/N along the data axis ("Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training" —
each replica owns a shard instead of a copy), all expressed as sharding
constraints inside the single jitted program so XLA schedules/overlaps
the collectives itself:

- stage 1: optimizer state resident 1/N; the step becomes
  reduce-scatter(grads) → shard-local optax update → all-gather(params);
- stage 2: the gradient-accumulation buffer is ALSO resident 1/N —
  grads are reduce-scattered once per microstep and accumulate in the
  scattered layout between optimizer updates (`accum_steps`), so grad
  bytes join the per-chip memory win;
- stage 3: resident params are ALSO 1/N; the step all-gathers them
  just-in-time inside the jitted program (the gather sits before the
  loss, so XLA overlaps it with early forward compute) and new params
  are written back scattered — no full copy ever lives in HBM.

Per-chip bytes per component drop ~1/data-axis-size (see
`optimizer_state_bytes` and the `train_{optimizer,grad,param}_state_bytes`
gauges), which is headroom for a bigger per-chip batch. The math is
identical — sharding is layout, not arithmetic — so loss tracks the
replicated step exactly for elementwise-stable optimizers
(sgd/momentum); adam-family optimizers amplify the ulp-level
reduction-order differences between two differently-partitioned XLA
programs through mu/sqrt(nu), so their trajectories track closely but
not bitwise (see TRAINING.md "memory math & parity").
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.mesh import AXIS_DATA, BATCH_AXES
from ray_tpu.parallel.sharding import (
    PartitionRules,
    add_axis_to_spec,
    path_str,
)

PyTree = Any


class StepWaterfall:
    """Per-step latency attribution for the train path (the direction-5
    scoreboard companion: MFU says how fast, this says where the time
    went). OFF by default — the instrumented step checks one bool, so
    attribution costs nothing when disabled; when enabled it adds a
    device sync per step (that is the point: a profiling run, not a
    record run — `bench.py --trace` turns it on).

    Phases per step: ``data_wait`` (caller-reported input fetch, see
    `note_data_wait`), ``h2d`` (host->device transfer of numpy batch
    leaves), ``compile`` (steps that tripped an XLA compile),
    ``compute`` (dispatch + device execution), and per-op
    ``collective.<op>`` buckets (host-side collective wall time
    observed during the step, split by the collective_seconds ``op=``
    label — reduce_scatter / all_gather / allreduce / ... — so a ZeRO
    step's win/cost is attributable, not inferred). Phases sum to the
    step's wall time (data_wait + h2d + compile-or-compute; the
    collective buckets are carved out of compute). The IN-program
    collective share cannot be wall-timed from the host; instead the
    compiled step's collective op census (counts by op, from the HLO)
    is recorded alongside — see ``program_collectives`` in
    `summary()` and the `bench.py --trace` table."""

    def __init__(self):
        # "0"/"false"/"" all mean OFF — an operator writing =0 to be
        # explicit must not silently enable per-step device syncs
        self.enabled = os.environ.get(
            "RAY_TPU_STEP_WATERFALL", "").strip().lower() \
            not in ("", "0", "false", "no")
        self._lock = threading.Lock()
        self.phases: dict[str, float] = {}  # guarded_by(_lock)
        self.steps = 0  # guarded_by(_lock)
        self._pending_data_wait = 0.0  # guarded_by(_lock)
        self._last_step_end: float | None = None  # guarded_by(_lock)
        self.program_collectives: dict[str, int] = {}  # guarded_by(_lock)

    def reset(self) -> None:
        # program_collectives survives: it describes the COMPILED step
        # (recorded at the warmup compile), not the timing window a
        # reset opens — resetting before a timed run must not lose it
        with self._lock:
            self.phases = {}
            self.steps = 0
            self._pending_data_wait = 0.0
            self._last_step_end = None

    def note_program_collectives(self, counts: dict[str, int]) -> None:
        """Record the compiled step's collective op census (from
        `parallel.ops.collective_op_counts` on the optimized HLO) —
        the structural view of in-program collective traffic the host
        clock cannot see."""
        with self._lock:
            self.program_collectives = dict(counts)

    def step_gap(self, t_start: float, data_wait: float) -> float:
        """Host time between the previous step's end and this step's
        start not already claimed by data_wait — the python/dispatch
        overhead of the train loop itself (charged to `host`, so a
        loop's phase totals sum wall-to-wall to its elapsed time)."""
        with self._lock:
            last = self._last_step_end
        if last is None:
            return 0.0
        return max(0.0, t_start - last - data_wait)

    def mark_step_end(self, t_end: float) -> None:
        with self._lock:
            self._last_step_end = t_end

    def note_data_wait(self, seconds: float) -> None:
        """Report time spent fetching/waiting for the NEXT batch (data
        pipeline stall); charged to the next instrumented step."""
        with self._lock:
            self._pending_data_wait += max(0.0, seconds)

    def take_data_wait(self) -> float:
        with self._lock:
            dw, self._pending_data_wait = self._pending_data_wait, 0.0
            return dw

    def add(self, step_phases: dict[str, float]) -> None:
        with self._lock:
            for k, v in step_phases.items():
                if v > 0.0:
                    self.phases[k] = self.phases.get(k, 0.0) + v
            self.steps += 1

    def summary(self) -> dict:
        with self._lock:
            phases = dict(self.phases)
            steps = self.steps
            prog = dict(self.program_collectives)
        total = sum(phases.values())
        out = {"steps": steps, "total_seconds": total,
               "phases": phases,
               "percent": {k: (100.0 * v / total if total else 0.0)
                           for k, v in phases.items()}}
        if prog:
            out["program_collectives"] = prog
        return out

    def table(self) -> str:
        """Human attribution table: percent of step time per phase."""
        s = self.summary()
        lines = [f"# step attribution over {s['steps']} steps "
                 f"({s['total_seconds']:.3f}s attributed)"]
        for k, v in sorted(s["phases"].items(), key=lambda kv: -kv[1]):
            lines.append(f"#   {k:<24} {v:9.4f}s  {s['percent'][k]:5.1f}%")
        prog = s.get("program_collectives")
        if prog:
            census = " ".join(f"{k}x{v}" for k, v in sorted(prog.items()))
            lines.append(f"# in-program collectives (per step): {census}")
        return "\n".join(lines)


waterfall = StepWaterfall()


def enable_step_waterfall(on: bool = True) -> None:
    """Turn per-step attribution on/off in THIS process. Worker
    processes inherit it from the RAY_TPU_STEP_WATERFALL env var
    (settable via runtime_env/setup_env), so a WorkerGroup gang can be
    flipped into profiling mode without code changes."""
    waterfall.enabled = on


class data_wait:
    """Context manager charging the enclosed block to the next step's
    ``data_wait`` phase — wrap your batch fetch::

        with spmd.data_wait():
            batch = next(batch_iter)
        state, metrics = step(state, batch)

    No-op (beyond two clock reads) when attribution is disabled."""

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if waterfall.enabled:
            waterfall.note_data_wait(time.perf_counter() - self._t0)
        return False


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    params: PyTree
    opt_state: PyTree
    step: jax.Array
    # gradient-accumulation buffer (None unless accum_steps > 1): the
    # param-shaped state that ZeRO stage 2 keeps resident reduce-
    # scattered 1/N between optimizer updates
    grad_accum: PyTree = None

    @staticmethod
    def create(params: PyTree, tx: optax.GradientTransformation,
               grad_accum: bool = False) -> "TrainState":
        return TrainState(
            params=params,
            opt_state=tx.init(params),
            step=jnp.zeros((), jnp.int32),
            grad_accum=(jax.tree.map(jnp.zeros_like, params)
                        if grad_accum else None),
        )


def batch_shardings(mesh: Mesh, batch_example: PyTree) -> PyTree:
    """Shard the leading (batch) dim of every leaf over (data, fsdp)."""
    axes = tuple(a for a in BATCH_AXES if dict(mesh.shape).get(a, 1) > 1)
    spec = P(axes if axes else None)
    return jax.tree.map(lambda _: NamedSharding(mesh, spec), batch_example)


def zero1_shardings(
    rules: PartitionRules, tree: PyTree, mesh: Mesh,
    data_axis: str = AXIS_DATA,
) -> PyTree:
    """The raw +data-axis layout for a param-shaped tree: each leaf's
    rule spec additionally sharded over `data_axis` on the first evenly-
    divisible dimension, so N data-parallel replicas each own a 1/N
    shard instead of a full copy. Leaves with no divisible dim (and
    scalars like optimizer step counts) stay on their rule layout.
    Works on concrete arrays and abstract (eval_shape) trees alike.
    This is the layout every ZeRO rung applies to its component —
    `zero_shardings` decides WHICH components get it per stage."""
    def one(path, leaf):
        spec = rules.spec_for(path_str(path), mesh)
        return NamedSharding(
            mesh, add_axis_to_spec(spec, leaf.shape, mesh, data_axis))

    return jax.tree_util.tree_map_with_path(one, tree)


# which ladder rung starts sharding each state component: stage >= rung
# means the component lives resident in the 1/N +data-axis layout
ZERO_LADDER = {"optimizer": 1, "grads": 2, "params": 3}


def zero_shardings(
    rules: PartitionRules, tree: PyTree, mesh: Mesh, stage: int,
    component: str = "optimizer", data_axis: str = AXIS_DATA,
) -> PyTree:
    """Per-component ZeRO NamedShardings: the `component`
    ("optimizer" | "grads" | "params") tree gets the +data-axis 1/N
    layout (`zero1_shardings`) iff `stage` has reached its ladder rung
    (optimizer: 1, grads: 2, params: 3), else its plain rule layout.
    The single source of truth for what each zero_stage shards."""
    if component not in ZERO_LADDER:
        raise ValueError(f"unknown ZeRO component {component!r}; "
                         f"expected one of {sorted(ZERO_LADDER)}")
    if stage >= ZERO_LADDER[component]:
        return zero1_shardings(rules, tree, mesh, data_axis)
    return rules.shardings(tree, mesh)


def _resolve_zero_stage(zero_stage: int | None,
                        shard_optimizer: bool) -> int:
    """`zero_stage=None` defers to the legacy `shard_optimizer` bool
    (True == stage 1); an explicit stage wins over the bool."""
    if zero_stage is None:
        return 1 if shard_optimizer else 0
    if zero_stage not in (0, 1, 2, 3):
        raise ValueError(f"zero_stage must be 0|1|2|3, got {zero_stage}")
    return int(zero_stage)


def state_shardings(
    rules: PartitionRules, state: TrainState, mesh: Mesh,
    shard_optimizer: bool = False, data_axis: str = AXIS_DATA,
    zero_stage: int | None = None,
) -> TrainState:
    """NamedShardings for a TrainState. Optimizer moments are param-shaped
    subtrees whose tree paths *end with* the parameter's own path (e.g.
    `0/mu/blocks/attn_qkv/kernel`), so the same partition rules — which
    match with `re.search` — shard them identically to their parameter;
    scalar leaves (step counts) fall through to the replicated catch-all.

    `zero_stage` picks the ladder rung (`shard_optimizer=True` is the
    stage-1 spelling): stage >= 1 lays the optimizer state out 1/N along
    `data_axis`, stage >= 2 also the grad-accumulation buffer (when the
    state carries one), stage >= 3 also the resident params — each via
    `zero_shardings`. The train step reshards at its boundaries via
    constraints, so batch layouts are unchanged."""
    stage = _resolve_zero_stage(zero_stage, shard_optimizer)
    return TrainState(
        params=zero_shardings(rules, state.params, mesh, stage, "params",
                              data_axis),
        opt_state=zero_shardings(rules, state.opt_state, mesh, stage,
                                 "optimizer", data_axis),
        step=NamedSharding(mesh, P()),
        grad_accum=(None if state.grad_accum is None else
                    zero_shardings(rules, state.grad_accum, mesh, stage,
                                   "grads", data_axis)),
    )


def optimizer_state_bytes(tree: PyTree) -> int:
    """Worst-case per-device bytes resident for a state tree: for every
    addressable device, sum the bytes of the shards it holds (a
    replicated leaf contributes its full size on every device; a
    ZeRO-sharded leaf 1/N), and take the max. Named for its original
    (optimizer-state) use but component-agnostic — the same measurement
    backs the `train_{optimizer,grad,param}_state_bytes` gauges and the
    sharded-layout memory-win assertions."""
    per_dev: dict = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array):
            for sh in leaf.addressable_shards:
                per_dev[sh.device] = per_dev.get(sh.device, 0) \
                    + sh.data.nbytes
    return max(per_dev.values(), default=0)


_opt_bytes_gauge = None
_grad_bytes_gauge = None
_param_bytes_gauge = None


def _optimizer_bytes_gauge():
    global _opt_bytes_gauge
    if _opt_bytes_gauge is None:
        from ray_tpu.util.metrics import Gauge

        _opt_bytes_gauge = Gauge(
            "train_optimizer_state_bytes",
            "Per-chip optimizer-state bytes (max over addressable "
            "devices), tagged by layout=replicated|zero1 — the ZeRO-1 "
            "memory win made visible pre/post sharding",
            tag_keys=("layout",))
    return _opt_bytes_gauge


def _grad_state_bytes_gauge():
    global _grad_bytes_gauge
    if _grad_bytes_gauge is None:
        from ray_tpu.util.metrics import Gauge

        _grad_bytes_gauge = Gauge(
            "train_grad_state_bytes",
            "Per-chip resident gradient-accumulation bytes (max over "
            "addressable devices), tagged by layout=replicated|zero2 — "
            "the ZeRO-2 memory win: grads live reduce-scattered 1/N "
            "between accumulation steps",
            tag_keys=("layout",))
    return _grad_bytes_gauge


def _param_state_bytes_gauge():
    global _param_bytes_gauge
    if _param_bytes_gauge is None:
        from ray_tpu.util.metrics import Gauge

        _param_bytes_gauge = Gauge(
            "train_param_state_bytes",
            "Per-chip resident parameter bytes (max over addressable "
            "devices), tagged by layout=replicated|zero3 — the ZeRO-3 "
            "memory win: params live 1/N and are all-gathered "
            "just-in-time inside the jitted step",
            tag_keys=("layout",))
    return _param_bytes_gauge


def make_train_step(
    loss_fn: Callable[[PyTree, PyTree], jax.Array],
    tx: optax.GradientTransformation,
    donate: bool = True,
    shard_optimizer: bool = False,
    mesh: Mesh | None = None,
    rules: PartitionRules | None = None,
    data_axis: str = AXIS_DATA,
    zero_stage: int | None = None,
    accum_steps: int = 1,
) -> Callable[[TrainState, PyTree], tuple[TrainState, dict]]:
    """Build a jitted train step `(state, batch) -> (state, metrics)`.

    Sharding is carried by the arrays themselves (state from
    `init_sharded_state`, batch device_put with `batch_shardings`); jit
    propagates it and GSPMD inserts the collectives. Call under
    `with mesh:` so in-model `constrain` calls resolve.

    ``zero_stage`` picks the ladder rung (requires `mesh` + `rules` for
    stage >= 1; `shard_optimizer=True` is the stage-1 spelling; pair
    with a state from ``init_sharded_state`` at the same stage). All
    rungs live inside the SAME jitted program as sharding constraints:

    - stage >= 1: grads are constrained first to their rule layout
      (the pin: without it the sharded consumer back-propagates into
      the backward GEMMs' partitioning and the grad arithmetic stops
      matching the replicated step) and then to the 1/N layout
      (reduce-scatter); the optax update runs on shards.
    - stage >= 2 (+ ``accum_steps`` > 1): the scattered grads
      accumulate into `state.grad_accum`, which stays resident 1/N
      between optimizer updates — the update fires every accum_steps
      microsteps on the mean, then the buffer resets to zeros.
    - stage >= 3: `state.params` arrive resident 1/N; the step
      constrains them to the rule layout BEFORE the loss (the same
      double-constraint pin, now as a just-in-time all-gather placed
      where XLA can overlap it with early forward compute) and writes
      new params back scattered. Stages 1-2 instead gather new params
      back to the rule layout after the update.

    XLA sees one program and overlaps the resharding collectives with
    compute; on XLA:CPU the partitioner realizes the scatter as
    allreduce+slice, on TPU as a true reduce-scatter.

    ``accum_steps`` composes with every stage (stage 0 accumulates in
    the rule layout): `state.step` counts microsteps, and the loss
    reported each call is the microbatch loss."""
    stage = _resolve_zero_stage(zero_stage, shard_optimizer)
    if stage >= 1 and (mesh is None or rules is None):
        raise ValueError(f"zero_stage={stage} needs mesh= and rules= "
                         "to derive the ZeRO layouts")
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    def _constrain(tree: PyTree, shardings: PyTree) -> PyTree:
        return jax.tree.map(jax.lax.with_sharding_constraint, tree,
                            shardings)

    def _zero(t):
        return _constrain(t, zero1_shardings(rules, t, mesh, data_axis))

    def step(state: TrainState, batch: PyTree):
        if stage >= 3:
            # just-in-time all-gather of the 1/N-resident params,
            # pinned to the rule layout so the forward/backward
            # partitioning matches the replicated program exactly
            params_full = _constrain(state.params,
                                     rules.shardings(state.params, mesh))
        else:
            params_full = state.params
        loss, grads = jax.value_and_grad(loss_fn)(params_full, batch)
        gnorm = optax.global_norm(grads)
        if stage >= 1:
            # full-layout pin, THEN the ZeRO reshard: without the
            # intermediate constraint the sharded consumer back-
            # propagates into the backward GEMMs' partitioning and the
            # grad arithmetic stops matching the replicated step
            grads = _constrain(grads, rules.shardings(grads, mesh))
            grads = _zero(grads)
            params_s = (state.params if stage >= 3
                        else _zero(state.params))
        else:
            params_s = state.params
        if accum_steps > 1:
            # accumulate in the resident layout (1/N for stage >= 2);
            # the update is computed every microstep and selected in on
            # the boundary — shape/sharding-stable, no lax.cond, and
            # with jnp.where the non-boundary cost is the update math
            # on already-materialized shards
            acc = jax.tree.map(jnp.add, state.grad_accum, grads)
            boundary = (state.step + 1) % accum_steps == 0
            mean = jax.tree.map(lambda a: a / accum_steps, acc)
            updates, opt_u = tx.update(mean, state.opt_state, params_s)
            params_u = optax.apply_updates(params_s, updates)

            def sel(a, b):
                return jnp.where(boundary, a, b)

            new_params = jax.tree.map(sel, params_u, params_s)
            new_opt = jax.tree.map(sel, opt_u, state.opt_state)
            new_accum = jax.tree.map(
                lambda a: jnp.where(boundary, jnp.zeros_like(a), a), acc)
        else:
            updates, new_opt = tx.update(grads, state.opt_state, params_s)
            new_params = optax.apply_updates(params_s, updates)
            new_accum = state.grad_accum
        if stage in (1, 2):
            new_params = _constrain(new_params,
                                    rules.shardings(new_params, mesh))
        elif stage >= 3:
            new_params = _zero(new_params)  # stays resident 1/N
        new_state = TrainState(
            params=new_params, opt_state=new_opt, step=state.step + 1,
            grad_accum=new_accum,
        )
        return new_state, {"loss": loss, "grad_norm": gnorm}

    jitted = jax.jit(step, donate_argnums=(0,) if donate else ())

    # Profiling hooks (the Podracer-style breakdown: compile vs. step —
    # a scaling cliff usually shows up first as recompiles or step-time
    # spread). Registry-backed, so worker-process numbers surface on the
    # head's cluster /metrics page tagged by node.
    from ray_tpu.util.metrics import Counter, Histogram

    m_step = Histogram(
        "train_step_seconds",
        "Host-side train-step dispatch time (includes device wait on "
        "synchronous backends)",
        boundaries=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60))
    m_miss = Counter(
        "train_compile_misses_total",
        "Train steps that triggered an XLA compile (new shape/sharding)")
    m_compile = Histogram(
        "train_compile_seconds", "XLA compile time for the train step",
        boundaries=(0.1, 0.5, 1, 5, 10, 30, 60, 120, 300))
    m_phase = Histogram(
        "train_step_phase_seconds",
        "Per-step waterfall phases (data_wait/h2d/compile/collective/"
        "compute) — populated only while step attribution is enabled",
        boundaries=(0.0001, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5,
                    30),
        tag_keys=("phase",))
    m_gather_share = None
    if stage >= 3:
        from ray_tpu.util.metrics import Gauge

        m_gather_share = Gauge(
            "train_zero_gather_share",
            "Fraction of step time spent in host-observed all_gather "
            "collectives while zero_stage >= 3 — the ZeRO-3 "
            "param-gather tax; input of the train-zero-gather-stall "
            "watchtower rule. Populated while step attribution is on.")

    def _attributed_step(state: TrainState, batch: PyTree):
        """Waterfall-mode step: wall-to-wall phase attribution. Adds a
        device sync per step (a profiling run, not a record run)."""
        from ray_tpu.util import tracing
        from ray_tpu.util.collective import _collective_seconds

        data_wait = waterfall.take_data_wait()
        t0 = time.perf_counter()
        gap = waterfall.step_gap(t0, data_wait)
        leaves = jax.tree_util.tree_leaves(batch)
        if any(not isinstance(x, jax.Array) for x in leaves):
            # numpy/host leaves: the h2d copy jit would do implicitly,
            # made explicit so it is timed as its own phase
            batch = jax.block_until_ready(jax.device_put(batch))
        t1 = time.perf_counter()
        coll0 = _collective_seconds().sums_by_tag("op")
        before = tracing.jit_cache_size(jitted)
        # arg layouts, captured pre-call: the census lowering below
        # needs them, and donation invalidates the arrays by then
        args_info = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None)),
            (state, batch))
        out = jitted(state, batch)
        # sync on the metrics dict (small leaves), not the new state:
        # blocking on loss/grad_norm means the whole step has executed
        out = (out[0], jax.block_until_ready(out[1]))
        t3 = time.perf_counter()
        dt = t3 - t1
        compiled = tracing.note_compile_if_grew(
            jitted, before, dt, m_miss, m_compile, "train.compile")
        if compiled:
            # collective op census of the compiled step (attribution
            # runs only — this lowers/compiles a second executable,
            # which is exactly the "profiling run, not record run"
            # trade the waterfall already makes)
            try:
                from ray_tpu.parallel.ops import collective_op_counts

                txt = jitted.lower(*args_info).compile().as_text()
                waterfall.note_program_collectives(
                    collective_op_counts(txt))
            except Exception:  # noqa: BLE001 - census is best-effort
                pass
        coll_now = _collective_seconds().sums_by_tag("op")
        coll_by_op = {op: v - coll0.get(op, 0.0)
                      for op, v in coll_now.items()
                      if v - coll0.get(op, 0.0) > 0.0}
        coll = sum(coll_by_op.values())
        if coll > dt > 0.0:  # clamp: collectives cannot exceed the step
            scale = dt / coll
            coll_by_op = {op: v * scale for op, v in coll_by_op.items()}
            coll = dt
        phases = {"data_wait": data_wait, "h2d": t1 - t0, "host": gap}
        for op, v in coll_by_op.items():
            phases[f"collective.{op}"] = v
        if m_gather_share is not None and dt > 0.0:
            m_gather_share.set(coll_by_op.get("all_gather", 0.0) / dt)
        phases["compile" if compiled else "compute"] = dt - coll
        if not compiled:
            m_step.observe(dt)
        for k, v in phases.items():
            if v > 0.0:
                m_phase.observe(v, tags={"phase": k})
        # laid-out sub-spans: data_wait | h2d | compile-or-compute, at
        # their true monotonic positions (perf_counter IS the monotonic
        # clock on linux; record_interval re-anchors to the epoch)
        if data_wait > 0.0:
            tracing.record_interval("train.step.data_wait",
                                    t0 - data_wait, t0, category="train")
        if t1 - t0 > 0.0:
            tracing.record_interval("train.step.h2d", t0, t1,
                                    category="train")
        tracing.record_interval(
            "train.step.compile" if compiled else "train.step.compute",
            t1, t3, category="train")
        waterfall.add(phases)
        waterfall.mark_step_end(t3)
        return out

    def instrumented(state: TrainState, batch: PyTree):
        if waterfall.enabled:
            return _attributed_step(state, batch)
        from ray_tpu.util import tracing

        before = tracing.jit_cache_size(jitted)
        t0 = time.perf_counter()
        out = jitted(state, batch)
        dt = time.perf_counter() - t0
        if not tracing.note_compile_if_grew(jitted, before, dt, m_miss,
                                            m_compile, "train.compile"):
            m_step.observe(dt)
        return out

    instrumented.jitted = jitted  # AOT access (lower/compile) if needed
    return instrumented


def init_sharded_state(
    init_fn: Callable[[], PyTree],
    tx: optax.GradientTransformation,
    mesh: Mesh,
    rules: PartitionRules,
    shard_optimizer: bool = False,
    data_axis: str = AXIS_DATA,
    zero_stage: int | None = None,
    accum_steps: int = 1,
) -> TrainState:
    """Initialize a TrainState directly into its sharded layout: the init
    is jitted with out_shardings so every shard is materialized on its
    owning device — no host-memory full copy (crucial for models larger
    than one chip's HBM). ``zero_stage`` (or the legacy
    ``shard_optimizer=True`` == stage 1) materializes each ladder
    component in its 1/N layout from the start — optimizer state
    (stage >= 1), the grad-accumulation buffer when ``accum_steps > 1``
    (stage >= 2), resident params (stage >= 3) — and reports the
    per-chip bytes on the `train_optimizer_state_bytes` /
    `train_grad_state_bytes` / `train_param_state_bytes` gauges."""
    stage = _resolve_zero_stage(zero_stage, shard_optimizer)

    def make():
        params = init_fn()
        return TrainState.create(params, tx, grad_accum=accum_steps > 1)

    abstract = jax.eval_shape(make)
    shardings = state_shardings(rules, abstract, mesh,
                                data_axis=data_axis, zero_stage=stage)
    with mesh:
        state = jax.jit(make, out_shardings=shardings)()
    _optimizer_bytes_gauge().set(
        float(optimizer_state_bytes(state.opt_state)),
        tags={"layout": "zero1" if stage >= 1 else "replicated"})
    _param_state_bytes_gauge().set(
        float(optimizer_state_bytes(state.params)),
        tags={"layout": "zero3" if stage >= 3 else "replicated"})
    if state.grad_accum is not None:
        _grad_state_bytes_gauge().set(
            float(optimizer_state_bytes(state.grad_accum)),
            tags={"layout": "zero2" if stage >= 2 else "replicated"})
    return state
