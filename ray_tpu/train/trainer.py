"""JaxTrainer — distributed data/model-parallel training driver.

Reference parity: TorchTrainer/DataParallelTrainer + BackendExecutor
(train/torch/torch_trainer.py:11, train/data_parallel_trainer.py:25,
train/_internal/backend_executor.py:69,142,458) with the v2 controller's
failure handling (train/v2/_internal/execution/controller.py:73) — no
Tune coupling in the fit path (the v2 design).

Flow: fit() creates a WorkerGroup of actors gang-placed in a PG, wires
rank/world env + the jax.distributed rendezvous (rank 0 hosts the
coordinator), starts the user train loop on every worker, then drives
the result loop — registering reported checkpoints (top-k) and
restarting the whole gang from the latest checkpoint on worker failure.
Gang-level restart is deliberate: one SPMD program spans all hosts, so a
single lost process invalidates the whole world (SURVEY.md §7 hard
parts) — elasticity is at gang granularity, unlike per-worker NCCL
rebuilds."""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable

import cloudpickle

from ray_tpu.train.checkpoint import (
    Checkpoint,
    CheckpointConfig,
    CheckpointManager,
)
from ray_tpu.train.worker_group import WorkerGroup, WorkerGroupError


@dataclasses.dataclass
class ScalingConfig:
    """Reference: ray.train.ScalingConfig (air/config.py). num_workers is
    the number of jax PROCESSES (one per host on TPU), not chips. Setting
    min_workers turns on ELASTIC sizing (reference: Train v2
    ScalingPolicy, v2/_internal/execution/scaling_policy/scaling_policy.py:26):
    each gang (re)start sizes the world to what the cluster can place,
    within [min_workers, num_workers]."""

    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: dict[str, float] | None = None
    placement_strategy: str = "PACK"
    # jax-on-CPU workers: how many virtual devices each process exposes
    # (tests / laptops; None on real TPU workers)
    num_cpu_devices_per_worker: int | None = None
    min_workers: int | None = None  # elastic floor (None = fixed size)
    # mid-run elastic: how often the result loop re-evaluates the
    # scaling decision against live capacity (reference: Train v2's
    # continuous ScalingPolicy, scaling_policy.py:26). 0 disables —
    # sizing then happens only at gang (re)starts.
    elastic_interval_s: float = 0.0

    def decide_num_workers(self) -> int:
        """Elastic sizing decision against the live resource view."""
        if self.min_workers is None:
            return self.num_workers
        import ray_tpu

        avail = ray_tpu.available_resources()
        req = self.worker_resources()
        fit = self.num_workers
        for r, q in req.items():
            if q > 0:
                # epsilon guards float residue from fractional releases
                fit = min(fit, int((avail.get(r, 0.0) + 1e-9) // q))
        return max(self.min_workers, min(self.num_workers, fit))

    def extra_capacity(self) -> int:
        """How many MORE workers the cluster could place right now (the
        running gang's own resources are already subtracted from the
        availability view)."""
        import ray_tpu

        avail = ray_tpu.available_resources()
        fit = 1 << 30
        for r, q in self.worker_resources().items():
            if q > 0:
                fit = min(fit, int((avail.get(r, 0.0) + 1e-9) // q))
        return max(0, fit)

    def worker_resources(self) -> dict[str, float]:
        if self.resources_per_worker is not None:
            return dict(self.resources_per_worker)
        return {"CPU": 1.0, "TPU": 1.0} if self.use_tpu else {"CPU": 1.0}


@dataclasses.dataclass
class FailureConfig:
    """Reference: ray.train.FailureConfig — max_failures gang restarts."""

    max_failures: int = 0


@dataclasses.dataclass
class RunConfig:
    """Reference: ray.train.RunConfig (air/config.py)."""

    name: str | None = None
    storage_path: str | None = None
    failure_config: FailureConfig | None = None
    checkpoint_config: CheckpointConfig | None = None
    # Tune stop criteria: {"metric": threshold} — a trial terminates when
    # any named metric reaches its threshold (reference: air/config.py
    # RunConfig.stop)
    stop: dict | None = None


@dataclasses.dataclass
class Result:
    """Reference: ray.train.Result."""

    metrics: dict
    checkpoint: Checkpoint | None
    path: str
    error: BaseException | None = None
    metrics_history: list = dataclasses.field(default_factory=list)


class TrainingFailedError(RuntimeError):
    pass


class JaxTrainer:
    """Run `train_loop_per_worker` on a gang of workers.

    The loop uses the session API (ray_tpu.train.report /
    get_context / get_checkpoint); inside it, build a mesh over
    jax.devices() — jax.distributed is already initialized across the
    gang by the time the loop runs."""

    def __init__(
        self,
        train_loop_per_worker: Callable | None = None,
        *,
        train_loop_config: dict | None = None,
        scaling_config: ScalingConfig | None = None,
        run_config: RunConfig | None = None,
        resume_from_checkpoint: Checkpoint | None = None,
        datasets: dict | None = None,
        strategy: str = "spmd",
    ):
        if strategy not in ("spmd", "pipeline"):
            raise ValueError(f"unknown train strategy {strategy!r} "
                             "(spmd | pipeline)")
        if strategy == "spmd" and train_loop_per_worker is None:
            raise ValueError("spmd strategy needs train_loop_per_worker")
        self._fn = train_loop_per_worker
        self._config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self._resume = resume_from_checkpoint
        self.strategy = strategy
        # name -> ray_tpu.data.Dataset, split across the gang at start
        # (reference: DataParallelTrainer datasets= + get_dataset_shard)
        self._datasets = datasets or {}

    # ------------------------------------------------------------------

    def fit(self) -> Result:
        if self.strategy == "pipeline":
            return self._fit_pipeline()
        return self._fit_spmd()

    def _fit_pipeline(self) -> Result:
        """Pipeline-parallel fit: stages on worker subsets, the 1F1B
        schedule per step (train/pipeline_strategy.py). Config keys in
        train_loop_config: `model` (PipelinedConfig kwargs), `batch`
        ({tokens, targets} numpy), `steps`, `num_stages` (default:
        scaling_config.num_workers), `num_microbatches`, `lr`, `seed`,
        plus the interleaved/ZeRO composition knobs `num_repeats`,
        `zero_stage`, `data_parallel`, `momentum`. Stage workers
        checkpoint their param shards through the CheckpointManager
        every `checkpoint_frequency` steps (the manager reassembles a
        restore-compatible full state via
        `load_pipeline_checkpoint`)."""
        from ray_tpu.train.pipeline_strategy import PipelineStrategy

        cfg = dict(self._config or {})
        if "batch" not in cfg:
            raise ValueError("pipeline strategy needs "
                             "train_loop_config['batch']")
        name = self.run_config.name or f"pipeline_{int(time.time())}"
        storage = self.run_config.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results")
        exp_dir = os.path.join(storage, name)
        os.makedirs(exp_dir, exist_ok=True)
        ckpt_cfg = (self.run_config.checkpoint_config
                    or CheckpointConfig())
        manager = CheckpointManager(exp_dir, ckpt_cfg)
        sc = self.scaling_config
        ps = PipelineStrategy(
            cfg.get("model") or {},
            num_stages=cfg.get("num_stages", sc.num_workers),
            num_microbatches=cfg.get("num_microbatches"),
            lr=cfg.get("lr", 1e-2),
            seed=cfg.get("seed", 0),
            resources_per_worker=sc.resources_per_worker,
            placement_strategy=sc.placement_strategy,
            num_repeats=int(cfg.get("num_repeats", 1)),
            zero_stage=int(cfg.get("zero_stage", 0)),
            data_parallel=int(cfg.get("data_parallel", 1)),
            momentum=float(cfg.get("momentum", 0.0)),
        )
        from ray_tpu import dashboard as _dash

        history: list[dict] = []
        last_ckpt: Checkpoint | None = None
        try:
            steps = int(cfg.get("steps", 1))
            freq = max(1, int(ckpt_cfg.checkpoint_frequency or 1))
            for step in range(steps):
                metrics = ps.train_step(cfg["batch"])
                metrics["step"] = step
                history.append(metrics)
                if (step + 1) % freq == 0 or step == steps - 1:
                    staged = ps.save_checkpoint(
                        os.path.join(exp_dir, f"staging_{step:06d}"))
                    last_ckpt = manager.register(staged, metrics)
                _dash.publish_view("train", name, {
                    "status": "RUNNING", "iteration": len(history),
                    "num_workers": ps.num_stages, "metrics": metrics})
            _dash.publish_view("train", name, {
                "status": "FINISHED", "iteration": len(history),
                "num_workers": ps.num_stages,
                "metrics": history[-1] if history else {}})
        except BaseException as e:
            # terminal-status contract matches the spmd path: a dead
            # view must not read RUNNING forever
            _dash.publish_view("train", name, {
                "status": "FAILED", "iteration": len(history),
                "error": str(e)})
            raise
        finally:
            ps.shutdown()
        return Result(metrics=history[-1] if history else {},
                      checkpoint=last_ckpt, path=exp_dir,
                      metrics_history=history)

    def _fit_spmd(self) -> Result:
        name = self.run_config.name or f"jax_trainer_{int(time.time())}"
        storage = self.run_config.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results")
        exp_dir = os.path.join(storage, name)
        os.makedirs(exp_dir, exist_ok=True)
        manager = CheckpointManager(
            exp_dir, self.run_config.checkpoint_config or CheckpointConfig())
        failure_config = self.run_config.failure_config or FailureConfig()

        resume = self._resume or manager.latest()
        resize_to = None
        failures = 0
        history: list[dict] = []
        last_error: BaseException | None = None
        from ray_tpu import dashboard as _dash

        _dash.publish_view("train", name, {
            "status": "RUNNING", "iteration": 0,
            "num_workers": self.scaling_config.num_workers})
        while True:
            wg = None
            try:
                target, resize_to = resize_to, None  # one-shot: a FAILED
                # resized start must not retry the stale target forever
                wg = self._start_worker_group(name, exp_dir, resume, target)
                metrics, ckpt = self._result_loop(wg, manager, history,
                                                  run_name=name)
                _dash.publish_view("train", name, {
                    "status": "FINISHED", "iteration": len(history),
                    "num_workers": wg.num_workers, "metrics": metrics})
                return Result(metrics=metrics, checkpoint=ckpt or
                              manager.latest(), path=exp_dir,
                              metrics_history=history)
            except _ElasticResize as e:
                # mid-run scaling decision: controlled gang restart from
                # the latest checkpoint at a result boundary (does not
                # consume the failure budget — reference: Train v2
                # ScalingPolicy resize decisions, scaling_policy.py:26).
                # The TARGET rides along: the availability view right
                # after shutdown is stale (old workers still releasing),
                # so re-deciding from it would undo the resize.
                resume = manager.latest()
                resize_to = e.target
            except (WorkerGroupError, _WorkerFailure) as e:
                last_error = e
                failures += 1
                if failures > failure_config.max_failures:
                    _dash.publish_view("train", name, {
                        "status": "FAILED", "iteration": len(history),
                        "error": str(e)})
                    raise TrainingFailedError(
                        f"training failed after {failures - 1} restarts: {e}"
                    ) from e
                resume = manager.latest()  # gang restart from latest ckpt
            finally:
                if wg is not None:
                    wg.shutdown()

    # ------------------------------------------------------------------

    def _start_worker_group(self, name: str, exp_dir: str,
                            resume: Checkpoint | None,
                            num_override: int | None = None) -> WorkerGroup:
        sc = self.scaling_config
        n_workers = num_override or sc.decide_num_workers()
        wg = WorkerGroup(
            num_workers=n_workers,
            resources_per_worker=sc.worker_resources(),
            placement_strategy=sc.placement_strategy,
        )
        try:
            infos = wg.execute("node_info")
            coordinator = None
            if wg.num_workers > 1:
                coordinator = f"{infos[0]['ip']}:{infos[0]['port']}"
            # rank/world env (reference: _create_rank_world_size_mappings,
            # backend_executor.py:376) + local ranks grouped by node
            by_node: dict[str, list[int]] = {}
            for rank, info in enumerate(infos):
                by_node.setdefault(info["node_id"], []).append(rank)
            node_order = list(by_node)
            node_ips = []
            _seen_nodes = set()
            for i in infos:
                if i["node_id"] not in _seen_nodes:
                    _seen_nodes.add(i["node_id"])
                    node_ips.append(i["ip"])
            # slice-identity view: node labels + per-node IPs, for the
            # slice-derived topology env (reference: backend_executor.py
            # :306-322 shares the slice view across colocated workers)
            node_labels: dict[str, dict] = {}
            node_ip_by_id: dict[str, str] = {}
            if sc.use_tpu:
                import ray_tpu as _rt

                try:
                    for n in _rt.nodes():
                        node_labels[n["NodeID"]] = n.get("Labels") or {}
                except Exception:  # local mode: no cluster view
                    pass
                for i in infos:
                    node_ip_by_id.setdefault(i["node_id"], i["ip"])
            env_refs = []
            for rank, info in enumerate(infos):
                node_id = info["node_id"]
                env = {
                    "RAY_TPU_TRAIN_RANK": rank,
                    "RAY_TPU_TRAIN_WORLD_SIZE": wg.num_workers,
                    "RAY_TPU_TRAIN_LOCAL_RANK": by_node[node_id].index(rank),
                    "RAY_TPU_TRAIN_NODE_RANK": node_order.index(node_id),
                }
                if sc.use_tpu:
                    # libtpu multi-host topology env (reference:
                    # TPUAcceleratorManager worker-id/hostnames wiring,
                    # _private/accelerators/tpu.py:157-170). Per HOST,
                    # not per worker: multiple train workers can share a
                    # TPU host. When the node carries slice labels, the
                    # worker id / hostnames come from SLICE identity
                    # (worker-id order), not gang join order.
                    from ray_tpu.core import tpu as tpu_mod

                    labels = node_labels.get(node_id, {})
                    env.update(self._slice_topology_env(
                        tpu_mod, labels, node_id, node_labels, node_ip_by_id,
                        fallback_id=node_order.index(node_id),
                        fallback_ips=node_ips))
                if coordinator:
                    env["RAY_TPU_TRAIN_COORDINATOR"] = coordinator
                env_refs.append((rank, env))
            for rank, env in env_refs:
                wg.execute_single(rank, "setup_env", env)
            # jax.distributed rendezvous: all workers join concurrently
            # (initialize blocks until the world is complete)
            import ray_tpu

            refs = [
                getattr(w, "setup_jax").remote(
                    coordinator, wg.num_workers, rank,
                    sc.num_cpu_devices_per_worker)
                for rank, w in enumerate(wg.workers)
            ]
            device_counts = ray_tpu.get(refs, timeout=180)
            fn_blob = cloudpickle.dumps(self._fn)
            for rank, info in enumerate(infos):
                node_id = info["node_id"]
                ctx = dict(
                    world_size=wg.num_workers,
                    world_rank=rank,
                    local_rank=by_node[node_id].index(rank),
                    local_world_size=len(by_node[node_id]),
                    node_rank=node_order.index(node_id),
                    experiment_name=name,
                    trial_dir=exp_dir,
                    coordinator_address=coordinator,
                )
                shards_blob = None
                if self._datasets:
                    shards_blob = cloudpickle.dumps({
                        dname: ds.shard(wg.num_workers, rank)
                        for dname, ds in self._datasets.items()})
                wg.execute_single(
                    rank, "start_training", fn_blob, self._config, ctx,
                    resume.path if resume else None, shards_blob)
            del device_counts
            return wg
        except Exception as e:
            wg.shutdown()
            if isinstance(e, WorkerGroupError):
                raise
            raise WorkerGroupError(f"worker group bootstrap failed: {e}") \
                from e

    # ------------------------------------------------------------------

    @staticmethod
    def _slice_topology_env(tpu_mod, labels, node_id, node_labels,
                            node_ip_by_id, fallback_id, fallback_ips):
        """TPU topology env for one worker. Slice-labelled nodes get their
        asserted TPU_WORKER_ID and hostnames ordered by worker-id across
        the gang's members of the same slice; unlabelled clusters fall
        back to gang join order (single-slice assumption)."""
        sl = labels.get(tpu_mod.SLICE_LABEL)
        if sl is None or labels.get(tpu_mod.WORKER_ID_LABEL) is None:
            return {"TPU_WORKER_ID": fallback_id,
                    "TPU_WORKER_HOSTNAMES": ",".join(fallback_ips)}
        members = sorted(
            ((int(lb[tpu_mod.WORKER_ID_LABEL]), nid)
             for nid, lb in node_labels.items()
             if lb.get(tpu_mod.SLICE_LABEL) == sl
             and lb.get(tpu_mod.WORKER_ID_LABEL) is not None
             and nid in node_ip_by_id))
        slice_ips = [node_ip_by_id[nid] for _, nid in members]
        # libtpu requires worker ids to index the hostname list 0..n-1.
        # A gang covering the FULL slice keeps the asserted ids; a gang on
        # a subset of hosts is reindexed by position (self-consistent
        # contiguous view of the sub-slice).
        position = next((i for i, (_, nid) in enumerate(members)
                         if nid == node_id), 0)
        return tpu_mod.topology_env(labels, slice_ips, worker_id=position)

    def _result_loop(self, wg: WorkerGroup, manager: CheckpointManager,
                     history: list, run_name: str = ""
                     ) -> tuple[dict, Checkpoint | None]:
        """Drive rounds of per-worker reports until every worker finishes
        (reference: backend_executor.get_next_results — all workers must
        report in lockstep)."""
        from ray_tpu.core import exceptions as exc

        last_metrics: dict = {}
        last_ckpt: Checkpoint | None = None
        finished: set[int] = set()
        sc = self.scaling_config
        next_elastic_check = (time.monotonic() + sc.elastic_interval_s
                              if sc.elastic_interval_s > 0 else None)
        while len(finished) < wg.num_workers:
            if next_elastic_check is not None and \
                    time.monotonic() >= next_elastic_check:
                next_elastic_check = time.monotonic() + sc.elastic_interval_s
                want = min(sc.num_workers,
                           wg.num_workers + sc.extra_capacity())
                if want > wg.num_workers and last_ckpt is not None:
                    # capacity appeared: grow the gang at a checkpointed
                    # boundary (shrink happens via the failure path when
                    # a worker is lost)
                    raise _ElasticResize(wg.num_workers, want)
            round_reports: dict[int, dict] = {}
            for rank in range(wg.num_workers):
                if rank in finished:
                    continue
                deadline = time.monotonic() + 300
                while True:
                    try:
                        r = wg.execute_single(rank, "next_result",
                                              timeout=30.0)
                    except exc.GetTimeoutError:
                        # slow (e.g. long XLA compile under load), not
                        # dead — keep polling until the round deadline
                        if time.monotonic() > deadline:
                            raise _WorkerFailure(
                                f"train worker {rank} unresponsive for "
                                f"300s", rank) from None
                        continue
                    except (exc.ActorDiedError, exc.ActorUnavailableError,
                            exc.TaskError) as e:
                        raise _WorkerFailure(
                            f"train worker {rank} died: {e}", rank) from e
                    if r["status"] == "report":
                        round_reports[rank] = r
                        break
                    if r["status"] == "finished":
                        finished.add(rank)
                        break
                    if r["status"] == "error":
                        raise _WorkerFailure(
                            f"train loop failed on rank {rank}: "
                            f"{r['error']}\n{r.get('traceback', '')}", rank)
                    if time.monotonic() > deadline:
                        raise _WorkerFailure(
                            f"train worker {rank} produced no result in "
                            f"300s", rank)
            if round_reports:
                rank0 = round_reports.get(0)
                if rank0 is not None:
                    last_metrics = rank0["metrics"]
                    history.append(dict(last_metrics))
                    if rank0.get("checkpoint_dir"):
                        last_ckpt = manager.register(
                            Checkpoint(rank0["checkpoint_dir"]),
                            last_metrics)
                    if run_name:
                        from ray_tpu import dashboard as _dash

                        _dash.publish_view("train", run_name, {
                            "status": "RUNNING",
                            "iteration": len(history),
                            "num_workers": wg.num_workers,
                            "metrics": last_metrics})
        return last_metrics, last_ckpt


class _WorkerFailure(RuntimeError):
    def __init__(self, msg, rank):
        super().__init__(msg)
        self.rank = rank


class _ElasticResize(Exception):
    def __init__(self, current: int, target: int):
        super().__init__(f"elastic resize {current} -> {target}")
        self.current = current
        self.target = target
