"""WorkerGroup — the gang of train-worker actors.

Reference parity: ray.train._internal.worker_group.WorkerGroup
(worker_group.py:102) + the actor-side _RayTrainWorker. Workers are
actors placed in one placement group (gang semantics: all-or-nothing,
strategy-shaped — backend_executor.py:142); each runs the user train
function on a dedicated thread with a TrainSession and serves
result-polling calls.

TPU-first: one worker per HOST (a worker owns every chip the nodelet
granted it), not one per device — a pod slice runs ONE SPMD program
(SURVEY.md §7), so world_size == number of jax processes.
"""

from __future__ import annotations

import os
import socket
import threading
import traceback
from typing import Any

import cloudpickle


class TrainWorker:
    """Actor hosted in a worker process. One per train rank."""

    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self.session = None

    # -- rendezvous ------------------------------------------------------

    def node_info(self) -> dict:
        """IP + a free port (rank 0's becomes the jax.distributed
        coordinator — reference rendezvous: train/torch/config.py:156 via
        get_address_and_port)."""
        from ray_tpu.core.rpc import node_ip

        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        import ray_tpu

        return {"ip": node_ip(), "port": port,
                "node_id": ray_tpu.get_runtime_context().node_id.hex()}

    def setup_env(self, env: dict) -> bool:
        os.environ.update({k: str(v) for k, v in env.items()})
        return True

    def setup_jax(self, coordinator: str | None, num_processes: int,
                  process_id: int, num_cpu_devices: int | None) -> int:
        """Configure jax in this process and join the distributed system
        (reference seam: Backend.on_start — _TorchBackend runs
        dist.init_process_group here, train/torch/config.py:66-124; the
        jax-native equivalent is jax.distributed.initialize with rank-0's
        address)."""
        import jax

        if num_cpu_devices:
            # strip any inherited --xla_force_host_platform_device_count
            # (e.g. from a test driver): it would override
            # jax_num_cpu_devices where that option exists, and fight
            # the value we append for jax<0.5. The backend initializes
            # lazily at the jax.devices() call below, so editing
            # XLA_FLAGS after the import is still in time.
            flags = os.environ.get("XLA_FLAGS", "")
            kept = [f for f in flags.split() if
                    "--xla_force_host_platform_device_count" not in f]
            if hasattr(jax.config, "jax_num_cpu_devices"):
                jax.config.update("jax_num_cpu_devices",
                                  int(num_cpu_devices))
            else:
                # jax<0.5: the XLA flag IS the device-count mechanism
                kept.append("--xla_force_host_platform_device_count="
                            f"{int(num_cpu_devices)}")
            os.environ["XLA_FLAGS"] = " ".join(kept)
            jax.config.update("jax_platforms", "cpu")
        if coordinator and num_processes > 1:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes,
                process_id=process_id,
            )
        return len(jax.devices())

    # -- training --------------------------------------------------------

    def start_training(self, fn_blob: bytes, train_loop_config: dict | None,
                       ctx: dict, resume_dir: str | None,
                       dataset_shards_blob: bytes | None = None) -> bool:
        from ray_tpu.train import session as S
        from ray_tpu.train.checkpoint import Checkpoint

        fn = cloudpickle.loads(fn_blob)
        shards = (cloudpickle.loads(dataset_shards_blob)
                  if dataset_shards_blob else None)
        context = S.TrainContext(**ctx)
        resume = Checkpoint(resume_dir) if resume_dir else None
        self.session = S.init_session(context, resume, shards)

        def run():
            try:
                if train_loop_config is not None:
                    result = fn(train_loop_config)
                else:
                    result = fn()
                self.session.final = result
            except BaseException as e:  # noqa: BLE001
                self.session.error = e
                self.session.error_tb = traceback.format_exc()
            finally:
                self.session.finished.set()

        threading.Thread(target=run, daemon=True,
                         name=f"train-fn-rank{self.rank}").start()
        return True

    def set_step_waterfall(self, on: bool = True) -> bool:
        """Flip per-step latency attribution in this worker process
        (train/spmd.py waterfall) — works after spmd is imported, unlike
        the RAY_TPU_STEP_WATERFALL env var which is read at import."""
        os.environ["RAY_TPU_STEP_WATERFALL"] = "1" if on else ""
        from ray_tpu.train import spmd

        spmd.enable_step_waterfall(on)
        return True

    def step_waterfall_summary(self) -> dict:
        """This rank's accumulated per-step phase attribution."""
        from ray_tpu.train import spmd

        return spmd.waterfall.summary()

    def next_result(self, timeout: float = 5.0) -> dict:
        """One report from this worker's session, or a status sentinel.
        Driven by the driver's result loop (reference:
        backend_executor.get_next_results :585)."""
        s = self.session
        if s is None:
            return {"status": "idle"}
        r = s.next_result(timeout=timeout)
        if r is not None:
            return {"status": "report", **r}
        if s.finished.is_set():
            if s.error is not None:
                return {"status": "error", "error": repr(s.error),
                        "traceback": getattr(s, "error_tb", "")}
            return {"status": "finished", "final": _safe(s.final)}
        return {"status": "running"}

    def ping(self) -> str:
        return "pong"


def _safe(v):
    try:
        cloudpickle.dumps(v)
        return v
    except Exception:  # noqa: BLE001
        return repr(v)


class WorkerGroupError(RuntimeError):
    def __init__(self, msg, rank=None):
        super().__init__(msg)
        self.rank = rank


class WorkerGroup:
    """N worker actors in one placement group.

    The default worker class is `TrainWorker` (SPMD gangs); strategies
    that need a different actor shape pass `worker_cls` — any class
    whose __init__ is (rank, world_size). The pipeline strategy
    (train/pipeline_strategy.py) runs its stage workers FIFO
    (`max_concurrency=1`) so the driver's 1F1B submission order is the
    per-stage execution order."""

    def __init__(self, num_workers: int,
                 resources_per_worker: dict[str, float] | None = None,
                 placement_strategy: str = "PACK",
                 pg_timeout: float = 60.0,
                 worker_cls: type | None = None,
                 max_concurrency: int = 2):
        import ray_tpu
        from ray_tpu.util.placement_group import (
            placement_group,
            remove_placement_group,
        )

        self.num_workers = num_workers
        res = dict(resources_per_worker or {"CPU": 1.0})
        self._remove_pg = remove_placement_group
        self.pg = placement_group([dict(res) for _ in range(num_workers)],
                                  strategy=placement_strategy)
        if not self.pg.wait(pg_timeout):
            self._remove_pg(self.pg)
            raise WorkerGroupError(
                f"placement group for {num_workers} x {res} not placeable "
                f"within {pg_timeout}s")
        cls = ray_tpu.remote(num_cpus=0)(worker_cls or TrainWorker)
        self.workers = [
            cls.options(
                placement_group=self.pg,
                placement_group_bundle_index=i,
                # default 2: next_result poll + control calls
                max_concurrency=max_concurrency,
            ).remote(i, num_workers)
            for i in range(num_workers)
        ]

    def execute(self, method: str, *args, timeout: float | None = 120.0,
                **kwargs) -> list:
        import ray_tpu
        from ray_tpu.util import tracing

        # one span per gang call: every rank's actor-side span carries a
        # child of this context, so the merged timeline shows the whole
        # gang under one trace_id (straggler ranks stick out)
        with tracing.span(f"worker_group.{method}", category="train"):
            refs = [getattr(w, method).remote(*args, **kwargs)
                    for w in self.workers]
            return ray_tpu.get(refs, timeout=timeout)

    def execute_single(self, rank: int, method: str, *args,
                       timeout: float | None = 120.0, **kwargs) -> Any:
        import ray_tpu
        from ray_tpu.util import tracing

        with tracing.span(f"worker_group.{method}[{rank}]",
                          category="train"):
            ref = getattr(self.workers[rank], method).remote(*args,
                                                             **kwargs)
            return ray_tpu.get(ref, timeout=timeout)

    def enable_step_waterfall(self, on: bool = True) -> list:
        """Flip per-step attribution on EVERY rank; fetch the per-rank
        phase tables afterwards with
        ``execute("step_waterfall_summary")`` (straggler ranks show up
        as one rank's compute/collective share diverging)."""
        return self.execute("set_step_waterfall", on)

    def execute_async(self, method: str, *args, **kwargs) -> list:
        from ray_tpu.util import tracing

        with tracing.span(f"worker_group.{method}.submit",
                          category="train"):
            return [getattr(w, method).remote(*args, **kwargs)
                    for w in self.workers]

    def shutdown(self):
        import ray_tpu

        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass
        try:
            self._remove_pg(self.pg)
        except Exception:  # noqa: BLE001
            pass
        self.workers = []
