"""In-program (SPMD) collectives over mesh axes.

This is the data-plane replacement for the reference's NCCL groups
(util/collective/collective_group/nccl_collective_group.py) and the
compiled-DAG channel collectives (experimental/channel/nccl_group.py):
inside a pjit/shard_map program, XLA lowers these to ICI collectives on
TPU — no process-level machinery at all. Use the host-side
ray_tpu.util.collective only for out-of-band CPU metadata.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ray_tpu.parallel.mesh import AXIS_DATA


# named_scope wrappers: collectives are in-trace (XLA lowers them), so
# they cannot be wall-timed from the host — the scope name is what lets
# the XLA/TPU profiler attribute collective time inside a step (the
# Podracer-style compile/collective/step breakdown; see OBSERVABILITY.md)


def psum(x, axis_name: str | tuple = AXIS_DATA):
    with jax.named_scope("rt.psum"):
        return jax.lax.psum(x, axis_name)


def pmean(x, axis_name: str | tuple = AXIS_DATA):
    with jax.named_scope("rt.pmean"):
        return jax.lax.pmean(x, axis_name)


def pmax(x, axis_name: str | tuple = AXIS_DATA):
    with jax.named_scope("rt.pmax"):
        return jax.lax.pmax(x, axis_name)


def all_gather(x, axis_name: str, *, axis: int = 0, tiled: bool = True):
    with jax.named_scope("rt.all_gather"):
        return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, *, scatter_dimension: int = 0):
    with jax.named_scope("rt.reduce_scatter"):
        return jax.lax.psum_scatter(x, axis_name,
                                    scatter_dimension=scatter_dimension,
                                    tiled=True)


def all_to_all(x, axis_name: str, *, split_axis: int, concat_axis: int):
    return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def ppermute(x, axis_name: str, perm):
    return jax.lax.ppermute(x, axis_name, perm)


def ring_shift(x, axis_name: str, shift: int = 1):
    """Shift values around the axis ring (building block of ring
    attention / pipelined collectives)."""
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str):
    return jax.lax.axis_index(axis_name)


def axis_size(axis_name: str):
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    # jax<0.5: psum of a unit weight is folded to the static axis size
    return jax.lax.psum(1, axis_name)


_HLO_COLLECTIVES = {
    "all-reduce": "allreduce",
    "all-gather": "all_gather",
    "reduce-scatter": "reduce_scatter",
    "collective-permute": "collective_permute",
    "all-to-all": "all_to_all",
}


def collective_op_counts(hlo_text: str) -> dict[str, int]:
    """Count the collective ops in a compiled HLO module, keyed by the
    catalog's `op=` label names (allreduce/all_gather/reduce_scatter/
    collective_permute/all_to_all).

    This is the structural face of collective attribution: in-program
    collectives cannot be wall-timed from the host (XLA fuses and
    overlaps them), but the compiled program says exactly which ones a
    step pays for — e.g. a ZeRO-1 step trades the grad allreduce for
    reduce-scatter + param all-gather (on XLA:CPU the partitioner keeps
    allreduce + slice and the param all-gathers appear; on TPU it forms
    true reduce-scatter). Async pairs (`*-start`/`*-done`) count once.
    """
    import re

    out: dict[str, int] = {}
    for hlo_name, label in _HLO_COLLECTIVES.items():
        n = len(re.findall(rf"{hlo_name}(?:-start)?\(", hlo_text))
        if n:
            out[label] = n
    return out


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
    """`jax.shard_map` with varying-manual-axes checking off by default:
    collective-heavy SPMD bodies (all_gather outputs, ring schedules)
    routinely produce values that are replicated at runtime but not
    statically inferable, and jax>=0.8 rejects those under check_vma.

    Older jax (<0.5) only ships the experimental entry point, where the
    same knob is spelled check_rep."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)
