"""Pipeline parallelism — microbatch schedules over the `pipe` mesh
axis (in-program) and over worker groups (MPMD, 1F1B).

SURVEY.md §7.8: PP is a first-class capability (the reference schedules
frameworks that implement it; here it is native). TPU-native design:

- stage parameters are stacked on a leading stage axis sharded over
  `pipe` (one stage's weights per device group);
- runs inside shard_map over the pipe axis: every device executes the
  SAME program (XLA-friendly: no per-stage control flow); at schedule
  tick t it applies its stage to the activation it holds, then the
  activations rotate one hop with ppermute — stage i naturally works on
  microbatch (t - i), the classic GPipe staircase with (S-1) bubble
  ticks on each side;
- microbatch m enters at stage 0 on tick m and exits stage S-1 on tick
  m + S - 1; outputs are collected by masked accumulation, so the whole
  schedule is one lax.scan (differentiable, no dynamic shapes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.parallel.ops import axis_size as _axis_size


# ---------------------------------------------------------------------------
# 1F1B (MPMD) schedule — the worker-group strategy's timetable
# ---------------------------------------------------------------------------
#
# The in-program schedules below run every stage on every device inside
# one SPMD program. The MPMD alternative ("Scaling Deep Learning
# Training with MPMD Pipeline Parallelism") gives each STAGE its own
# worker process and streams activations between them; the classic
# one-forward-one-backward (1F1B) order keeps at most (S - s) live
# activations on stage s while reaching the same (S-1)/(S-1+M) bubble
# as GPipe. These helpers are pure schedule math — data, not lax — so
# the driver (train/pipeline_strategy.py) can submit actor calls in
# exactly this order and a unit test can pin the interleave.


def one_f_one_b_schedule(num_stages: int, num_microbatches: int
                         ) -> list[list[tuple[str, int]]]:
    """Per-stage 1F1B op order: result[s] is the exact sequence of
    ("fwd"|"bwd", microbatch) ops stage s executes. Stage s warms up
    with min(M, S-1-s) forwards, alternates fwd/bwd through the steady
    state, then drains the remaining backwards — the Megatron
    schedules.py order, as a list."""
    S, M = num_stages, num_microbatches
    if S < 1 or M < 1:
        raise ValueError(f"need stages >= 1 and microbatches >= 1, "
                         f"got {S}, {M}")
    sched: list[list[tuple[str, int]]] = []
    for s in range(S):
        warm = min(M, S - 1 - s)
        ops = [("fwd", m) for m in range(warm)]
        for i in range(M - warm):
            ops.append(("fwd", warm + i))
            ops.append(("bwd", i))
        for m in range(M - warm, M):
            ops.append(("bwd", m))
        sched.append(ops)
    return sched


def one_f_one_b_submission_order(num_stages: int, num_microbatches: int
                                 ) -> list[tuple[str, int, int]]:
    """Global topological submission order for the 1F1B schedule:
    (kind, stage, microbatch) triples such that every op appears after
    its dependencies — fwd(s,m) after fwd(s-1,m); bwd(s,m) after
    fwd(s,m) and bwd(s+1,m) — while each stage's own ops appear in its
    `one_f_one_b_schedule` order. A driver submitting actor calls in
    this order can wire every call's inputs to already-created object
    refs, and per-actor FIFO execution then IS the 1F1B interleave."""
    S, M = num_stages, num_microbatches
    per_stage = one_f_one_b_schedule(S, M)
    ptr = [0] * S
    emitted: set[tuple[str, int, int]] = set()
    order: list[tuple[str, int, int]] = []
    remaining = sum(len(ops) for ops in per_stage)
    while len(order) < remaining:
        progressed = False
        for s in range(S):
            while ptr[s] < len(per_stage[s]):
                kind, m = per_stage[s][ptr[s]]
                deps = []
                if kind == "fwd" and s > 0:
                    deps.append(("fwd", s - 1, m))
                if kind == "bwd":
                    deps.append(("fwd", s, m))
                    if s < S - 1:
                        deps.append(("bwd", s + 1, m))
                if not all(d in emitted for d in deps):
                    break
                op = (kind, s, m)
                order.append(op)
                emitted.add(op)
                ptr[s] += 1
                progressed = True
        if not progressed:
            raise RuntimeError(  # unreachable: 1F1B is deadlock-free
                f"1F1B submission stalled at {ptr} for S={S} M={M}")
    return order


def simulate_1f1b(num_stages: int, num_microbatches: int,
                  fwd_ticks: float = 1.0, bwd_ticks: float = 1.0) -> dict:
    """Discrete-event simulation of the 1F1B schedule with fixed op
    costs: returns {"makespan", "busy", "bubble_ratio"} where
    bubble_ratio = 1 - busy / (S * makespan). With fwd == bwd cost this
    reproduces the textbook (S-1)/(S-1+M) bubble exactly — the
    theoretical floor the strategy's measured bubble is compared to."""
    S, M = num_stages, num_microbatches
    per_stage = one_f_one_b_schedule(S, M)
    cost = {"fwd": fwd_ticks, "bwd": bwd_ticks}
    done: dict[tuple[str, int, int], float] = {}
    free = [0.0] * S
    for kind, s, m in one_f_one_b_submission_order(S, M):
        deps = []
        if kind == "fwd" and s > 0:
            deps.append(("fwd", s - 1, m))
        if kind == "bwd":
            deps.append(("fwd", s, m))
            if s < S - 1:
                deps.append(("bwd", s + 1, m))
        start = max([free[s]] + [done[d] for d in deps])
        free[s] = done[(kind, s, m)] = start + cost[kind]
    makespan = max(free)
    busy = sum(cost[k] for ops in per_stage for k, _ in ops)
    return {"makespan": makespan, "busy": busy,
            "bubble_ratio": 1.0 - busy / (S * makespan)}


def theoretical_bubble(num_stages: int, num_microbatches: int) -> float:
    """(S-1)/(S-1+M): the 1F1B/GPipe pipeline-fill bubble fraction."""
    S, M = num_stages, num_microbatches
    return (S - 1) / (S - 1 + M) if S > 1 else 0.0


# ---------------------------------------------------------------------------
# Interleaved (circular) 1F1B over worker groups — virtual pipeline stages
# ---------------------------------------------------------------------------
#
# The MPMD counterpart of `pipeline_apply_interleaved`: split the model
# into V = S*R VIRTUAL stages placed round-robin (virtual stage v lives
# on worker v % S, repeat slot v // S). Each fwd/bwd op now costs ~1/R of
# a flat-stage op while total per-worker compute is unchanged, so the
# pipeline fill/drain — the only idle time — shrinks by the same factor:
#
#   bubble = (S-1) / (R*M + S-1)        vs flat  (S-1) / (M + S-1)
#
# strictly lower for R >= 2 whenever M >= S (the circular schedule's
# causality condition, same as pipeline_apply_interleaved). The ticks:
# fwd of (r, s, m) at tick r*M + m + s; the backward pass mirrors the
# forward circle, bwd of (r, s, m) at F + (R-1-r)*M + m + (S-1-s) with
# F = R*M + S - 1. Both passes are conflict-free (one op per worker per
# tick) and dependency-safe for M >= S; a driver submitting actor calls
# in tick order onto FIFO workers realizes exactly this timetable.


def interleaved_1f1b_submission_order(num_stages: int, num_microbatches: int,
                                      num_repeats: int
                                      ) -> list[tuple[str, int, int]]:
    """Global topological submission order for the circular interleaved
    schedule: (kind, virtual_stage, microbatch) triples with
    virtual_stage in [0, S*R); the owning worker is virtual_stage % S
    and its repeat slot is virtual_stage // S. Dependencies — fwd(v,m)
    after fwd(v-1,m); bwd(v,m) after fwd(v,m) and bwd(v+1,m) — are
    satisfied in order, so per-worker FIFO execution IS the schedule.
    With num_repeats == 1 this degrades to a valid flat 1F1B-shaped
    order (all-forward-then-backward per microbatch wave)."""
    S, M, R = num_stages, num_microbatches, num_repeats
    if S < 1 or M < 1 or R < 1:
        raise ValueError(f"need stages/microbatches/repeats >= 1, "
                         f"got {S}, {M}, {R}")
    if M < S:
        raise ValueError(
            f"interleaved schedule needs microbatches {M} >= stages {S}")
    F = R * M + S - 1  # forward-phase tick count
    ops: list[tuple[int, int, str, int, int]] = []
    for r in range(R):
        for m in range(M):
            for s in range(S):
                v = r * S + s
                ops.append((r * M + m + s, s, "fwd", v, m))
                ops.append((F + (R - 1 - r) * M + m + (S - 1 - s),
                            s, "bwd", v, m))
    ops.sort()
    return [(kind, v, m) for _, _, kind, v, m in ops]


def simulate_interleaved_1f1b(num_stages: int, num_microbatches: int,
                              num_repeats: int, fwd_ticks: float = 1.0,
                              bwd_ticks: float = 1.0) -> dict:
    """Discrete-event simulation of the circular interleaved schedule
    with per-VIRTUAL-stage op costs of fwd_ticks/R and bwd_ticks/R (the
    model is the same size — each chunk is 1/R of a flat stage). With
    fwd == bwd cost this reproduces (S-1)/(R*M + S-1) exactly, the floor
    the strategy's measured bubble is compared to. Same keys as
    `simulate_1f1b` so callers can A/B the two."""
    S, M, R = num_stages, num_microbatches, num_repeats
    V = S * R
    cost = {"fwd": fwd_ticks / R, "bwd": bwd_ticks / R}
    done: dict[tuple[str, int, int], float] = {}
    free = [0.0] * S
    busy = 0.0
    for kind, v, m in interleaved_1f1b_submission_order(S, M, R):
        w = v % S
        deps = []
        if kind == "fwd" and v > 0:
            deps.append(("fwd", v - 1, m))
        if kind == "bwd":
            deps.append(("fwd", v, m))
            if v < V - 1:
                deps.append(("bwd", v + 1, m))
        start = max([free[w]] + [done[d] for d in deps])
        free[w] = done[(kind, v, m)] = start + cost[kind]
        busy += cost[kind]
    makespan = max(free)
    return {"makespan": makespan, "busy": busy,
            "bubble_ratio": 1.0 - busy / (S * makespan)}


def theoretical_bubble_interleaved(num_stages: int, num_microbatches: int,
                                   num_repeats: int) -> float:
    """(S-1)/(R*M + S-1): the circular interleaved-1F1B bubble fraction
    — flat `theoretical_bubble` divided by ~R at equal S and M."""
    S, M, R = num_stages, num_microbatches, num_repeats
    return (S - 1) / (R * M + S - 1) if S > 1 else 0.0


def pipeline_apply(stage_fn, stage_params, x, axis_name: str = "pipe",
                   num_microbatches: int | None = None) -> jax.Array:
    """Run `stage_fn(params_i, h) -> h` for stages i = 0..S-1 as a
    pipeline over the `axis_name` mesh axis.

    Inside shard_map: `stage_params` is THIS device's stage slice (the
    caller shards the stacked stage dim), `x` is the full batch
    (replicated along the pipe axis), split into `num_microbatches`
    equal microbatches along dim 0. Returns the full output batch.
    """
    S = _axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    B = x.shape[0]
    M = num_microbatches or S
    assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
    mb = B // M
    micro = x.reshape(M, mb, *x.shape[1:])

    n_ticks = M + S - 1
    # right-rotation by one hop: stage i sends to stage i+1
    shift_perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        held, outputs = carry
        # stage 0 ingests microbatch t (when in range) — other stages
        # keep what arrived from their left neighbor
        feed = micro[jnp.clip(t, 0, M - 1)]
        held = jnp.where(stage == 0,
                         jnp.where(t < M, feed, jnp.zeros_like(feed)),
                         held)
        out = stage_fn(stage_params, held)
        # last stage emits microbatch (t - S + 1) when in range
        m_out = t - (S - 1)
        emit = jnp.logical_and(stage == S - 1,
                               jnp.logical_and(m_out >= 0, m_out < M))
        outputs = outputs.at[jnp.clip(m_out, 0, M - 1)].add(
            jnp.where(emit, out, jnp.zeros_like(out)))
        held = lax.ppermute(out, axis_name, shift_perm)
        return (held, outputs), None

    held0 = jnp.zeros_like(micro[0])
    out0 = jnp.zeros((M, mb, *x.shape[1:]), x.dtype)
    (_, outputs), _ = lax.scan(tick, (held0, out0), jnp.arange(n_ticks))
    # outputs were produced only on the last stage; share them with every
    # pipe rank so the result is replicated along the axis (psum over a
    # one-hot contribution)
    outputs = lax.psum(
        jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)
    return outputs.reshape(B, *x.shape[1:])


def pipeline_apply_interleaved(stage_fn, stage_params, x,
                               axis_name: str = "pipe",
                               num_microbatches: int | None = None,
                               num_repeats: int = 1) -> jax.Array:
    """Interleaved (circular) pipeline schedule — the TPU-native answer
    to Megatron's interleaved 1F1B (reference role: virtual pipeline
    stages, megatron/core/pipeline_parallel/schedules.py; jax shape:
    MaxText's circular pipeline). Each device holds `num_repeats`
    VIRTUAL stages (round-robin placement: device s owns virtual stages
    s, s+S, ..), so the per-device bubble drops from (S-1)/M to
    (S-1)/(R*M); under jax autodiff the scan's backward runs the
    mirrored schedule, interleaving per-microbatch forward/backward the
    way hand-scheduled 1F1B does on GPU runtimes.

    Schedule (M microbatches, S devices, R repeats, V = S*R virtual
    stages): microbatch m enters repeat r at tick r*M + m; at tick t,
    device s processes microbatch (t - s) mod M at repeat (t - s) // M —
    no collisions, one stage-execution per device per tick. Activations
    leaving the last device park in a circular buffer until their next
    repeat's entry tick. Total ticks R*M + S - 1.

    `stage_params` is THIS device's (R, ...) stack of virtual-stage
    params (caller shards the (V, ...) stack over `axis_name` with
    round-robin order: virtual stage v lives at device v % S, slot
    v // S). Requires M >= S (the park time M-S+1 must be >= 1... it is
    >= 0; M >= S keeps the buffer causal).
    """
    S = _axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    R = num_repeats
    B = x.shape[0]
    M = num_microbatches or S
    assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
    assert M >= S, f"interleaved schedule needs microbatches {M} >= stages {S}"
    mb = B // M
    micro = x.reshape(M, mb, *x.shape[1:])

    n_ticks = R * M + S - 1
    shift_perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        held, circ, outputs = carry
        # device s works on microbatch m=(t-s) mod M, repeat r=(t-s)//M
        age = t - stage
        m = jnp.mod(age, M)
        r = jnp.clip(age // M, 0, R - 1)
        active = jnp.logical_and(age >= 0, age < R * M)
        # stage 0 ingest: fresh microbatch on repeat 0, parked wrap after
        feed = jnp.where(age < M, micro[jnp.clip(m, 0, M - 1)],
                         circ[jnp.clip(m, 0, M - 1)])
        held = jnp.where(stage == 0, feed, held)
        params_r = jax.tree.map(lambda p: p[r], stage_params)
        out = jnp.where(active, stage_fn(params_r, held),
                        jnp.zeros_like(held))
        # last stage at a non-final repeat: the activation wraps — it
        # reaches stage 0 next tick and parks in circ until its entry
        # tick (r+1)*M + m; slot m == (arrival_tick - S) mod M
        emit_final = jnp.logical_and(stage == S - 1,
                                     jnp.logical_and(active, r == R - 1))
        outputs = outputs.at[jnp.clip(m, 0, M - 1)].add(
            jnp.where(emit_final, out, jnp.zeros_like(out)))
        held = lax.ppermute(out, axis_name, shift_perm)
        park_slot = jnp.mod(t + 1 - S, M)
        park = jnp.logical_and(stage == 0, t + 1 >= S)
        circ = circ.at[jnp.clip(park_slot, 0, M - 1)].set(
            jnp.where(park, held, circ[jnp.clip(park_slot, 0, M - 1)]))
        return (held, circ, outputs), None

    held0 = jnp.zeros_like(micro[0])
    circ0 = jnp.zeros((M, mb, *x.shape[1:]), x.dtype)
    out0 = jnp.zeros((M, mb, *x.shape[1:]), x.dtype)
    (_, _, outputs), _ = lax.scan(tick, (held0, circ0, out0),
                                  jnp.arange(n_ticks))
    outputs = lax.psum(
        jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)
    return outputs.reshape(B, *x.shape[1:])
