"""Pipeline parallelism — GPipe-style microbatch schedule over the
`pipe` mesh axis.

SURVEY.md §7.8: PP is a first-class capability (the reference schedules
frameworks that implement it; here it is native). TPU-native design:

- stage parameters are stacked on a leading stage axis sharded over
  `pipe` (one stage's weights per device group);
- runs inside shard_map over the pipe axis: every device executes the
  SAME program (XLA-friendly: no per-stage control flow); at schedule
  tick t it applies its stage to the activation it holds, then the
  activations rotate one hop with ppermute — stage i naturally works on
  microbatch (t - i), the classic GPipe staircase with (S-1) bubble
  ticks on each side;
- microbatch m enters at stage 0 on tick m and exits stage S-1 on tick
  m + S - 1; outputs are collected by masked accumulation, so the whole
  schedule is one lax.scan (differentiable, no dynamic shapes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.parallel.ops import axis_size as _axis_size


def pipeline_apply(stage_fn, stage_params, x, axis_name: str = "pipe",
                   num_microbatches: int | None = None) -> jax.Array:
    """Run `stage_fn(params_i, h) -> h` for stages i = 0..S-1 as a
    pipeline over the `axis_name` mesh axis.

    Inside shard_map: `stage_params` is THIS device's stage slice (the
    caller shards the stacked stage dim), `x` is the full batch
    (replicated along the pipe axis), split into `num_microbatches`
    equal microbatches along dim 0. Returns the full output batch.
    """
    S = _axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    B = x.shape[0]
    M = num_microbatches or S
    assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
    mb = B // M
    micro = x.reshape(M, mb, *x.shape[1:])

    n_ticks = M + S - 1
    # right-rotation by one hop: stage i sends to stage i+1
    shift_perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        held, outputs = carry
        # stage 0 ingests microbatch t (when in range) — other stages
        # keep what arrived from their left neighbor
        feed = micro[jnp.clip(t, 0, M - 1)]
        held = jnp.where(stage == 0,
                         jnp.where(t < M, feed, jnp.zeros_like(feed)),
                         held)
        out = stage_fn(stage_params, held)
        # last stage emits microbatch (t - S + 1) when in range
        m_out = t - (S - 1)
        emit = jnp.logical_and(stage == S - 1,
                               jnp.logical_and(m_out >= 0, m_out < M))
        outputs = outputs.at[jnp.clip(m_out, 0, M - 1)].add(
            jnp.where(emit, out, jnp.zeros_like(out)))
        held = lax.ppermute(out, axis_name, shift_perm)
        return (held, outputs), None

    held0 = jnp.zeros_like(micro[0])
    out0 = jnp.zeros((M, mb, *x.shape[1:]), x.dtype)
    (_, outputs), _ = lax.scan(tick, (held0, out0), jnp.arange(n_ticks))
    # outputs were produced only on the last stage; share them with every
    # pipe rank so the result is replicated along the axis (psum over a
    # one-hot contribution)
    outputs = lax.psum(
        jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)
    return outputs.reshape(B, *x.shape[1:])


def pipeline_apply_interleaved(stage_fn, stage_params, x,
                               axis_name: str = "pipe",
                               num_microbatches: int | None = None,
                               num_repeats: int = 1) -> jax.Array:
    """Interleaved (circular) pipeline schedule — the TPU-native answer
    to Megatron's interleaved 1F1B (reference role: virtual pipeline
    stages, megatron/core/pipeline_parallel/schedules.py; jax shape:
    MaxText's circular pipeline). Each device holds `num_repeats`
    VIRTUAL stages (round-robin placement: device s owns virtual stages
    s, s+S, ..), so the per-device bubble drops from (S-1)/M to
    (S-1)/(R*M); under jax autodiff the scan's backward runs the
    mirrored schedule, interleaving per-microbatch forward/backward the
    way hand-scheduled 1F1B does on GPU runtimes.

    Schedule (M microbatches, S devices, R repeats, V = S*R virtual
    stages): microbatch m enters repeat r at tick r*M + m; at tick t,
    device s processes microbatch (t - s) mod M at repeat (t - s) // M —
    no collisions, one stage-execution per device per tick. Activations
    leaving the last device park in a circular buffer until their next
    repeat's entry tick. Total ticks R*M + S - 1.

    `stage_params` is THIS device's (R, ...) stack of virtual-stage
    params (caller shards the (V, ...) stack over `axis_name` with
    round-robin order: virtual stage v lives at device v % S, slot
    v // S). Requires M >= S (the park time M-S+1 must be >= 1... it is
    >= 0; M >= S keeps the buffer causal).
    """
    S = _axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    R = num_repeats
    B = x.shape[0]
    M = num_microbatches or S
    assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
    assert M >= S, f"interleaved schedule needs microbatches {M} >= stages {S}"
    mb = B // M
    micro = x.reshape(M, mb, *x.shape[1:])

    n_ticks = R * M + S - 1
    shift_perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        held, circ, outputs = carry
        # device s works on microbatch m=(t-s) mod M, repeat r=(t-s)//M
        age = t - stage
        m = jnp.mod(age, M)
        r = jnp.clip(age // M, 0, R - 1)
        active = jnp.logical_and(age >= 0, age < R * M)
        # stage 0 ingest: fresh microbatch on repeat 0, parked wrap after
        feed = jnp.where(age < M, micro[jnp.clip(m, 0, M - 1)],
                         circ[jnp.clip(m, 0, M - 1)])
        held = jnp.where(stage == 0, feed, held)
        params_r = jax.tree.map(lambda p: p[r], stage_params)
        out = jnp.where(active, stage_fn(params_r, held),
                        jnp.zeros_like(held))
        # last stage at a non-final repeat: the activation wraps — it
        # reaches stage 0 next tick and parks in circ until its entry
        # tick (r+1)*M + m; slot m == (arrival_tick - S) mod M
        emit_final = jnp.logical_and(stage == S - 1,
                                     jnp.logical_and(active, r == R - 1))
        outputs = outputs.at[jnp.clip(m, 0, M - 1)].add(
            jnp.where(emit_final, out, jnp.zeros_like(out)))
        held = lax.ppermute(out, axis_name, shift_perm)
        park_slot = jnp.mod(t + 1 - S, M)
        park = jnp.logical_and(stage == 0, t + 1 >= S)
        circ = circ.at[jnp.clip(park_slot, 0, M - 1)].set(
            jnp.where(park, held, circ[jnp.clip(park_slot, 0, M - 1)]))
        return (held, circ, outputs), None

    held0 = jnp.zeros_like(micro[0])
    circ0 = jnp.zeros((M, mb, *x.shape[1:]), x.dtype)
    out0 = jnp.zeros((M, mb, *x.shape[1:]), x.dtype)
    (_, _, outputs), _ = lax.scan(tick, (held0, circ0, out0),
                                  jnp.arange(n_ticks))
    outputs = lax.psum(
        jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)
    return outputs.reshape(B, *x.shape[1:])
