"""Pipeline parallelism — GPipe-style microbatch schedule over the
`pipe` mesh axis.

SURVEY.md §7.8: PP is a first-class capability (the reference schedules
frameworks that implement it; here it is native). TPU-native design:

- stage parameters are stacked on a leading stage axis sharded over
  `pipe` (one stage's weights per device group);
- runs inside shard_map over the pipe axis: every device executes the
  SAME program (XLA-friendly: no per-stage control flow); at schedule
  tick t it applies its stage to the activation it holds, then the
  activations rotate one hop with ppermute — stage i naturally works on
  microbatch (t - i), the classic GPipe staircase with (S-1) bubble
  ticks on each side;
- microbatch m enters at stage 0 on tick m and exits stage S-1 on tick
  m + S - 1; outputs are collected by masked accumulation, so the whole
  schedule is one lax.scan (differentiable, no dynamic shapes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn, stage_params, x, axis_name: str = "pipe",
                   num_microbatches: int | None = None) -> jax.Array:
    """Run `stage_fn(params_i, h) -> h` for stages i = 0..S-1 as a
    pipeline over the `axis_name` mesh axis.

    Inside shard_map: `stage_params` is THIS device's stage slice (the
    caller shards the stacked stage dim), `x` is the full batch
    (replicated along the pipe axis), split into `num_microbatches`
    equal microbatches along dim 0. Returns the full output batch.
    """
    S = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    B = x.shape[0]
    M = num_microbatches or S
    assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
    mb = B // M
    micro = x.reshape(M, mb, *x.shape[1:])

    n_ticks = M + S - 1
    # right-rotation by one hop: stage i sends to stage i+1
    shift_perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        held, outputs = carry
        # stage 0 ingests microbatch t (when in range) — other stages
        # keep what arrived from their left neighbor
        feed = micro[jnp.clip(t, 0, M - 1)]
        held = jnp.where(stage == 0,
                         jnp.where(t < M, feed, jnp.zeros_like(feed)),
                         held)
        out = stage_fn(stage_params, held)
        # last stage emits microbatch (t - S + 1) when in range
        m_out = t - (S - 1)
        emit = jnp.logical_and(stage == S - 1,
                               jnp.logical_and(m_out >= 0, m_out < M))
        outputs = outputs.at[jnp.clip(m_out, 0, M - 1)].add(
            jnp.where(emit, out, jnp.zeros_like(out)))
        held = lax.ppermute(out, axis_name, shift_perm)
        return (held, outputs), None

    held0 = jnp.zeros_like(micro[0])
    out0 = jnp.zeros((M, mb, *x.shape[1:]), x.dtype)
    (_, outputs), _ = lax.scan(tick, (held0, out0), jnp.arange(n_ticks))
    # outputs were produced only on the last stage; share them with every
    # pipe rank so the result is replicated along the axis (psum over a
    # one-hot contribution)
    outputs = lax.psum(
        jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)
    return outputs.reshape(B, *x.shape[1:])
