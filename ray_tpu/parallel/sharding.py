"""Partition-rule based sharding for parameter pytrees.

The reference delegates parameter layout to torch DDP/FSDP wrappers
(ray/train/torch/train_loop_utils.py:162,179-183). The TPU-native
formulation is declarative: a model ships an ordered list of
(path-regex -> PartitionSpec) rules; we map them over the param pytree to
NamedShardings and let GSPMD insert the collectives.
"""

from __future__ import annotations

import math
import re
from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

PyTree = Any


def path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


_path_str = path_str  # pre-round-14 private name


class PartitionRules:
    """Ordered (regex, PartitionSpec) rules; first match wins.

    Specs may name axes that a given mesh doesn't have — those axis names
    are dropped at resolution time, so one rule set serves every mesh
    shape (a tensor='absent' mesh simply replicates that dimension).
    """

    def __init__(self, rules: Sequence[tuple[str, PartitionSpec]]):
        self._rules = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(self, path: str, mesh: Mesh | None = None) -> PartitionSpec:
        for pat, spec in self._rules:
            if pat.search(path):
                return _prune_spec(spec, mesh) if mesh is not None else spec
        return PartitionSpec()

    def shardings(self, tree: PyTree, mesh: Mesh) -> PyTree:
        return jax.tree_util.tree_map_with_path(
            lambda path, _: NamedSharding(
                mesh, self.spec_for(_path_str(path), mesh)
            ),
            tree,
        )

    def specs(self, tree: PyTree, mesh: Mesh | None = None) -> PyTree:
        return jax.tree_util.tree_map_with_path(
            lambda path, _: self.spec_for(_path_str(path), mesh), tree
        )


def _prune_spec(spec: PartitionSpec, mesh) -> PartitionSpec:
    """Drop axis names not present in (or of size 1 in) the mesh.

    Works for both concrete `Mesh` and `AbstractMesh` (whose .shape is a
    name->size mapping).
    """
    shape = dict(mesh.shape)
    have = {n for n, s in shape.items() if s > 1}

    def prune(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in have)
            return kept if kept else None
        return entry if entry in have else None

    return PartitionSpec(*(prune(e) for e in spec))


def add_axis_to_spec(spec: PartitionSpec, shape, mesh, axis: str
                     ) -> PartitionSpec:
    """Extend `spec` (already pruned to `mesh`) with `axis` on the first
    dimension of `shape` that divides evenly by the combined shard count
    — the ZeRO-style "also shard this leaf over the replica axis"
    transformation. Leaves already touching `axis`, scalars, and leaves
    with no evenly-divisible dimension come back unchanged (those stay
    replicated over `axis` and are counted by the caller's ~1/N memory
    assertion slack)."""
    sizes = dict(mesh.shape)
    n = sizes.get(axis, 1)
    if n <= 1 or not shape:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))

    def axes_of(entry):
        if entry is None:
            return ()
        if isinstance(entry, (tuple, list)):
            return tuple(entry)
        return (entry,)

    if any(axis in axes_of(e) for e in entries):
        return spec
    for i, dim in enumerate(shape):
        cur = axes_of(entries[i])
        already = math.prod(sizes.get(a, 1) for a in cur)
        if dim % (already * n) == 0:
            entries[i] = cur + (axis,) if cur else axis
            return PartitionSpec(*entries)
    return spec


def shard_pytree(tree: PyTree, rules: PartitionRules, mesh: Mesh) -> PyTree:
    """Device-put `tree` with shardings derived from `rules`."""
    shardings = rules.shardings(tree, mesh)
    return jax.device_put(tree, shardings)


def constrain(x: jax.Array, *spec_entries) -> jax.Array:
    """with_sharding_constraint that tolerates axes missing from the
    ambient mesh (so model code can always write the full logical spec)."""
    mesh = _current_mesh()
    if mesh is None:
        return x
    spec = _prune_spec(PartitionSpec(*spec_entries), mesh)
    if isinstance(mesh, Mesh):
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    return jax.lax.with_sharding_constraint(x, spec)


def _current_mesh():
    """The ambient mesh, if model code runs under `jax.sharding.use_mesh`
    (or a `with mesh:` block); None otherwise (single-device paths)."""
    try:
        env = jax.sharding.get_abstract_mesh()
        if env is not None and env.axis_names:
            return env
    except Exception:
        pass
    try:
        m = jax.interpreters.pxla.thread_resources.env.physical_mesh
        if m.axis_names:
            return m
    except Exception:
        pass
    return None
