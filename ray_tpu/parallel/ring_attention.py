"""Ring attention — causal attention over a sequence-sharded mesh axis.

Long-context context-parallelism (SURVEY.md §5: absent from the
reference, which delegates sequence scaling to user frameworks; required
here as a first-class capability). Design:

- every device holds a (B, T/n, H, D) shard of q/k/v along the `seq`
  mesh axis;
- n ring steps: attend the local q block against the currently-held k/v
  block with an online-softmax partial update (f32 statistics), then
  ppermute the k/v block one hop around the ring — overlap-friendly on
  TPU (ICI neighbor exchange), never materializing more than a
  (T/n)x(T/n) score block per device;
- block-level causality: a kv block strictly in the future contributes
  nothing (its update is masked out); the diagonal block is masked
  triangularly inside.

Differentiable by construction (jnp ops + lax.scan + ppermute transpose)
— no custom VJP needed. Use under shard_map with the `seq` axis; see
ulysses_attention for the all-to-all alternative (head-sharded compute).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.parallel.ops import axis_size as _axis_size

_NEG = -1e30


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = "seq", causal: bool = True) -> jax.Array:
    """q,k,v: per-device (B, t, H, D) shards of a (B, T, H, D) global
    array sharded on dim 1 over `axis_name`. Returns the matching output
    shard. Call inside shard_map/pjit-manual over that axis."""
    B, t, H, D = q.shape
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    scale = 1.0 / (D ** 0.5)
    qf = q.astype(jnp.float32)

    # positions of the local q rows / current kv cols within the GLOBAL seq
    q_pos = my * t + jnp.arange(t)  # (t,)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, _):
        o, m, l, kb, vb, src = carry
        # which global block the held kv is: src (traced scalar)
        kv_pos = src * t + jnp.arange(t)  # (t,)
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kb.astype(jnp.float32)) * scale
        if causal:
            mask = kv_pos[None, :] <= q_pos[:, None]  # (t_q, t_k)
            s = jnp.where(mask[None, None], s, _NEG)
        m_blk = jnp.max(s, axis=-1)  # (B,H,t)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new[..., None])  # (B,H,t,t)
        alpha = jnp.exp(m - m_new)  # (B,H,t)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, vb.astype(jnp.float32))
        o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv
        kb, vb = lax.ppermute((kb, vb), axis_name, perm)
        src = (src - 1) % n  # after the shift we hold our neighbor's block
        return (o_new, m_new, l_new, kb, vb, src), None

    o0 = jnp.zeros((B, t, H, D), jnp.float32)
    m0 = jnp.full((B, H, t), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, t), jnp.float32)
    (o, m, l, _, _, _), _ = lax.scan(
        step, (o0, m0, l0, k, v, my), None, length=n)
    l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows (none in causal)
    out = o / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str = "seq", causal: bool = True,
                      attn_fn=None) -> jax.Array:
    """Ulysses-style sequence parallelism: all-to-all swaps the sharded
    dimension from sequence to heads, runs FULL-sequence attention on
    H/n heads per device, and swaps back. Cheaper than a ring when
    H >= n and the full T fits per device; the all-to-all rides ICI.

    q,k,v: per-device (B, T/n, H, D) shards -> same-shaped output shard.
    `attn_fn(q,k,v)` runs the dense attention (defaults to the causal
    einsum reference; pass the flash kernel on TPU)."""
    if attn_fn is None:
        from ray_tpu.ops.attention import causal_attention_reference

        attn_fn = causal_attention_reference

    def a2a(x, split, concat):
        return lax.all_to_all(x, axis_name, split_axis=split,
                              concat_axis=concat, tiled=True)

    # (B, T/n, H, D) -> (B, T, H/n, D)
    qh, kh, vh = (a2a(x, 2, 1) for x in (q, k, v))
    oh = attn_fn(qh, kh, vh)
    # back: (B, T, H/n, D) -> (B, T/n, H, D)
    return a2a(oh, 1, 2)
