"""Device-mesh construction from TPU topology.

TPU-first replacement for the reference's process-group bootstrap
(ray/train/torch/config.py:66-124 builds an NCCL world of N one-GPU
workers). Here the unit of compute is a pod slice running one SPMD
program: we build a `jax.sharding.Mesh` whose axes carry the parallelism
meaning (data / fsdp / tensor / seq / expert / pipe), laid out so that
collectives ride ICI within a slice and DCN across slices.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

# Replica-deterministic RNG under sharding (the invariant graftlint
# GL003 protects): with the legacy non-partitionable threefry (the
# default before jax 0.5), a jitted `jax.random.*` whose output is
# sharded computes DIFFERENT bits than the same call unsharded — the
# partitioner rewrites the counter layout — so sharded init/dropout
# silently diverges from the single-device program. Partitionable
# threefry makes the bits a pure function of key+shape regardless of
# sharding. Newer jax defaults to True; force it on older versions.
if not getattr(jax.config, "jax_threefry_partitionable", True):
    jax.config.update("jax_threefry_partitionable", True)

# Canonical axis names. Order matters: the slowest-varying axis should be
# the one crossing DCN (dcn/data), the fastest-varying ones (tensor/seq)
# need the highest bandwidth and should map to adjacent ICI neighbors.
AXIS_DCN = "dcn"  # across pod slices (data-parallel only; low bandwidth)
AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_PIPE = "pipe"
AXIS_EXPERT = "expert"
AXIS_SEQ = "seq"
AXIS_TENSOR = "tensor"

# Canonical order from outermost (DCN-friendly) to innermost (ICI-hungry).
CANONICAL_AXIS_ORDER = (
    AXIS_DCN,
    AXIS_DATA,
    AXIS_PIPE,
    AXIS_FSDP,
    AXIS_EXPERT,
    AXIS_SEQ,
    AXIS_TENSOR,
)

# Batch-like activation dimensions are sharded over every replica-ish axis.
BATCH_AXES = (AXIS_DCN, AXIS_DATA, AXIS_FSDP)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape. Size -1 on at most one axis means "infer
    from the device count". Axes of size 1 are kept (they cost nothing and
    make partition specs uniform across configurations)."""

    data: int = -1
    pipe: int = 1
    fsdp: int = 1
    expert: int = 1
    seq: int = 1
    tensor: int = 1
    dcn: int = 1  # number of pod slices (outermost, data-parallel only)

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = {
            AXIS_DCN: self.dcn,
            AXIS_DATA: self.data,
            AXIS_PIPE: self.pipe,
            AXIS_FSDP: self.fsdp,
            AXIS_EXPERT: self.expert,
            AXIS_SEQ: self.seq,
            AXIS_TENSOR: self.tensor,
        }
        unknown = [k for k, v in sizes.items() if v == -1]
        if len(unknown) > 1:
            raise ValueError(f"at most one axis may be -1, got {unknown}")
        known = math.prod(v for v in sizes.values() if v != -1)
        if unknown:
            if n_devices % known != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {known}"
                )
            sizes[unknown[0]] = n_devices // known
        elif known != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {known} devices, have {n_devices}"
            )
        return sizes


def build_mesh(
    spec: MeshSpec | dict[str, int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a Mesh over `devices` (default: all) in canonical axis order.

    Uses `mesh_utils.create_device_mesh` so that, on real TPU topologies,
    axis neighbors are ICI neighbors; on CPU/host platforms it falls back
    to a simple reshape.
    """
    devices = list(devices if devices is not None else jax.devices())
    if spec is None:
        spec = MeshSpec()
    sizes = (
        spec.resolve(len(devices))
        if isinstance(spec, MeshSpec)
        else dict(spec)
    )
    names = tuple(a for a in CANONICAL_AXIS_ORDER if a in sizes)
    # Any axes the caller passed that are not canonical go last.
    names += tuple(a for a in sizes if a not in names)
    shape = tuple(sizes[a] for a in names)
    if math.prod(shape) != len(devices):
        raise ValueError(f"mesh shape {shape} != {len(devices)} devices")
    dev_array = None
    n_slices = sizes.get(AXIS_DCN, 1)
    if n_slices > 1 and len(slice_groups(devices)) == n_slices:
        # 2-level hybrid mesh: the dcn axis crosses slice boundaries
        # (DCN links), every other axis stays within a slice (ICI) —
        # "How to Scale Your Model" multislice recipe.
        ici_shape = tuple(1 if a == AXIS_DCN else sizes[a] for a in names)
        dcn_shape = tuple(n_slices if a == AXIS_DCN else 1 for a in names)
        try:
            dev_array = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices)
        except (ValueError, NotImplementedError):
            dev_array = None
    if dev_array is None:
        try:
            dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
        except (ValueError, NotImplementedError):
            dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, names)


def local_mesh(**axes: int) -> Mesh:
    """Convenience: mesh over all local devices, e.g. local_mesh(data=-1)."""
    if not axes:
        axes = {AXIS_DATA: -1}
    spec = MeshSpec(**axes)
    return build_mesh(spec)


def slice_groups(devices: Sequence[jax.Device] | None = None) -> dict[int, list]:
    """Group devices by TPU slice index (DCN domain). On non-TPU platforms
    every device lands in slice 0. Used by the scheduler's slice-bundle
    placement (reference: TPU pod metadata, ray/_private/accelerators/tpu.py:19-44).
    """
    devices = list(devices if devices is not None else jax.devices())
    groups: dict[int, list] = {}
    for d in devices:
        idx = getattr(d, "slice_index", 0) or 0
        groups.setdefault(idx, []).append(d)
    return groups
