"""TPU-native parallelism layer.

Replaces the reference's NCCL/Gloo worlds (python/ray/util/collective/,
python/ray/train/torch/config.py:66-124) with SPMD over jax device meshes:
mesh construction from TPU slice topology, partition-rule based sharding,
and a collective API that lowers to XLA ICI/DCN primitives.
"""

from ray_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_PIPE,
    AXIS_SEQ,
    AXIS_TENSOR,
    MeshSpec,
    build_mesh,
    local_mesh,
)
from ray_tpu.parallel.sharding import (
    PartitionRules,
    shard_pytree,
)

__all__ = [
    "AXIS_DATA",
    "AXIS_FSDP",
    "AXIS_TENSOR",
    "AXIS_SEQ",
    "AXIS_EXPERT",
    "AXIS_PIPE",
    "MeshSpec",
    "build_mesh",
    "local_mesh",
    "PartitionRules",
    "shard_pytree",
]
