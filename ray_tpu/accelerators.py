"""Accelerator plugin registry — the generic seam over device types.

Reference parity: ray._private.accelerators (accelerators/__init__.py
registry + AcceleratorManager ABC, accelerators/accelerator.py:23):
each accelerator type implements detection (how many on this node,
what type), node labeling, and per-worker visibility handoff; the
resource layer stays generic over the registry. TPU is the first-class
implementation (delegating to core/tpu.py slice identity); the NVIDIA
manager shows the seam generalizes — it detects via the standard env/
driver paths and manages CUDA_VISIBLE_DEVICES, though no GPU exists in
this image to exercise it.
"""

from __future__ import annotations

import os


class AcceleratorManager:
    """One accelerator family (reference: AcceleratorManager ABC —
    accelerator.py:23)."""

    # resource name in resource dicts ({"TPU": 1})
    resource_name: str = ""

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        """Devices physically present on this node (0 = none)."""
        raise NotImplementedError

    @staticmethod
    def get_current_node_accelerator_type() -> str | None:
        """Family/pod type string, e.g. "v5e" / "A100"."""
        return None

    @staticmethod
    def get_current_node_labels() -> dict[str, str]:
        """Identity labels to assert on the node (slice/topology)."""
        return {}

    @staticmethod
    def configure_worker_env(env: dict, claimed: bool):
        """Mutate a worker's spawn env: hand the device through when the
        worker's resources claim it, hide it otherwise."""


class TPUAcceleratorManager(AcceleratorManager):
    """TPU via the jax/axon runtime (reference:
    accelerators/tpu.py:19-170)."""

    resource_name = "TPU"

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        # avoid initializing a jax backend just to count: the axon pool
        # env marks a tunnel-attached chip; TPU_CHIPS_PER_HOST covers
        # real TPU VMs
        if os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS"):
            try:
                return int(os.environ.get("TPU_CHIPS_PER_HOST", "4"))
            except ValueError:
                return 4
        return 1 if os.environ.get("PALLAS_AXON_POOL_IPS") else 0

    @staticmethod
    def get_current_node_accelerator_type() -> str | None:
        from ray_tpu.core import tpu as tpu_mod

        return tpu_mod.detect_slice_labels().get(tpu_mod.POD_TYPE_LABEL)

    @staticmethod
    def get_current_node_labels() -> dict[str, str]:
        from ray_tpu.core import tpu as tpu_mod

        return tpu_mod.detect_slice_labels()

    @staticmethod
    def configure_worker_env(env: dict, claimed: bool):
        if claimed:
            # hand the chip through (reference: TPU_VISIBLE_CHIPS
            # management, accelerators/tpu.py:157-170)
            env.pop("JAX_PLATFORMS", None)
            if "RAY_TPU_AXON_POOL_IPS" in env:
                env["PALLAS_AXON_POOL_IPS"] = env["RAY_TPU_AXON_POOL_IPS"]
        else:
            # never grab the (single) chip by default; park the pool env
            # so a later TPU-claiming worker can restore it
            if "PALLAS_AXON_POOL_IPS" in env:
                env["RAY_TPU_AXON_POOL_IPS"] = \
                    env.pop("PALLAS_AXON_POOL_IPS")
            env["JAX_PLATFORMS"] = "cpu"


class NvidiaGPUAcceleratorManager(AcceleratorManager):
    """NVIDIA via the standard driver/env surface (reference:
    accelerators/nvidia_gpu.py). Present to prove the seam is generic;
    this image has no GPU."""

    resource_name = "GPU"

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        try:
            return len(os.listdir("/proc/driver/nvidia/gpus"))
        except OSError:
            return 0

    @staticmethod
    def configure_worker_env(env: dict, claimed: bool):
        if not claimed:
            env["CUDA_VISIBLE_DEVICES"] = ""
        else:
            env.pop("CUDA_VISIBLE_DEVICES", None)


_REGISTRY: dict[str, type[AcceleratorManager]] = {}


def register(manager: type[AcceleratorManager]):
    _REGISTRY[manager.resource_name] = manager
    return manager


def get_manager(resource_name: str) -> type[AcceleratorManager] | None:
    return _REGISTRY.get(resource_name)


def all_managers() -> dict[str, type[AcceleratorManager]]:
    return dict(_REGISTRY)


def detect_node_resources() -> dict[str, float]:
    """Auto-detected accelerator resources for this node (reference:
    resource autodetection at node start)."""
    out: dict[str, float] = {}
    for name, mgr in _REGISTRY.items():
        n = mgr.get_current_node_num_accelerators()
        if n > 0:
            out[name] = float(n)
    return out


register(TPUAcceleratorManager)
register(NvidiaGPUAcceleratorManager)
