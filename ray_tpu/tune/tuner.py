"""Tuner — experiment driver over trial actors.

Reference parity: ray.tune.Tuner (tune/tuner.py:44, fit :344) driving the
TuneController event loop (tune/execution/tune_controller.py:68, step
:666): trials are actors; the controller starts up to the concurrency
limit, polls reports, consults the scheduler (ASHA early stopping), and
persists experiment state so `Tuner.restore` can finish interrupted
sweeps. Trials run as actors on the task/actor runtime — each can itself
be a JaxTrainer fit (trainer-in-trial, how Train rides Tune in the
reference, base_trainer.py:577-623)."""

from __future__ import annotations

import dataclasses
import json
import os
import queue
import threading
import time
import traceback
from typing import Any, Callable

import cloudpickle

from ray_tpu.tune.schedulers import CONTINUE, STOP, FIFOScheduler
from ray_tpu.tune.search import generate_variants

# ---------------------------------------------------------------- session

_trial_session = None


class _TrialSession:
    def __init__(self, restored_checkpoint=None):
        # small bound keeps fast trainables in rough lockstep with the
        # controller so scheduler decisions (ASHA cuts, PBT exploits)
        # apply mid-flight instead of after the trial already finished
        self.results: queue.Queue = queue.Queue(maxsize=2)
        self.iteration = 0
        self.stopped = threading.Event()
        self.restored_checkpoint = restored_checkpoint
        self.latest_checkpoint = None
        self.ckpt_lock = threading.Lock()

    def report(self, metrics: dict, checkpoint=None):
        if self.stopped.is_set():
            raise _StopTrial()
        self.iteration += 1
        m = dict(metrics)
        m.setdefault("training_iteration", self.iteration)
        if checkpoint is not None:
            # PBT exploit clones this state into another trial
            # (reference: pbt.py _exploit via trial checkpoints)
            with self.ckpt_lock:
                self.latest_checkpoint = cloudpickle.dumps(checkpoint)
        while True:
            try:
                self.results.put(m, timeout=0.1)
                break
            except queue.Full:
                if self.stopped.is_set():
                    raise _StopTrial() from None


class _StopTrial(BaseException):
    """Raised inside the trainable to unwind when the scheduler stops the
    trial (BaseException so bare `except Exception` in user code doesn't
    swallow it — reference uses the session's StopIteration channel)."""


def report(metrics: dict, checkpoint=None, **kwargs):
    """ray_tpu.tune.report — inside a trainable. `checkpoint` may be any
    picklable state; PBT clones it into exploited trials."""
    if _trial_session is None:
        raise RuntimeError("tune.report() outside a trial")
    _trial_session.report(metrics, checkpoint=checkpoint)


def get_checkpoint():
    """Inside a trainable: the checkpoint this trial was (re)started from
    (None on a fresh start; set after a PBT exploit or restore)."""
    if _trial_session is None:
        raise RuntimeError("tune.get_checkpoint() outside a trial")
    return _trial_session.restored_checkpoint


class TrialActor:
    """Hosts one trial: runs the trainable on a thread, serves polling."""

    def __init__(self, trial_id: str, fn_blob: bytes, config: dict,
                 ckpt_blob: bytes | None = None):
        global _trial_session
        self.trial_id = trial_id
        restored = cloudpickle.loads(ckpt_blob) if ckpt_blob else None
        self.session = _TrialSession(restored_checkpoint=restored)
        _trial_session = self.session
        self.error: str | None = None
        self.finished = threading.Event()
        fn = cloudpickle.loads(fn_blob)

        def run():
            try:
                fn(config)
            except _StopTrial:
                pass
            except BaseException as e:  # noqa: BLE001
                self.error = "".join(traceback.format_exception(e))
            finally:
                self.finished.set()

        threading.Thread(target=run, daemon=True,
                         name=f"trial-{trial_id}").start()

    def poll(self, timeout: float = 2.0) -> dict:
        out = []
        deadline = time.monotonic() + timeout
        while True:
            try:
                out.append(self.session.results.get_nowait())
            except queue.Empty:
                if out or self.finished.is_set():
                    break
                if time.monotonic() >= deadline:
                    break
                time.sleep(0.02)
        done = self.finished.is_set() and self.session.results.empty()
        with self.session.ckpt_lock:
            ckpt = self.session.latest_checkpoint
            self.session.latest_checkpoint = None  # ship each blob once
        return {"results": out, "done": done, "error": self.error,
                "checkpoint": ckpt}

    def stop(self):
        self.session.stopped.set()
        return True


# ---------------------------------------------------------------- trials


class Trial:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    TERMINATED = "TERMINATED"
    ERROR = "ERROR"
    STOPPED = "STOPPED"  # by scheduler

    def __init__(self, trial_id: str, config: dict):
        self.trial_id = trial_id
        self.config = config
        self.status = Trial.PENDING
        self.last_result: dict = {}
        self.error: str | None = None
        self.actor = None

    def to_json(self) -> dict:
        return {"trial_id": self.trial_id, "config": _json_safe(self.config),
                "status": self.status, "last_result": _json_safe(self.last_result),
                "error": self.error}


@dataclasses.dataclass
class TuneConfig:
    """Reference: ray.tune.TuneConfig."""

    metric: str | None = None
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: int | None = None
    scheduler: Any = None
    search_alg: Any = None  # a tune.search.Searcher (e.g. TPESearcher)
    seed: int | None = None
    trial_resources: dict[str, float] | None = None


@dataclasses.dataclass
class TuneResult:
    trial_id: str
    config: dict
    metrics: dict
    error: str | None = None


class ResultGrid:
    """Reference: ray.tune.ResultGrid."""

    def __init__(self, results: list[TuneResult], metric, mode):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i):
        return self._results[i]

    @property
    def errors(self):
        return [r for r in self._results if r.error]

    def get_best_result(self, metric: str | None = None,
                        mode: str | None = None) -> TuneResult:
        metric = metric or self._metric
        mode = mode or self._mode
        scored = [r for r in self._results
                  if r.error is None and metric in r.metrics]
        if not scored:
            raise ValueError("no successful trial reported "
                             f"metric {metric!r}")
        return (max if mode == "max" else min)(
            scored, key=lambda r: r.metrics[metric])

    def get_dataframe(self):
        rows = [{"trial_id": r.trial_id, **r.metrics,
                 **{f"config/{k}": v for k, v in r.config.items()}}
                for r in self._results]
        try:
            import pandas as pd

            return pd.DataFrame(rows)
        except ImportError:
            return rows


# ------------------------------------------------- trainable adapters


def _stop_met(stop: dict | None, result: dict) -> bool:
    """Reference: ray.tune run(stop={...}) — stop when any named metric
    reaches its threshold."""
    if not stop:
        return False
    for k, v in stop.items():
        r = result.get(k)
        if r is not None and r >= v:
            return True
    return False


def _class_trainable_fn(cls, ckpt_every: int = 1):
    """Drive a Trainable subclass as a function trial: loop train(),
    ship full state as the checkpoint each iteration, resume from the
    session checkpoint on (re)start (reference:
    tune/trainable/function_trainable.py wrapping vs class Trainable —
    here the class API is bridged onto the session protocol). Stop
    criteria are enforced driver-side in fit(), uniformly for every
    trainable kind; the loop ends when the scheduler/driver stops the
    session (report raises _StopTrial)."""

    def fn(config):
        t = cls(config)
        ckpt = get_checkpoint()
        if ckpt is not None:
            t._restore_full_state(ckpt)
        try:
            while True:
                result = t.train()
                ship = t.iteration % max(1, ckpt_every) == 0
                report(result,
                       checkpoint=t._full_state() if ship else None)
        finally:
            t.stop()

    return fn


def _algo_config_fn(base_config, ckpt_every: int = 1):
    """Drive an rllib AlgorithmConfig as a trial: each trial copies the
    base config, overwrites the sampled hyperparams, builds the
    algorithm (itself a Trainable), and loops train/checkpoint
    (reference: Tuner("PPO", param_space=config) —
    tune/registry + Algorithm-as-Trainable)."""
    blob = cloudpickle.dumps(base_config)

    def fn(config):
        base = cloudpickle.loads(blob)
        # validated update: a typo'd sweep key raises instead of
        # silently running every trial on defaults
        base.update_from_dict(config)
        algo = base.build()
        ckpt = get_checkpoint()
        if ckpt is not None:
            algo._restore_full_state(ckpt)
        try:
            while True:
                result = algo.train()
                ship = algo.iteration % max(1, ckpt_every) == 0
                report(result,
                       checkpoint=algo._full_state() if ship else None)
        finally:
            algo.stop()

    return fn


# ---------------------------------------------------------------- tuner


class Tuner:
    def __init__(self, trainable: Callable, *, param_space: dict | None = None,
                 tune_config: TuneConfig | None = None,
                 run_config=None):
        from ray_tpu.train.trainer import RunConfig

        self._trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._restored_trials: list[Trial] | None = None
        self._restored_ckpts: dict[str, bytes] = {}

    # -- persistence -----------------------------------------------------

    def _exp_dir(self) -> str:
        name = self.run_config.name or "tune_experiment"
        storage = self.run_config.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results")
        return os.path.join(storage, name)

    def _save_state(self, trials: list[Trial]):
        d = self._exp_dir()
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, ".tuner_state.json.tmp")
        with open(tmp, "w") as f:
            json.dump({"trials": [t.to_json() for t in trials]}, f)
        os.replace(tmp, os.path.join(d, "tuner_state.json"))

    @classmethod
    def restore(cls, path: str, trainable: Callable) -> "Tuner":
        """Resume an interrupted experiment: finished trials keep their
        recorded results; unfinished ones restart FROM THEIR LAST
        CHECKPOINT when one was persisted (reference: Tuner.restore,
        tune/tuner.py + trial checkpoint dirs)."""
        with open(os.path.join(path, "tuner_state.json")) as f:
            state = json.load(f)
        tuner = cls(trainable)
        tuner.run_config.name = os.path.basename(path.rstrip("/"))
        tuner.run_config.storage_path = os.path.dirname(path.rstrip("/"))
        trials = []
        for tj in state["trials"]:
            t = Trial(tj["trial_id"], tj["config"])
            t.status = tj["status"]
            t.last_result = tj["last_result"]
            t.error = tj.get("error")
            if t.status in (Trial.PENDING, Trial.RUNNING):
                t.status = Trial.PENDING  # rerun interrupted trials
                ckpt_file = os.path.join(path, f"ckpt_{t.trial_id}.pkl")
                if os.path.exists(ckpt_file):
                    with open(ckpt_file, "rb") as cf:
                        tuner._restored_ckpts[t.trial_id] = cf.read()
            trials.append(t)
        tuner._restored_trials = trials
        return tuner

    def _persist_checkpoint(self, trial_id: str, blob: bytes):
        d = self._exp_dir()
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, f".ckpt_{trial_id}.tmp")
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, os.path.join(d, f"ckpt_{trial_id}.pkl"))

    # -- trainable resolution --------------------------------------------

    def _resolve_trainable(self) -> tuple[Callable, dict]:
        """Function trainables pass through; Trainable subclasses and
        rllib AlgorithmConfig objects are adapted onto the session
        protocol. AlgorithmConfig fields holding search markers
        (grid_search / Domain) become the param space."""
        from ray_tpu.tune.trainable import is_trainable_class

        t = self._trainable
        param_space = dict(self.param_space or {})
        cc = getattr(self.run_config, "checkpoint_config", None)
        ckpt_every = getattr(cc, "checkpoint_frequency", 1) if cc else 1
        if is_trainable_class(t):
            return _class_trainable_fn(t, ckpt_every), param_space
        if hasattr(t, "build") and hasattr(t, "extract_param_space"):
            algo_space = t.extract_param_space()
            return _algo_config_fn(t, ckpt_every), \
                {**algo_space, **param_space}
        return t, param_space

    # -- fit -------------------------------------------------------------

    def fit(self) -> ResultGrid:
        import ray_tpu

        tc = self.tune_config
        scheduler = tc.scheduler or FIFOScheduler()
        if hasattr(scheduler, "set_objective") and tc.metric:
            scheduler.set_objective(tc.metric, tc.mode)
        searcher = tc.search_alg
        if searcher is not None and hasattr(searcher, "set_objective") \
                and tc.metric:
            searcher.set_objective(tc.metric, tc.mode)
        trainable, param_space = self._resolve_trainable()
        stop_criteria = getattr(self.run_config, "stop", None)
        num_to_create = 0
        if self._restored_trials is not None:
            trials = self._restored_trials
        elif searcher is not None:
            # model-based search: configs are suggested one at a time as
            # slots free, conditioned on completed results
            trials = []
            num_to_create = max(1, tc.num_samples)
        else:
            variants = generate_variants(param_space, tc.num_samples,
                                         tc.seed)
            trials = [Trial(f"trial_{i:05d}", cfg)
                      for i, cfg in enumerate(variants)]
        fn_blob = cloudpickle.dumps(trainable)
        res = dict(tc.trial_resources or {"CPU": 1.0})
        limit = tc.max_concurrent_trials or max(
            1, int(ray_tpu.cluster_resources().get("CPU", 1)))
        actor_cls = ray_tpu.remote(**{
            "num_cpus": res.get("CPU", 1.0),
            "resources": {k: v for k, v in res.items() if k != "CPU"},
        })(TrialActor)

        pending = [t for t in trials if t.status == Trial.PENDING]
        running: list[Trial] = []
        ckpts: dict[str, bytes] = {}  # trial_id -> latest checkpoint blob
        self._save_state(trials)
        while pending or running or num_to_create > 0:
            while (pending or num_to_create > 0) and len(running) < limit:
                if pending:
                    t = pending.pop(0)
                else:
                    tid = f"trial_{len(trials):05d}"
                    cfg = searcher.suggest(tid)
                    if cfg is None:
                        num_to_create = 0
                        break
                    num_to_create -= 1
                    t = Trial(tid, cfg)
                    trials.append(t)
                t.actor = actor_cls.options(
                    max_concurrency=2).remote(
                        t.trial_id, fn_blob, t.config,
                        self._restored_ckpts.get(t.trial_id))
                t.status = Trial.RUNNING
                running.append(t)
                if hasattr(scheduler, "on_trial_add"):
                    scheduler.on_trial_add(t.trial_id, t.config)
            refs = {t.trial_id: t.actor.poll.remote() for t in running}
            for t in list(running):
                try:
                    r = ray_tpu.get(refs[t.trial_id], timeout=120)
                except Exception as e:  # noqa: BLE001
                    t.status = Trial.ERROR
                    t.error = f"trial actor failed: {e}"
                    running.remove(t)
                    scheduler.on_trial_complete(t.trial_id)
                    if searcher is not None:
                        searcher.on_trial_complete(t.trial_id, None)
                    continue
                if r.get("checkpoint"):
                    ckpts[t.trial_id] = r["checkpoint"]
                    self._persist_checkpoint(t.trial_id, r["checkpoint"])
                decision = CONTINUE
                hit_stop = False
                for m in r["results"]:
                    t.last_result = m
                    if searcher is not None:
                        searcher.on_trial_result(t.trial_id, m)
                    d = scheduler.on_result(t.trial_id, m)
                    if d == STOP:
                        decision = STOP
                    elif isinstance(d, tuple) and \
                            d[0] in ("EXPLOIT", "REALLOCATE"):
                        decision = d
                    if _stop_met(stop_criteria, m):
                        # pin last_result at the stopping report: an
                        # async trial may have raced a few iterations
                        # past the criteria before we stop it
                        hit_stop = True
                        break
                if r["error"]:
                    t.status = Trial.ERROR
                    t.error = r["error"]
                elif r["done"] or hit_stop:
                    t.status = Trial.TERMINATED
                    if hit_stop and not r["done"]:
                        try:
                            ray_tpu.get(t.actor.stop.remote(), timeout=30)
                        except Exception:  # noqa: BLE001
                            pass
                elif isinstance(decision, tuple) and \
                        decision[0] == "REALLOCATE":
                    # resource-changing scheduler: restart this trial
                    # from ITS OWN latest checkpoint with a new resource
                    # request (reference: resource_changing_scheduler.py
                    # — the trial pauses and resumes re-sized)
                    _, new_res = decision
                    own_ckpt = ckpts.get(t.trial_id)
                    if own_ckpt is None:
                        # no checkpoint to resume from yet: tell the
                        # scheduler so its allocation view rolls back
                        # and it retries later
                        if hasattr(scheduler, "on_realloc_aborted"):
                            scheduler.on_realloc_aborted(t.trial_id)
                    else:
                        try:
                            ray_tpu.kill(t.actor)
                        except Exception:  # noqa: BLE001
                            pass
                        cls_resized = ray_tpu.remote(**{
                            "num_cpus": new_res.get("CPU", 1.0),
                            "resources": {k: v for k, v in new_res.items()
                                          if k != "CPU"},
                        })(TrialActor)
                        t.actor = cls_resized.options(
                            max_concurrency=2).remote(
                                t.trial_id, fn_blob, t.config, own_ckpt)
                        t.resources = dict(new_res)
                        self._save_state(trials)
                elif isinstance(decision, tuple):
                    # PBT exploit: restart this trial from the source
                    # trial's checkpoint with the mutated config
                    # (reference: pbt.py _exploit)
                    _, source_id, new_config = decision
                    src_ckpt = ckpts.get(source_id)
                    if src_ckpt is None:
                        # no source checkpoint yet: tell the scheduler so
                        # its config view matches the unchanged trial
                        if hasattr(scheduler, "on_exploit_aborted"):
                            scheduler.on_exploit_aborted(t.trial_id)
                    else:
                        try:
                            ray_tpu.kill(t.actor)
                        except Exception:  # noqa: BLE001
                            pass
                        t.config = new_config
                        t.actor = actor_cls.options(
                            max_concurrency=2).remote(
                                t.trial_id, fn_blob, new_config, src_ckpt)
                        if hasattr(scheduler, "on_exploit_applied"):
                            scheduler.on_exploit_applied(t.trial_id)
                        self._save_state(trials)
                elif decision == STOP:
                    t.status = Trial.STOPPED
                    try:
                        ray_tpu.get(t.actor.stop.remote(), timeout=30)
                    except Exception:  # noqa: BLE001
                        pass
                if t.status != Trial.RUNNING:
                    # always reap the actor: a terminated trial's worker
                    # process would otherwise keep holding its resources
                    try:
                        ray_tpu.kill(t.actor)
                    except Exception:  # noqa: BLE001
                        pass
                    t.actor = None
                    running.remove(t)
                    scheduler.on_trial_complete(t.trial_id)
                    if searcher is not None:
                        searcher.on_trial_complete(t.trial_id, t.last_result)
                    self._save_state(trials)
            time.sleep(0.02)
        self._save_state(trials)
        results = [TuneResult(t.trial_id, t.config, t.last_result, t.error)
                   for t in trials]
        return ResultGrid(results, tc.metric, tc.mode)


def _json_safe(d: dict) -> dict:
    out = {}
    for k, v in d.items():
        try:
            json.dumps(v)
            out[k] = v
        except (TypeError, ValueError):
            out[k] = repr(v)
    return out

