"""Tuner — experiment driver over trial actors.

Reference parity: ray.tune.Tuner (tune/tuner.py:44, fit :344) driving the
TuneController event loop (tune/execution/tune_controller.py:68, step
:666): trials are actors; the controller starts up to the concurrency
limit, polls reports, consults the scheduler (ASHA early stopping), and
persists experiment state so `Tuner.restore` can finish interrupted
sweeps. Trials run as actors on the task/actor runtime — each can itself
be a JaxTrainer fit (trainer-in-trial, how Train rides Tune in the
reference, base_trainer.py:577-623)."""

from __future__ import annotations

import dataclasses
import json
import os
import queue
import threading
import time
import traceback
from typing import Any, Callable

import cloudpickle

from ray_tpu.tune.schedulers import CONTINUE, STOP, FIFOScheduler
from ray_tpu.tune.search import generate_variants

# ---------------------------------------------------------------- session

_trial_session = None


class _TrialSession:
    def __init__(self, restored_checkpoint=None):
        # small bound keeps fast trainables in rough lockstep with the
        # controller so scheduler decisions (ASHA cuts, PBT exploits)
        # apply mid-flight instead of after the trial already finished
        self.results: queue.Queue = queue.Queue(maxsize=2)
        self.iteration = 0
        self.stopped = threading.Event()
        self.restored_checkpoint = restored_checkpoint
        self.latest_checkpoint = None
        self.ckpt_lock = threading.Lock()

    def report(self, metrics: dict, checkpoint=None):
        if self.stopped.is_set():
            raise _StopTrial()
        self.iteration += 1
        m = dict(metrics)
        m.setdefault("training_iteration", self.iteration)
        if checkpoint is not None:
            # PBT exploit clones this state into another trial
            # (reference: pbt.py _exploit via trial checkpoints)
            with self.ckpt_lock:
                self.latest_checkpoint = cloudpickle.dumps(checkpoint)
        while True:
            try:
                self.results.put(m, timeout=0.1)
                break
            except queue.Full:
                if self.stopped.is_set():
                    raise _StopTrial() from None


class _StopTrial(BaseException):
    """Raised inside the trainable to unwind when the scheduler stops the
    trial (BaseException so bare `except Exception` in user code doesn't
    swallow it — reference uses the session's StopIteration channel)."""


def report(metrics: dict, checkpoint=None, **kwargs):
    """ray_tpu.tune.report — inside a trainable. `checkpoint` may be any
    picklable state; PBT clones it into exploited trials."""
    if _trial_session is None:
        raise RuntimeError("tune.report() outside a trial")
    _trial_session.report(metrics, checkpoint=checkpoint)


def get_checkpoint():
    """Inside a trainable: the checkpoint this trial was (re)started from
    (None on a fresh start; set after a PBT exploit or restore)."""
    if _trial_session is None:
        raise RuntimeError("tune.get_checkpoint() outside a trial")
    return _trial_session.restored_checkpoint


class TrialActor:
    """Hosts one trial: runs the trainable on a thread, serves polling."""

    def __init__(self, trial_id: str, fn_blob: bytes, config: dict,
                 ckpt_blob: bytes | None = None):
        global _trial_session
        self.trial_id = trial_id
        restored = cloudpickle.loads(ckpt_blob) if ckpt_blob else None
        self.session = _TrialSession(restored_checkpoint=restored)
        _trial_session = self.session
        self.error: str | None = None
        self.finished = threading.Event()
        fn = cloudpickle.loads(fn_blob)

        def run():
            try:
                fn(config)
            except _StopTrial:
                pass
            except BaseException:  # noqa: BLE001
                self.error = traceback.format_exc()
            finally:
                self.finished.set()

        threading.Thread(target=run, daemon=True,
                         name=f"trial-{trial_id}").start()

    def poll(self, timeout: float = 2.0) -> dict:
        out = []
        deadline = time.monotonic() + timeout
        while True:
            try:
                out.append(self.session.results.get_nowait())
            except queue.Empty:
                if out or self.finished.is_set():
                    break
                if time.monotonic() >= deadline:
                    break
                time.sleep(0.02)
        done = self.finished.is_set() and self.session.results.empty()
        with self.session.ckpt_lock:
            ckpt = self.session.latest_checkpoint
            self.session.latest_checkpoint = None  # ship each blob once
        return {"results": out, "done": done, "error": self.error,
                "checkpoint": ckpt}

    def stop(self):
        self.session.stopped.set()
        return True


# ---------------------------------------------------------------- trials


class Trial:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    TERMINATED = "TERMINATED"
    ERROR = "ERROR"
    STOPPED = "STOPPED"  # by scheduler

    def __init__(self, trial_id: str, config: dict):
        self.trial_id = trial_id
        self.config = config
        self.status = Trial.PENDING
        self.last_result: dict = {}
        self.error: str | None = None
        self.actor = None

    def to_json(self) -> dict:
        return {"trial_id": self.trial_id, "config": _json_safe(self.config),
                "status": self.status, "last_result": _json_safe(self.last_result),
                "error": self.error}


@dataclasses.dataclass
class TuneConfig:
    """Reference: ray.tune.TuneConfig."""

    metric: str | None = None
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: int | None = None
    scheduler: Any = None
    seed: int | None = None
    trial_resources: dict[str, float] | None = None


@dataclasses.dataclass
class TuneResult:
    trial_id: str
    config: dict
    metrics: dict
    error: str | None = None


class ResultGrid:
    """Reference: ray.tune.ResultGrid."""

    def __init__(self, results: list[TuneResult], metric, mode):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i):
        return self._results[i]

    @property
    def errors(self):
        return [r for r in self._results if r.error]

    def get_best_result(self, metric: str | None = None,
                        mode: str | None = None) -> TuneResult:
        metric = metric or self._metric
        mode = mode or self._mode
        scored = [r for r in self._results
                  if r.error is None and metric in r.metrics]
        if not scored:
            raise ValueError("no successful trial reported "
                             f"metric {metric!r}")
        return (max if mode == "max" else min)(
            scored, key=lambda r: r.metrics[metric])

    def get_dataframe(self):
        rows = [{"trial_id": r.trial_id, **r.metrics,
                 **{f"config/{k}": v for k, v in r.config.items()}}
                for r in self._results]
        try:
            import pandas as pd

            return pd.DataFrame(rows)
        except ImportError:
            return rows


# ---------------------------------------------------------------- tuner


class Tuner:
    def __init__(self, trainable: Callable, *, param_space: dict | None = None,
                 tune_config: TuneConfig | None = None,
                 run_config=None):
        from ray_tpu.train.trainer import RunConfig

        self._trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._restored_trials: list[Trial] | None = None

    # -- persistence -----------------------------------------------------

    def _exp_dir(self) -> str:
        name = self.run_config.name or "tune_experiment"
        storage = self.run_config.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results")
        return os.path.join(storage, name)

    def _save_state(self, trials: list[Trial]):
        d = self._exp_dir()
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, ".tuner_state.json.tmp")
        with open(tmp, "w") as f:
            json.dump({"trials": [t.to_json() for t in trials]}, f)
        os.replace(tmp, os.path.join(d, "tuner_state.json"))

    @classmethod
    def restore(cls, path: str, trainable: Callable) -> "Tuner":
        """Resume an interrupted experiment: finished trials keep their
        recorded results, unfinished ones run again (reference:
        Tuner.restore, tune/tuner.py)."""
        with open(os.path.join(path, "tuner_state.json")) as f:
            state = json.load(f)
        tuner = cls(trainable)
        tuner.run_config.name = os.path.basename(path.rstrip("/"))
        tuner.run_config.storage_path = os.path.dirname(path.rstrip("/"))
        trials = []
        for tj in state["trials"]:
            t = Trial(tj["trial_id"], tj["config"])
            t.status = tj["status"]
            t.last_result = tj["last_result"]
            t.error = tj.get("error")
            if t.status in (Trial.PENDING, Trial.RUNNING):
                t.status = Trial.PENDING  # rerun interrupted trials
            trials.append(t)
        tuner._restored_trials = trials
        return tuner

    # -- fit -------------------------------------------------------------

    def fit(self) -> ResultGrid:
        import ray_tpu

        tc = self.tune_config
        scheduler = tc.scheduler or FIFOScheduler()
        if hasattr(scheduler, "set_objective") and tc.metric:
            scheduler.set_objective(tc.metric, tc.mode)
        if self._restored_trials is not None:
            trials = self._restored_trials
        else:
            variants = generate_variants(self.param_space, tc.num_samples,
                                         tc.seed)
            trials = [Trial(f"trial_{i:05d}", cfg)
                      for i, cfg in enumerate(variants)]
        fn_blob = cloudpickle.dumps(self._trainable)
        res = dict(tc.trial_resources or {"CPU": 1.0})
        limit = tc.max_concurrent_trials or max(
            1, int(ray_tpu.cluster_resources().get("CPU", 1)))
        actor_cls = ray_tpu.remote(**{
            "num_cpus": res.get("CPU", 1.0),
            "resources": {k: v for k, v in res.items() if k != "CPU"},
        })(TrialActor)

        pending = [t for t in trials if t.status == Trial.PENDING]
        running: list[Trial] = []
        ckpts: dict[str, bytes] = {}  # trial_id -> latest checkpoint blob
        self._save_state(trials)
        while pending or running:
            while pending and len(running) < limit:
                t = pending.pop(0)
                t.actor = actor_cls.options(
                    max_concurrency=2).remote(t.trial_id, fn_blob, t.config)
                t.status = Trial.RUNNING
                running.append(t)
                if hasattr(scheduler, "on_trial_add"):
                    scheduler.on_trial_add(t.trial_id, t.config)
            refs = {t.trial_id: t.actor.poll.remote() for t in running}
            for t in list(running):
                try:
                    r = ray_tpu.get(refs[t.trial_id], timeout=120)
                except Exception as e:  # noqa: BLE001
                    t.status = Trial.ERROR
                    t.error = f"trial actor failed: {e}"
                    running.remove(t)
                    scheduler.on_trial_complete(t.trial_id)
                    continue
                if r.get("checkpoint"):
                    ckpts[t.trial_id] = r["checkpoint"]
                decision = CONTINUE
                for m in r["results"]:
                    t.last_result = m
                    d = scheduler.on_result(t.trial_id, m)
                    if d == STOP:
                        decision = STOP
                    elif isinstance(d, tuple) and d[0] == "EXPLOIT":
                        decision = d
                if r["error"]:
                    t.status = Trial.ERROR
                    t.error = r["error"]
                elif r["done"]:
                    t.status = Trial.TERMINATED
                elif isinstance(decision, tuple):
                    # PBT exploit: restart this trial from the source
                    # trial's checkpoint with the mutated config
                    # (reference: pbt.py _exploit)
                    _, source_id, new_config = decision
                    src_ckpt = ckpts.get(source_id)
                    if src_ckpt is None:
                        # no source checkpoint yet: tell the scheduler so
                        # its config view matches the unchanged trial
                        if hasattr(scheduler, "on_exploit_aborted"):
                            scheduler.on_exploit_aborted(t.trial_id)
                    else:
                        try:
                            ray_tpu.kill(t.actor)
                        except Exception:  # noqa: BLE001
                            pass
                        t.config = new_config
                        t.actor = actor_cls.options(
                            max_concurrency=2).remote(
                                t.trial_id, fn_blob, new_config, src_ckpt)
                        if hasattr(scheduler, "on_exploit_applied"):
                            scheduler.on_exploit_applied(t.trial_id)
                        self._save_state(trials)
                elif decision == STOP:
                    t.status = Trial.STOPPED
                    try:
                        ray_tpu.get(t.actor.stop.remote(), timeout=30)
                    except Exception:  # noqa: BLE001
                        pass
                if t.status != Trial.RUNNING:
                    # always reap the actor: a terminated trial's worker
                    # process would otherwise keep holding its resources
                    try:
                        ray_tpu.kill(t.actor)
                    except Exception:  # noqa: BLE001
                        pass
                    t.actor = None
                    running.remove(t)
                    scheduler.on_trial_complete(t.trial_id)
                    self._save_state(trials)
            time.sleep(0.02)
        self._save_state(trials)
        results = [TuneResult(t.trial_id, t.config, t.last_result, t.error)
                   for t in trials]
        return ResultGrid(results, tc.metric, tc.mode)


def _json_safe(d: dict) -> dict:
    out = {}
    for k, v in d.items():
        try:
            json.dumps(v)
            out[k] = v
        except (TypeError, ValueError):
            out[k] = repr(v)
    return out

