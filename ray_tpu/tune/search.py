"""Search spaces + trial generation.

Reference parity: ray.tune search-space API (tune/search/sample.py —
uniform/loguniform/choice/randint, grid_search marker) and the
BasicVariantGenerator (tune/search/basic_variant.py) that crosses grid
axes and samples stochastic domains num_samples times.
"""

from __future__ import annotations

import itertools
import random
from typing import Any


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False):
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng):
        if self.log:
            import math

            return math.exp(rng.uniform(math.log(self.lower),
                                        math.log(self.upper)))
        return rng.uniform(self.lower, self.upper)


class Integer(Domain):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def choice(categories) -> Categorical:
    return Categorical(categories)


def grid_search(values) -> dict:
    return {"grid_search": list(values)}


def _is_grid(v) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


def generate_variants(param_space: dict, num_samples: int,
                      seed: int | None = None) -> list[dict]:
    """Cross-product of grid axes × num_samples draws of stochastic
    domains (reference: BasicVariantGenerator semantics — num_samples
    multiplies the grid)."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in param_space.items() if _is_grid(v)]
    grid_values = [param_space[k]["grid_search"] for k in grid_keys]
    combos = list(itertools.product(*grid_values)) if grid_keys else [()]
    variants = []
    for _ in range(max(1, num_samples)):
        for combo in combos:
            cfg = {}
            for k, v in param_space.items():
                if k in grid_keys:
                    cfg[k] = combo[grid_keys.index(k)]
                elif isinstance(v, Domain):
                    cfg[k] = v.sample(rng)
                else:
                    cfg[k] = v
            variants.append(cfg)
    return variants
