"""Search spaces + trial generation.

Reference parity: ray.tune search-space API (tune/search/sample.py —
uniform/loguniform/choice/randint, grid_search marker) and the
BasicVariantGenerator (tune/search/basic_variant.py) that crosses grid
axes and samples stochastic domains num_samples times.
"""

from __future__ import annotations

import itertools
import random
from typing import Any


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False):
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng):
        if self.log:
            import math

            return math.exp(rng.uniform(math.log(self.lower),
                                        math.log(self.upper)))
        return rng.uniform(self.lower, self.upper)


class Integer(Domain):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def choice(categories) -> Categorical:
    return Categorical(categories)


def grid_search(values) -> dict:
    return {"grid_search": list(values)}


def _is_grid(v) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


class Searcher:
    """Model-based search algorithm ABC (reference:
    tune/search/searcher.py Searcher — suggest/on_trial_complete). The
    Tuner asks `suggest` for each new trial's config and feeds the final
    metric back through `on_trial_complete`, so the searcher can
    condition later draws on earlier results (unlike the stateless
    BasicVariantGenerator path)."""

    def set_objective(self, metric: str, mode: str):
        self.metric = getattr(self, "metric", None) or metric
        self.mode = getattr(self, "mode", None) or mode

    def suggest(self, trial_id: str) -> dict | None:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: dict):
        pass

    def on_trial_complete(self, trial_id: str, result: dict | None = None):
        pass


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator (reference role:
    tune/search/optuna/optuna_search.py, whose default sampler is TPE —
    Bergstra et al. 2011). Dependency-free implementation:

    - first `n_initial` trials are random draws;
    - afterwards, observations are split into the top `gamma` fraction
      ("good") and the rest ("bad"); per dimension a Parzen KDE is built
      over each split, candidates are drawn from the good KDE and ranked
      by the density ratio l(x)/g(x); the best candidate wins.

    Supports Float (linear/log), Integer, and Categorical domains; plain
    values pass through untouched.
    """

    def __init__(self, space: dict, metric: str | None = None,
                 mode: str | None = None, n_initial: int = 10,
                 gamma: float = 0.25, n_candidates: int = 24,
                 seed: int | None = None):
        self.space = dict(space)
        for k, v in self.space.items():
            if _is_grid(v):
                raise ValueError(
                    f"grid_search({k!r}) is incompatible with TPESearcher; "
                    "use choice() instead")
        self.metric = metric
        self.mode = mode
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._suggested: dict[str, dict] = {}
        self._observed: list[tuple[dict, float]] = []

    # -- observation ------------------------------------------------------

    def on_trial_complete(self, trial_id: str, result: dict | None = None):
        cfg = self._suggested.pop(trial_id, None)
        if cfg is None or not result:
            return
        value = result.get(self.metric)
        if value is None:
            return
        score = float(value) if self.mode == "min" else -float(value)
        self._observed.append((cfg, score))

    # -- suggestion -------------------------------------------------------

    def suggest(self, trial_id: str) -> dict:
        if len(self._observed) < self.n_initial:
            cfg = self._sample_random()
        else:
            cfg = self._sample_tpe()
        self._suggested[trial_id] = cfg
        return dict(cfg)

    def _sample_random(self) -> dict:
        return {k: (v.sample(self._rng) if isinstance(v, Domain) else v)
                for k, v in self.space.items()}

    def _split(self):
        ranked = sorted(self._observed, key=lambda o: o[1])
        n_good = max(1, int(len(ranked) * self.gamma))
        return ranked[:n_good], ranked[n_good:]

    def _sample_tpe(self) -> dict:
        import math

        good, bad = self._split()
        out = {}
        for k, dom in self.space.items():
            if not isinstance(dom, Domain):
                out[k] = dom
                continue
            if isinstance(dom, Categorical):
                out[k] = self._tpe_categorical(k, dom, good, bad)
                continue
            log = isinstance(dom, Float) and dom.log
            to_x = (lambda v: math.log(v)) if log else (lambda v: float(v))
            lo, hi = to_x(dom.lower), to_x(dom.upper)
            gx = [to_x(c[k]) for c, _ in good]
            bx = [to_x(c[k]) for c, _ in bad] or gx
            # Parzen bandwidth: Silverman-flavored, floored to a fraction
            # of the range so early KDEs stay explorative
            def kde(xs, x):
                bw = max((hi - lo) / 12.0,
                         1.06 * (_std(xs) or (hi - lo)) *
                         max(len(xs), 1) ** -0.2)
                return sum(math.exp(-0.5 * ((x - xi) / bw) ** 2)
                           for xi in xs) / (len(xs) * bw) + 1e-12
            best_x, best_ratio = None, -1.0
            for _ in range(self.n_candidates):
                # draw from the good KDE: pick an anchor, jitter by bw
                anchor = self._rng.choice(gx)
                bw = max((hi - lo) / 12.0,
                         1.06 * (_std(gx) or (hi - lo)) *
                         max(len(gx), 1) ** -0.2)
                x = min(hi, max(lo, self._rng.gauss(anchor, bw)))
                ratio = kde(gx, x) / kde(bx, x)
                if ratio > best_ratio:
                    best_x, best_ratio = x, ratio
            v = math.exp(best_x) if log else best_x
            if isinstance(dom, Integer):
                v = min(dom.upper - 1, max(dom.lower, int(round(v))))
            out[k] = v
        return out

    def _tpe_categorical(self, k, dom, good, bad):
        cats = dom.categories
        # smoothed count ratio good/bad per category
        gcount = {c: 1.0 for c in cats}
        bcount = {c: 1.0 for c in cats}
        for cfg, _ in good:
            gcount[cfg[k]] = gcount.get(cfg[k], 1.0) + 1.0
        for cfg, _ in bad:
            bcount[cfg[k]] = bcount.get(cfg[k], 1.0) + 1.0
        scores = [gcount[c] / bcount[c] for c in cats]
        total = sum(scores)
        r = self._rng.random() * total
        acc = 0.0
        for c, s in zip(cats, scores):
            acc += s
            if r <= acc:
                return c
        return cats[-1]


def _std(xs):
    if len(xs) < 2:
        return 0.0
    m = sum(xs) / len(xs)
    return (sum((x - m) ** 2 for x in xs) / (len(xs) - 1)) ** 0.5


def generate_variants(param_space: dict, num_samples: int,
                      seed: int | None = None) -> list[dict]:
    """Cross-product of grid axes × num_samples draws of stochastic
    domains (reference: BasicVariantGenerator semantics — num_samples
    multiplies the grid)."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in param_space.items() if _is_grid(v)]
    grid_values = [param_space[k]["grid_search"] for k in grid_keys]
    combos = list(itertools.product(*grid_values)) if grid_keys else [()]
    variants = []
    for _ in range(max(1, num_samples)):
        for combo in combos:
            cfg = {}
            for k, v in param_space.items():
                if k in grid_keys:
                    cfg[k] = combo[grid_keys.index(k)]
                elif isinstance(v, Domain):
                    cfg[k] = v.sample(rng)
                else:
                    cfg[k] = v
            variants.append(cfg)
    return variants
