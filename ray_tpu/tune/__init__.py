"""ray_tpu.tune — hyperparameter sweeps over trial actors.

Reference parity: ray.tune (python/ray/tune/) — Tuner.fit over actor
trials with search spaces, random/grid generation, ASHA early stopping,
Population Based Training (checkpoint exploit + hyperparam explore),
median stopping, and on-disk experiment state with restore.
"""

from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
)
from ray_tpu.tune.search import (
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from ray_tpu.tune.tuner import (
    ResultGrid,
    TuneConfig,
    Tuner,
    TuneResult,
    get_checkpoint,
    report,
)

__all__ = [
    "ASHAScheduler",
    "FIFOScheduler",
    "MedianStoppingRule",
    "PopulationBasedTraining",
    "ResultGrid",
    "TuneConfig",
    "TuneResult",
    "Tuner",
    "choice",
    "get_checkpoint",
    "grid_search",
    "loguniform",
    "randint",
    "report",
    "uniform",
]
