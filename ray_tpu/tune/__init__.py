"""ray_tpu.tune — hyperparameter sweeps over trial actors.

Reference parity: ray.tune (python/ray/tune/) — Tuner.fit over actor
trials with search spaces, random/grid generation, ASHA early stopping,
Population Based Training (checkpoint exploit + hyperparam explore),
median stopping, and on-disk experiment state with restore.
"""

from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
    ResourceChangingScheduler,
)
from ray_tpu.tune.search import (
    Searcher,
    TPESearcher,
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from ray_tpu.tune.trainable import Trainable
from ray_tpu.tune.tuner import (
    ResultGrid,
    TuneConfig,
    Tuner,
    TuneResult,
    get_checkpoint,
    report,
)

__all__ = [
    "ASHAScheduler",
    "FIFOScheduler",
    "MedianStoppingRule",
    "PB2",
    "PopulationBasedTraining",
    "ResourceChangingScheduler",
    "ResultGrid",
    "Searcher",
    "TPESearcher",
    "Trainable",
    "TuneConfig",
    "TuneResult",
    "Tuner",
    "choice",
    "get_checkpoint",
    "grid_search",
    "loguniform",
    "randint",
    "report",
    "uniform",
]
