"""ray_tpu.tune — hyperparameter sweeps over trial actors.

Reference parity: ray.tune (python/ray/tune/) — Tuner.fit over actor
trials with search spaces, random/grid generation, ASHA early stopping,
and on-disk experiment state with restore.
"""

from ray_tpu.tune.schedulers import ASHAScheduler, FIFOScheduler
from ray_tpu.tune.search import (
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from ray_tpu.tune.tuner import (
    ResultGrid,
    TuneConfig,
    Tuner,
    TuneResult,
    report,
)

__all__ = [
    "ASHAScheduler",
    "FIFOScheduler",
    "ResultGrid",
    "TuneConfig",
    "TuneResult",
    "Tuner",
    "choice",
    "grid_search",
    "loguniform",
    "randint",
    "report",
    "uniform",
]
