"""Trainable — the class-based trial API.

Reference parity: ray.tune.Trainable (tune/trainable/trainable.py:58):
subclasses implement `setup(config)`, `step()`, `save_checkpoint()`,
`load_checkpoint(state)`; the framework drives `train()` which wraps one
`step()` with iteration bookkeeping. Tune runs a Trainable subclass as a
trial by looping train() and shipping `save_checkpoint()` blobs through
the session, so schedulers (ASHA stop, PBT/PB2 exploit) can pause a
trial and any restart resumes from the last checkpoint instead of from
scratch.
"""

from __future__ import annotations

import time


class Trainable:
    """Subclass and implement setup/step/save_checkpoint/load_checkpoint.

    Unlike the function-trainable (which calls `tune.report` itself), the
    class API inverts control: the trial loop calls `train()` repeatedly
    and persists checkpoints between steps.
    """

    def __init__(self, config: dict | None = None):
        self.config = config or {}
        self._iteration = 0
        self._time_total = 0.0
        self.setup(self.config)

    # -- subclass surface -------------------------------------------------

    def setup(self, config: dict):
        """One-time initialization (reference: Trainable.setup)."""

    def step(self) -> dict:
        """One training iteration; returns a metrics dict (reference:
        Trainable.step — MUST be overridden)."""
        raise NotImplementedError

    def save_checkpoint(self) -> dict:
        """Return picklable state capturing everything `load_checkpoint`
        needs to resume (reference: Trainable.save_checkpoint)."""
        return {}

    def load_checkpoint(self, state: dict):
        """Restore from a `save_checkpoint` payload."""

    def cleanup(self):
        """Release resources (actors, files) at trial end."""

    # -- framework surface ------------------------------------------------

    @property
    def iteration(self) -> int:
        return self._iteration

    def train(self) -> dict:
        """One step + bookkeeping (reference: Trainable.train :331 wraps
        step with iteration/time accounting)."""
        t0 = time.perf_counter()
        result = self.step() or {}
        dt = time.perf_counter() - t0
        self._iteration += 1
        self._time_total += dt
        result.setdefault("training_iteration", self._iteration)
        result.setdefault("time_this_iter_s", dt)
        result.setdefault("time_total_s", self._time_total)
        return result

    def stop(self):
        self.cleanup()

    # -- session bridging (used by the Tuner's class-trainable driver) ---

    def _full_state(self) -> dict:
        return {"__trainable__": self.save_checkpoint(),
                "__iteration__": self._iteration,
                "__time_total__": self._time_total}

    def _restore_full_state(self, state: dict):
        self._iteration = int(state.get("__iteration__", 0))
        self._time_total = float(state.get("__time_total__", 0.0))
        self.load_checkpoint(state.get("__trainable__", {}))


def is_trainable_class(obj) -> bool:
    return isinstance(obj, type) and issubclass(obj, Trainable)
