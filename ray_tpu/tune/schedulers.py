"""Trial schedulers: FIFO and ASHA early stopping.

Reference parity: ray.tune.schedulers — FIFOScheduler (trial_scheduler.py)
and ASHAScheduler / AsyncSuccessiveHalving (async_hyperband.py): rungs at
grace_period * reduction_factor^k; when a trial reaches a rung, it stops
unless its metric is in the top 1/reduction_factor of results recorded at
that rung.
"""

from __future__ import annotations


CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, result: dict) -> str:
        return CONTINUE

    def on_trial_complete(self, trial_id: str):
        pass


class ASHAScheduler:
    def __init__(self, metric: str | None = None, mode: str | None = None,
                 time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4, brackets: int = 1):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        # milestones: grace, grace*rf, grace*rf^2 ... < max_t
        self.milestones: list[int] = []
        m = grace_period
        while m < max_t:
            self.milestones.append(m)
            m *= reduction_factor
        # rung -> list of recorded metric values
        self._rungs: dict[int, list[float]] = {m: [] for m in self.milestones}
        self._trial_progress: dict[str, int] = {}

    def set_objective(self, metric: str, mode: str):
        self.metric = self.metric or metric
        self.mode = self.mode or mode

    def on_result(self, trial_id: str, result: dict) -> str:
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        decision = CONTINUE
        for m in self.milestones:
            if self._trial_progress.get(trial_id, 0) < m <= t:
                rung = self._rungs[m]
                rung.append(float(value))
                if not self._in_top_fraction(float(value), rung):
                    decision = STOP
        self._trial_progress[trial_id] = t
        return decision

    def _in_top_fraction(self, value: float, rung: list[float]) -> bool:
        if len(rung) < self.rf:
            return True  # not enough evidence to cut yet
        ranked = sorted(rung, reverse=(self.mode == "max"))
        k = max(1, len(ranked) // self.rf)
        cutoff = ranked[k - 1]
        return value >= cutoff if self.mode == "max" else value <= cutoff

    def on_trial_complete(self, trial_id: str):
        self._trial_progress.pop(trial_id, None)
