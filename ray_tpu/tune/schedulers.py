"""Trial schedulers: FIFO and ASHA early stopping.

Reference parity: ray.tune.schedulers — FIFOScheduler (trial_scheduler.py)
and ASHAScheduler / AsyncSuccessiveHalving (async_hyperband.py): rungs at
grace_period * reduction_factor^k; when a trial reaches a rung, it stops
unless its metric is in the top 1/reduction_factor of results recorded at
that rung.
"""

from __future__ import annotations


CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, result: dict) -> str:
        return CONTINUE

    def on_trial_complete(self, trial_id: str):
        pass


class ASHAScheduler:
    def __init__(self, metric: str | None = None, mode: str | None = None,
                 time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4, brackets: int = 1):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        # milestones: grace, grace*rf, grace*rf^2 ... < max_t
        self.milestones: list[int] = []
        m = grace_period
        while m < max_t:
            self.milestones.append(m)
            m *= reduction_factor
        # rung -> list of recorded metric values
        self._rungs: dict[int, list[float]] = {m: [] for m in self.milestones}
        self._trial_progress: dict[str, int] = {}

    def set_objective(self, metric: str, mode: str):
        self.metric = self.metric or metric
        self.mode = self.mode or mode

    def on_result(self, trial_id: str, result: dict) -> str:
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        decision = CONTINUE
        for m in self.milestones:
            if self._trial_progress.get(trial_id, 0) < m <= t:
                rung = self._rungs[m]
                rung.append(float(value))
                if not self._in_top_fraction(float(value), rung):
                    decision = STOP
        self._trial_progress[trial_id] = t
        return decision

    def _in_top_fraction(self, value: float, rung: list[float]) -> bool:
        if len(rung) < self.rf:
            return True  # not enough evidence to cut yet
        ranked = sorted(rung, reverse=(self.mode == "max"))
        k = max(1, len(ranked) // self.rf)
        cutoff = ranked[k - 1]
        return value >= cutoff if self.mode == "max" else value <= cutoff

    def on_trial_complete(self, trial_id: str):
        self._trial_progress.pop(trial_id, None)


class MedianStoppingRule:
    """Stop a trial whose running-average metric at step t is worse than
    the median of the other trials' running averages at t (reference:
    ray.tune.schedulers.MedianStoppingRule, median_stopping_rule.py)."""

    def __init__(self, metric: str | None = None, mode: str | None = None,
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._sums: dict[str, float] = {}
        self._counts: dict[str, int] = {}

    def set_objective(self, metric: str, mode: str):
        self.metric = self.metric or metric
        self.mode = self.mode or mode

    def on_result(self, trial_id: str, result: dict) -> str:
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        self._sums[trial_id] = self._sums.get(trial_id, 0.0) + float(value)
        self._counts[trial_id] = self._counts.get(trial_id, 0) + 1
        if t <= self.grace_period:
            return CONTINUE
        others = [self._sums[k] / self._counts[k]
                  for k in self._sums if k != trial_id]
        if len(others) < self.min_samples:
            return CONTINUE
        med = sorted(others)[len(others) // 2]
        mine = self._sums[trial_id] / self._counts[trial_id]
        worse = mine < med if self.mode == "max" else mine > med
        return STOP if worse else CONTINUE

    def on_trial_complete(self, trial_id: str):
        pass


class PopulationBasedTraining:
    """PBT: bottom-quantile trials clone a top-quantile trial's checkpoint
    and perturb its hyperparams (reference:
    ray.tune.schedulers.pbt.PopulationBasedTraining, pbt.py:221 —
    _checkpoint_or_exploit / _exploit / explore).

    The Tuner acts on the ("EXPLOIT", source_trial_id, new_config)
    decision by restarting the trial's actor from the source trial's
    latest reported checkpoint with the mutated config.
    """

    def __init__(self, time_attr: str = "training_iteration",
                 metric: str | None = None, mode: str | None = None,
                 perturbation_interval: int = 5,
                 hyperparam_mutations: dict | None = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 perturbation_factors=(1.2, 0.8),
                 seed: int | None = None):
        import random

        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = dict(hyperparam_mutations or {})
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self.factors = perturbation_factors
        self._rng = random.Random(seed)
        self._scores: dict[str, float] = {}   # latest metric per trial
        self._configs: dict[str, dict] = {}
        self._last_perturb: dict[str, int] = {}
        self._pending_exploit: dict[str, tuple] = {}
        self.exploit_count = 0  # observability / tests

    def set_objective(self, metric: str, mode: str):
        self.metric = self.metric or metric
        self.mode = self.mode or mode

    def on_trial_add(self, trial_id: str, config: dict):
        self._configs[trial_id] = dict(config)

    def on_result(self, trial_id: str, result: dict):
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        self._scores[trial_id] = float(value)
        if t - self._last_perturb.get(trial_id, 0) < self.interval:
            return CONTINUE
        prev_perturb = self._last_perturb.get(trial_id, 0)
        self._last_perturb[trial_id] = t
        lower, upper = self._quantiles()
        if trial_id not in lower or not upper:
            return CONTINUE
        source = self._rng.choice(upper)
        new_config = self._explore(self._configs.get(source, {}))
        # remember pre-exploit state: the Tuner aborts the exploit when
        # the source has no checkpoint yet, and scheduler state must then
        # match the trial's ACTUAL (unchanged) config
        self._pending_exploit[trial_id] = (
            dict(self._configs.get(trial_id, {})), prev_perturb)
        self._configs[trial_id] = dict(new_config)
        self.exploit_count += 1
        return ("EXPLOIT", source, new_config)

    def on_exploit_applied(self, trial_id: str):
        self._pending_exploit.pop(trial_id, None)

    def on_exploit_aborted(self, trial_id: str):
        """The Tuner could not apply the exploit (no source checkpoint):
        roll back config + perturbation clock."""
        saved = self._pending_exploit.pop(trial_id, None)
        if saved is not None:
            old_config, old_perturb = saved
            self._configs[trial_id] = old_config
            self._last_perturb[trial_id] = old_perturb
            self.exploit_count -= 1

    def _quantiles(self):
        """(bottom, top) trial-id lists by latest score."""
        if len(self._scores) < 2:
            return [], []
        ranked = sorted(self._scores, key=self._scores.get,
                        reverse=(self.mode == "max"))
        k = max(1, int(len(ranked) * self.quantile))
        return ranked[-k:], ranked[:k]

    def _explore(self, config: dict) -> dict:
        out = dict(config)
        for key, spec in self.mutations.items():
            if isinstance(spec, (list, tuple)):
                out[key] = self._rng.choice(list(spec))
                continue
            if callable(spec):
                out[key] = spec()
                continue
            cur = out.get(key)
            if isinstance(cur, (int, float)) and \
                    self._rng.random() >= self.resample_prob:
                out[key] = cur * self._rng.choice(self.factors)
                if isinstance(cur, int):
                    out[key] = max(1, int(out[key]))
        return out

    def on_trial_complete(self, trial_id: str):
        self._scores.pop(trial_id, None)


class PB2(PopulationBasedTraining):
    """Population Based Bandits (reference:
    ray.tune.schedulers.pb2.PB2, tune/schedulers/pb2.py — Parker-Holder
    et al. 2020): PBT's exploit step kept, but the EXPLORE step replaced
    by a GP-bandit. Observed (config, reward-change) pairs fit a GP; the
    new config maximizes UCB mean + kappa*std over `hyperparam_bounds`,
    so the population searches the continuous box directly instead of
    multiplying current values by fixed factors — which is what lets PB2
    escape a bad initialization PBT would only crawl away from.
    """

    def __init__(self, time_attr: str = "training_iteration",
                 metric: str | None = None, mode: str | None = None,
                 perturbation_interval: int = 5,
                 hyperparam_bounds: dict | None = None,
                 quantile_fraction: float = 0.25,
                 kappa: float = 1.5, seed: int | None = None):
        super().__init__(time_attr=time_attr, metric=metric, mode=mode,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={},
                         quantile_fraction=quantile_fraction, seed=seed)
        self.bounds = dict(hyperparam_bounds or {})
        self.kappa = kappa
        # (normalized config vector, reward delta) observations
        self._gp_data: list[tuple[list[float], float]] = []
        self._last_obs: dict[str, tuple[float, float]] = {}  # t, value

    # -- data collection --------------------------------------------------

    def on_result(self, trial_id: str, result: dict):
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is not None and value is not None and self.bounds:
            prev = self._last_obs.get(trial_id)
            if prev is not None and t > prev[0]:
                delta = (float(value) - prev[1]) / (t - prev[0])
                if self.mode == "min":
                    delta = -delta
                vec = self._normalize(self._configs.get(trial_id, {}))
                if vec is not None:
                    self._gp_data.append((vec, delta))
                    if len(self._gp_data) > 200:
                        self._gp_data.pop(0)
            self._last_obs[trial_id] = (float(t), float(value))
        return super().on_result(trial_id, result)

    def _normalize(self, config: dict) -> list[float] | None:
        vec = []
        for k, (lo, hi) in self.bounds.items():
            v = config.get(k)
            if not isinstance(v, (int, float)):
                return None
            vec.append((float(v) - lo) / max(hi - lo, 1e-12))
        return vec

    # -- GP-UCB explore ---------------------------------------------------

    def _explore(self, config: dict) -> dict:
        out = dict(config)
        if not self.bounds:
            return out
        keys = list(self.bounds)
        cand = self._candidates(config)
        best = cand[0]
        if len(self._gp_data) >= 4:
            import numpy as np

            X = np.array([d[0] for d in self._gp_data])
            y = np.array([d[1] for d in self._gp_data])
            y = (y - y.mean()) / (y.std() + 1e-9)
            mu, sd = _gp_predict(X, y, np.array(cand))
            best = cand[int(np.argmax(mu + self.kappa * sd))]
        for i, k in enumerate(keys):
            lo, hi = self.bounds[k]
            v = lo + best[i] * (hi - lo)
            cur = config.get(k)
            out[k] = int(round(v)) if isinstance(cur, int) else v
        return out

    def _candidates(self, config: dict, n: int = 64) -> list[list[float]]:
        d = len(self.bounds)
        cand = [[self._rng.random() for _ in range(d)] for _ in range(n)]
        base = self._normalize(config)
        if base is not None:
            # local jitters around the exploited config keep exploitation
            # of a good region possible alongside global draws
            for _ in range(n // 4):
                cand.append([min(1.0, max(0.0,
                             b + self._rng.gauss(0, 0.1))) for b in base])
        return cand


class ResourceChangingScheduler:
    """Reallocate trial resources mid-flight (reference:
    ray.tune.schedulers.ResourceChangingScheduler,
    resource_changing_scheduler.py — wraps a base scheduler; a
    resources_allocation_function decides each trial's new allocation
    from the population's results). The Tuner acts on the
    ("REALLOCATE", resources) decision by restarting the trial's actor
    from its latest checkpoint with the new resource request — the same
    checkpoint-restart machinery PBT's exploit uses.

    The default allocation function is DistributeResourcesToTopJob-
    shaped: the current best trial gets `top_cpus`, everyone else
    `base_cpus`."""

    def __init__(self, base_scheduler=None,
                 resources_allocation_function=None,
                 reallocation_interval: int = 4,
                 time_attr: str = "training_iteration",
                 base_cpus: float = 1.0, top_cpus: float = 2.0,
                 metric: str | None = None, mode: str | None = None):
        self.base = base_scheduler or FIFOScheduler()
        self.fn = resources_allocation_function
        self.interval = reallocation_interval
        self.time_attr = time_attr
        self.base_cpus = base_cpus
        self.top_cpus = top_cpus
        self.metric = metric
        self.mode = mode
        self._scores: dict[str, float] = {}
        self._alloc: dict[str, float] = {}  # current CPUs per trial
        self._last_realloc: dict[str, int] = {}
        self.realloc_count = 0

    def set_objective(self, metric: str, mode: str):
        self.metric = self.metric or metric
        self.mode = self.mode or mode
        if hasattr(self.base, "set_objective"):
            self.base.set_objective(metric, mode)

    def on_trial_add(self, trial_id: str, config: dict):
        self._alloc.setdefault(trial_id, self.base_cpus)
        if hasattr(self.base, "on_trial_add"):
            self.base.on_trial_add(trial_id, config)

    def on_trial_complete(self, trial_id: str):
        self._scores.pop(trial_id, None)
        self._alloc.pop(trial_id, None)
        self.base.on_trial_complete(trial_id)

    def _default_allocation(self, trial_id: str) -> dict | None:
        if len(self._scores) < 2:
            return None
        best = (max if self.mode == "max" else min)(
            self._scores, key=self._scores.get)
        want = self.top_cpus if trial_id == best else self.base_cpus
        if abs(self._alloc.get(trial_id, self.base_cpus) - want) < 1e-9:
            return None  # unchanged: no restart
        return {"CPU": want}

    def on_result(self, trial_id: str, result: dict):
        value = result.get(self.metric)
        if value is not None:
            self._scores[trial_id] = float(value)
        d = self.base.on_result(trial_id, result)
        if d != CONTINUE:
            return d
        t = result.get(self.time_attr)
        if t is None or \
                t - self._last_realloc.get(trial_id, 0) < self.interval:
            return CONTINUE
        self._last_realloc[trial_id] = t
        new_res = (self.fn(trial_id, dict(self._scores),
                           dict(self._alloc))
                   if self.fn else self._default_allocation(trial_id))
        if not new_res:
            return CONTINUE
        self._pending_realloc = (trial_id,
                                 self._alloc.get(trial_id, self.base_cpus),
                                 self._last_realloc[trial_id])
        self._alloc[trial_id] = new_res.get("CPU", self.base_cpus)
        self.realloc_count += 1
        return ("REALLOCATE", new_res)

    def on_realloc_aborted(self, trial_id: str):
        """The Tuner could not resize (no checkpoint yet): roll back the
        allocation view and the interval clock so a later report retries
        instead of believing the resize happened."""
        pending = getattr(self, "_pending_realloc", None)
        if pending is not None and pending[0] == trial_id:
            _, old_alloc, old_t = pending
            self._alloc[trial_id] = old_alloc
            # rewind the clock so the next report past the interval
            # fires again
            self._last_realloc[trial_id] = old_t - self.interval
            self.realloc_count -= 1
            self._pending_realloc = None


def _gp_predict(X, y, Xq, lengthscale: float = 0.3, noise: float = 1e-2):
    """RBF-kernel GP posterior mean/std at query points (inputs already
    normalized to [0,1]^d)."""
    import numpy as np

    def k(A, B):
        d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / lengthscale ** 2)

    K = k(X, X) + noise * np.eye(len(X))
    L = np.linalg.cholesky(K)
    alpha = np.linalg.solve(L.T, np.linalg.solve(L, y))
    Ks = k(Xq, X)
    mu = Ks @ alpha
    v = np.linalg.solve(L, Ks.T)
    var = np.clip(1.0 - (v ** 2).sum(0), 1e-9, None)
    return mu, np.sqrt(var)
