"""Cluster launcher — boot a cluster from a YAML config.

Reference parity: `ray up` / `ray down` / `ray attach` / `ray exec`
(python/ray/scripts/scripts.py:1383), the NodeUpdater that drives each
node through UNINITIALIZED → SETTING-UP → RUNNING
(autoscaler/_private/updater.py), and the command-runner seam that
abstracts "run a command on that node" (command_runner.py — SSH for real
clouds, subprocess for the local provider). The local provider boots
head + workers as detached `ray_tpu start` subprocesses on one box — the
same path a cloud provider drives over SSH — and the cluster state file
lets `down`, `exec`, and the v2 autoscaler find the nodes later.

YAML schema (reference: autoscaler/ray-schema.json, trimmed):

    cluster_name: demo
    max_workers: 4
    provider: {type: local}            # or gcp_tpu
    auth: {ssh_user: ubuntu}           # ssh provider path
    head_node_type: head
    available_node_types:
      head:   {resources: {CPU: 2}, min_workers: 0, max_workers: 0}
      worker: {resources: {CPU: 1}, min_workers: 2, max_workers: 4}
    initialization_commands: []        # once per node, before start
    setup_commands: []                 # env prep (pip installs, ...)
"""

from __future__ import annotations

import json
import os
import shlex
import signal
import subprocess
import sys
import time

_STATE_DIR = "/tmp/ray_tpu/clusters"

UNINITIALIZED = "UNINITIALIZED"
SETTING_UP = "SETTING-UP"
RUNNING = "RUNNING"
UPDATE_FAILED = "UPDATE-FAILED"
TERMINATED = "TERMINATED"


def load_cluster_config(path: str) -> dict:
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f)
    cfg.setdefault("cluster_name", "default")
    cfg.setdefault("provider", {"type": "local"})
    cfg.setdefault("max_workers", 4)
    cfg.setdefault("available_node_types", {
        "head": {"resources": {"CPU": 1.0}, "min_workers": 0},
        "worker": {"resources": {"CPU": 1.0}, "min_workers": 0},
    })
    cfg.setdefault("head_node_type",
                   next(iter(cfg["available_node_types"])))
    cfg.setdefault("initialization_commands", [])
    cfg.setdefault("setup_commands", [])
    return cfg


# ------------------------------------------------------ command runners


class CommandRunner:
    """Run shell commands "on a node" (reference: command_runner.py
    CommandRunnerInterface)."""

    def run(self, cmd: str, timeout: float = 120.0) -> int:
        raise NotImplementedError

    def run_daemon(self, cmd: str, log_path: str) -> int:
        """Start a long-lived process; returns its pid."""
        raise NotImplementedError


class SubprocessCommandRunner(CommandRunner):
    """The local "SSH seam": commands execute on this box via
    subprocess — exactly what the SSH runner does remotely, minus the
    transport (reference: fake_multi_node + LocalNodeProvider)."""

    def __init__(self, env: dict | None = None):
        self.env = {**os.environ, **(env or {})}

    def run(self, cmd: str, timeout: float = 120.0) -> int:
        return subprocess.run(cmd, shell=True, env=self.env,
                              timeout=timeout).returncode

    def run_daemon(self, cmd: str, log_path: str) -> int:
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        with open(log_path, "ab") as log:
            proc = subprocess.Popen(
                cmd, shell=True, env=self.env, stdout=log,
                stderr=subprocess.STDOUT, start_new_session=True)
        return proc.pid


class SSHCommandRunner(CommandRunner):
    """Real-cloud path: shell out to ssh (reference:
    command_runner.py SSHCommandRunner). Untested in this zero-egress
    image; the subprocess runner exercises the identical updater flow."""

    def __init__(self, ip: str, ssh_user: str = "root",
                 ssh_private_key: str | None = None):
        self.ip = ip
        base = ["ssh", "-o", "StrictHostKeyChecking=no",
                "-o", "ConnectTimeout=10"]
        if ssh_private_key:
            base += ["-i", ssh_private_key]
        self._ssh = base + [f"{ssh_user}@{ip}"]

    def run(self, cmd: str, timeout: float = 120.0) -> int:
        return subprocess.run(self._ssh + [cmd], timeout=timeout).returncode

    def run_daemon(self, cmd: str, log_path: str) -> int:
        wrapped = f"nohup {cmd} > {shlex.quote(log_path)} 2>&1 & echo $!"
        out = subprocess.run(self._ssh + [wrapped], capture_output=True,
                             text=True, timeout=30)
        try:
            return int(out.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError):
            return -1


# --------------------------------------------------------- node updater


class NodeUpdater:
    """Drive one node to RUNNING (reference: updater.py NodeUpdater.run
    — init commands, setup commands, then the start-ray command)."""

    def __init__(self, node_name: str, runner: CommandRunner,
                 init_commands: list[str], setup_commands: list[str]):
        self.node_name = node_name
        self.runner = runner
        self.init_commands = list(init_commands)
        self.setup_commands = list(setup_commands)
        self.status = UNINITIALIZED

    def update(self, start_cmd: str, log_path: str) -> int:
        """Returns the daemon pid, or raises on a failed phase."""
        self.status = SETTING_UP
        for cmd in self.init_commands + self.setup_commands:
            rc = self.runner.run(cmd)
            if rc != 0:
                self.status = UPDATE_FAILED
                raise RuntimeError(
                    f"node {self.node_name}: setup command failed "
                    f"(rc={rc}): {cmd}")
        pid = self.runner.run_daemon(start_cmd, log_path)
        self.status = RUNNING
        return pid


# ------------------------------------------------------------ up / down


def _state_path(cluster_name: str, state_dir: str | None = None) -> str:
    d = state_dir or _STATE_DIR
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f"{cluster_name}.json")


def _save_state(state: dict, state_dir: str | None = None):
    path = _state_path(state["cluster_name"], state_dir)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f, indent=1)
    os.replace(tmp, path)


def load_state(cluster_name: str, state_dir: str | None = None) -> dict:
    with open(_state_path(cluster_name, state_dir)) as f:
        return json.load(f)


def _wait_for_file(path: str, timeout: float = 60.0) -> str:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                return f.read().strip()
        time.sleep(0.2)
    raise TimeoutError(f"timed out waiting for {path}")


def _start_cmd(node_type: str, spec: dict, *, head: bool,
               head_address: str | None, session_dir: str,
               address_file: str | None, info_file: str) -> str:
    res = dict(spec.get("resources", {}))
    cpus = res.pop("CPU", 1.0)
    parts = [shlex.quote(sys.executable), "-m", "ray_tpu.scripts.cli",
             "start", "--num-cpus", str(cpus),
             "--session-dir", shlex.quote(session_dir),
             "--node-info-file", shlex.quote(info_file),
             "--labels", shlex.quote(json.dumps(
                 {"ray_tpu.node_type": node_type}))]
    if res:
        parts += ["--resources", shlex.quote(json.dumps(res))]
    if head:
        parts += ["--head", "--address-file", shlex.quote(address_file)]
    else:
        parts += ["--address", shlex.quote(head_address)]
    return " ".join(parts)


def up(config: dict, state_dir: str | None = None,
       runner_factory=None) -> dict:
    """Boot head + min_workers from a config dict (reference: ray up —
    scripts.py:1383 calling create_or_update_cluster). Returns the
    cluster state (head address, node pids)."""
    name = config["cluster_name"]
    provider_type = config.get("provider", {}).get("type", "local")
    if provider_type not in ("local", "gcp_tpu"):
        raise ValueError(f"unknown provider type {provider_type!r}")
    base = os.path.join(state_dir or _STATE_DIR, name)
    os.makedirs(base, exist_ok=True)
    runner_factory = runner_factory or (
        lambda node_name: SubprocessCommandRunner())

    head_type = config["head_node_type"]
    types = config["available_node_types"]
    state = {"cluster_name": name, "state_dir": state_dir,
             "head": None, "workers": [], "config": config}

    # -- head -------------------------------------------------------------
    addr_file = os.path.join(base, "head_address")
    info_file = os.path.join(base, "head_info.json")
    for stale in (addr_file, info_file):
        if os.path.exists(stale):
            os.remove(stale)
    updater = NodeUpdater("head", runner_factory("head"),
                          config["initialization_commands"],
                          config["setup_commands"])
    head_cmd = _start_cmd(head_type, types[head_type], head=True,
                          head_address=None,
                          session_dir=os.path.join(base, "head"),
                          address_file=addr_file, info_file=info_file)
    head_pid = updater.update(head_cmd, os.path.join(base, "head.log"))
    head_address = _wait_for_file(addr_file)
    head_info = json.loads(_wait_for_file(info_file))
    state["head"] = {"pid": head_pid, "address": head_address,
                     "node_type": head_type, "status": updater.status,
                     "node_id_hex": head_info["node_id_hex"]}
    _save_state(state, state_dir)

    # -- workers (min_workers per type) -----------------------------------
    idx = 0
    for node_type, spec in types.items():
        n = int(spec.get("min_workers", 0))
        if node_type == head_type:
            n = 0  # the head already carries its own nodelet
        for _ in range(n):
            idx += 1
            state["workers"].append(_launch_worker(
                config, state, node_type, idx, base, head_address,
                runner_factory))
            _save_state(state, state_dir)
    return state


def _launch_worker(config: dict, state: dict, node_type: str, idx: int,
                   base: str, head_address: str, runner_factory) -> dict:
    types = config["available_node_types"]
    info_file = os.path.join(base, f"worker{idx}_info.json")
    if os.path.exists(info_file):
        os.remove(info_file)
    updater = NodeUpdater(f"worker{idx}", runner_factory(f"worker{idx}"),
                          config["initialization_commands"],
                          config["setup_commands"])
    cmd = _start_cmd(node_type, types[node_type], head=False,
                     head_address=head_address,
                     session_dir=os.path.join(base, f"worker{idx}"),
                     address_file=None, info_file=info_file)
    pid = updater.update(cmd, os.path.join(base, f"worker{idx}.log"))
    info = json.loads(_wait_for_file(info_file))
    return {"pid": pid, "node_type": node_type, "index": idx,
            "status": updater.status, "node_id_hex": info["node_id_hex"],
            "address": info["address"]}


def pid_alive(pid: int) -> bool:
    """True while the process actually runs — reaps it when it is our
    zombie child (launchers are usually the daemons' parent)."""
    try:
        os.waitpid(pid, os.WNOHANG)
    except ChildProcessError:
        pass
    try:
        os.kill(pid, 0)
    except OSError:
        return False
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().rsplit(")", 1)[-1].split()[0] != "Z"
    except OSError:
        return False


def _kill(pid: int, timeout: float = 10.0):
    try:
        os.killpg(pid, signal.SIGINT)
    except (ProcessLookupError, PermissionError):
        try:
            os.kill(pid, signal.SIGINT)
        except OSError:
            return
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if not pid_alive(pid):
            return
        time.sleep(0.1)
    try:
        os.killpg(pid, signal.SIGKILL)
    except OSError:
        try:
            os.kill(pid, signal.SIGKILL)
        except OSError:
            pass


def down(cluster_name: str, state_dir: str | None = None) -> dict:
    """Terminate every node of the cluster (reference: ray down —
    teardown_cluster). Workers first, head last, state file removed."""
    state = load_state(cluster_name, state_dir)
    for w in state.get("workers", []):
        _kill(w["pid"])
        w["status"] = TERMINATED
    if state.get("head"):
        _kill(state["head"]["pid"])
        state["head"]["status"] = TERMINATED
    try:
        os.remove(_state_path(cluster_name, state_dir))
    except OSError:
        pass
    return state


def exec_on_cluster(cluster_name: str, cmd: str,
                    state_dir: str | None = None) -> int:
    """Run a command against the cluster with RAY_TPU_ADDRESS exported
    (reference: ray exec)."""
    state = load_state(cluster_name, state_dir)
    env = {**os.environ, "RAY_TPU_ADDRESS": state["head"]["address"]}
    return subprocess.run(cmd, shell=True, env=env).returncode


def attach(cluster_name: str, state_dir: str | None = None) -> int:
    """Interactive shell with the cluster address exported (reference:
    ray attach — ssh into the head; locally: a subshell)."""
    return exec_on_cluster(cluster_name,
                           os.environ.get("SHELL", "/bin/sh"), state_dir)


# --------------------------------------------- autoscaler provider view


class LaunchedNodeProvider:
    """NodeProvider over a launched cluster's worker processes, so the
    v2 Reconciler adopts and manages them (reference: the local node
    provider backing `ray up` clusters). create_node launches a fresh
    worker through the same updater path `up` used."""

    def __init__(self, cluster_name: str, node_type: str = "worker",
                 state_dir: str | None = None):
        self.cluster_name = cluster_name
        self.node_type = node_type
        self.state_dir = state_dir

    def _state(self) -> dict:
        return load_state(self.cluster_name, self.state_dir)

    def non_terminated_nodes(self) -> list:
        out = []
        for w in self._state().get("workers", []):
            if w.get("status") == TERMINATED:
                continue
            if not pid_alive(w["pid"]):
                continue
            out.append(w)
        return out

    def node_id(self, handle) -> bytes:
        return bytes.fromhex(handle["node_id_hex"])

    def create_node(self, node_type: str | None = None):
        state = self._state()
        cfg = state["config"]
        base = os.path.join(self.state_dir or _STATE_DIR,
                            self.cluster_name)
        idx = max([w["index"] for w in state["workers"]], default=0) + 1
        w = _launch_worker(cfg, state, node_type or self.node_type, idx,
                           base, state["head"]["address"],
                           lambda n: SubprocessCommandRunner())
        state["workers"].append(w)
        _save_state(state, self.state_dir)
        return w

    def terminate_node(self, handle):
        state = self._state()
        _kill(handle["pid"])
        for w in state["workers"]:
            if w["index"] == handle["index"]:
                w["status"] = TERMINATED
        _save_state(state, self.state_dir)
