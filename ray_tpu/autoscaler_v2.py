"""Autoscaler v2 — declarative instance lifecycle + reconciler.

Reference parity: python/ray/autoscaler/v2/ — the v2 redesign splits
the single scale loop into (a) a versioned INSTANCE STORAGE holding a
typed per-instance state machine (instance_manager/common.py:198 —
QUEUED → REQUESTED → ALLOCATED → RAY_RUNNING → TERMINATING →
TERMINATED, with failure edges), (b) a pure SCHEDULER that turns
cluster demand into desired instances (v2/scheduler.py), and (c) a
RECONCILER that converges storage ↔ cloud-provider ↔ ray-cluster views
idempotently every tick, with stuck-state timeouts
(instance_manager/reconciler.py). The v1 loop (`autoscaler.py`) stays
for simple deployments; v2 is the operator-grade path: every decision
is recorded as a versioned instance transition you can inspect, and a
crashed autoscaler resumes from storage instead of re-deriving state.

The same NodeProvider ABC drives both (create_node/terminate_node —
including GCPTPUNodeProvider's whole-slice semantics).
"""

from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from typing import Any, Callable

# ---------------------------------------------------------------- states

QUEUED = "QUEUED"  # demanded, not yet requested from the provider
REQUESTED = "REQUESTED"  # provider asked; waiting for the node
ALLOCATED = "ALLOCATED"  # provider says it exists; ray not up yet
RAY_RUNNING = "RAY_RUNNING"  # registered with the head, schedulable
TERMINATING = "TERMINATING"  # terminate issued; waiting for the provider
TERMINATED = "TERMINATED"  # gone (terminal)
ALLOCATION_FAILED = "ALLOCATION_FAILED"  # provider failure (terminal)

# legal transitions (reference: InstanceUtil.get_valid_transitions)
_TRANSITIONS: dict[str, set[str]] = {
    QUEUED: {REQUESTED, TERMINATED},
    REQUESTED: {ALLOCATED, ALLOCATION_FAILED, TERMINATING},
    ALLOCATED: {RAY_RUNNING, TERMINATING},
    RAY_RUNNING: {TERMINATING},
    TERMINATING: {TERMINATED},
    TERMINATED: set(),
    ALLOCATION_FAILED: set(),
}

# how long an instance may sit in a transient state before the
# reconciler declares it stuck (reference: reconciler timeouts)
DEFAULT_STUCK_TIMEOUTS_S = {
    REQUESTED: 120.0,
    ALLOCATED: 120.0,
    TERMINATING: 60.0,
}


class InvalidTransitionError(RuntimeError):
    pass


@dataclasses.dataclass
class Instance:
    """One managed node's lifecycle record (reference: the Instance
    proto in v2/schema)."""

    instance_id: str
    node_type: str
    status: str = QUEUED
    provider_handle: Any = None  # what the NodeProvider returned
    node_id: bytes | None = None  # once registered with the head
    version: int = 0
    status_since: float = dataclasses.field(default_factory=time.monotonic)
    history: list[tuple[str, float]] = dataclasses.field(
        default_factory=list)


class InstanceStorage:
    """Versioned in-memory instance table with update subscribers
    (reference: instance_storage.py — compare-and-swap updates so two
    reconciler passes can never interleave a transition)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instances: dict[str, Instance] = {}
        self._version = 0
        self._subscribers: list[Callable[[Instance], None]] = []

    def subscribe(self, fn: Callable[[Instance], None]):
        self._subscribers.append(fn)

    def add(self, node_type: str) -> Instance:
        inst = Instance(instance_id=uuid.uuid4().hex[:12],
                        node_type=node_type)
        with self._lock:
            self._version += 1
            inst.version = self._version
            inst.history.append((QUEUED, time.monotonic()))
            self._instances[inst.instance_id] = inst
            snap = self._snapshot(inst)
        self._notify(snap)
        return inst

    def transition(self, instance_id: str, new_status: str,
                   expected_version: int | None = None, **updates):
        """CAS state transition; raises on illegal edges so bugs surface
        as errors, not as silently-drifting state."""
        with self._lock:
            inst = self._instances.get(instance_id)
            if inst is None:
                raise KeyError(instance_id)
            if expected_version is not None and \
                    inst.version != expected_version:
                raise InvalidTransitionError(
                    f"version conflict on {instance_id}: "
                    f"{inst.version} != {expected_version}")
            if new_status not in _TRANSITIONS[inst.status]:
                raise InvalidTransitionError(
                    f"{inst.status} -> {new_status} is not a legal edge")
            inst.status = new_status
            inst.status_since = time.monotonic()
            inst.history.append((new_status, time.monotonic()))
            for k, v in updates.items():
                setattr(inst, k, v)
            self._version += 1
            inst.version = self._version
            snap = self._snapshot(inst)
        self._notify(snap)
        return inst

    @staticmethod
    def _snapshot(inst: Instance) -> Instance:
        """Immutable copy built UNDER the storage lock, so a concurrent
        transition can never tear the payload a subscriber receives.
        Cross-thread delivery order remains best-effort — consumers
        sort by .version."""
        return dataclasses.replace(inst, history=list(inst.history))

    def _notify(self, snap: Instance):
        for fn in self._subscribers:
            try:
                fn(snap)
            except Exception:  # noqa: BLE001
                pass

    def prune_terminal(self, keep: int = 200):
        """Drop the oldest terminal records past `keep` (a provider in
        persistent stockout would otherwise grow one ALLOCATION_FAILED
        record per tick, forever)."""
        with self._lock:
            terminal = [i for i in self._instances.values()
                        if i.status in (TERMINATED, ALLOCATION_FAILED)]
            terminal.sort(key=lambda i: i.status_since)
            for inst in terminal[:-keep] if keep else terminal:
                self._instances.pop(inst.instance_id, None)

    def list(self, *statuses: str) -> list[Instance]:
        with self._lock:
            out = list(self._instances.values())
        if statuses:
            out = [i for i in out if i.status in statuses]
        return out

    def get(self, instance_id: str) -> Instance | None:
        with self._lock:
            return self._instances.get(instance_id)


# ---------------------------------------------------------------- scheduler


@dataclasses.dataclass
class SchedulingDecision:
    to_launch: dict[str, int]  # node_type -> count
    to_terminate: list[str]  # instance ids (idle past timeout)
    reason: str = ""


class Scheduler:
    """Pure function of (demand, live instances, config) → decision
    (reference: v2/scheduler.py ResourceDemandScheduler). Demand:
    queued work with no headroom or PENDING placement groups."""

    def __init__(self, node_type: str, min_workers: int, max_workers: int,
                 idle_timeout_s: float):
        self.node_type = node_type
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.idle_timeout_s = idle_timeout_s
        self._idle_since: dict[str, float] = {}

    def decide(self, demand: bool, instances: list[Instance],
               idle_node_ids: set[bytes]) -> SchedulingDecision:
        live = [i for i in instances
                if i.status in (QUEUED, REQUESTED, ALLOCATED, RAY_RUNNING)]
        n_live = len(live)
        to_launch: dict[str, int] = {}
        if n_live < self.min_workers:
            to_launch[self.node_type] = self.min_workers - n_live
        elif demand and n_live < self.max_workers:
            to_launch[self.node_type] = 1
        # idle scale-down: RAY_RUNNING instances whose node stayed idle
        # past the timeout, never below min_workers
        now = time.monotonic()
        to_terminate: list[str] = []
        running = [i for i in live if i.status == RAY_RUNNING]
        surplus = n_live - self.min_workers
        for inst in running:
            if inst.node_id not in idle_node_ids:
                self._idle_since.pop(inst.instance_id, None)
                continue
            t0 = self._idle_since.setdefault(inst.instance_id, now)
            if now - t0 >= self.idle_timeout_s and surplus > 0:
                to_terminate.append(inst.instance_id)
                self._idle_since.pop(inst.instance_id, None)
                surplus -= 1
        return SchedulingDecision(to_launch, to_terminate,
                                  reason="demand" if demand else "steady")


# ---------------------------------------------------------------- reconciler


class Reconciler:
    """Converges instance storage ↔ provider ↔ ray views each tick
    (reference: instance_manager/reconciler.py). Every step is
    idempotent: a second tick with unchanged inputs is a no-op."""

    def __init__(self, head_address: str, provider, node_type: str = "worker",
                 min_workers: int = 0, max_workers: int = 4,
                 idle_timeout_s: float = 30.0,
                 stuck_timeouts: dict[str, float] | None = None):
        from ray_tpu.core.rpc import RpcClient

        self.head_address = head_address
        self.provider = provider
        self.storage = InstanceStorage()
        self.scheduler = Scheduler(node_type, min_workers, max_workers,
                                   idle_timeout_s)
        self.client = RpcClient.shared()
        # MERGE with defaults: a user tuning one state must not silently
        # disable the other stuck handlers
        self.stuck_timeouts = {**DEFAULT_STUCK_TIMEOUTS_S,
                               **(stuck_timeouts or {})}
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None
        # serializes manual reconcile() calls against the loop thread
        self._reconcile_lock = threading.Lock()
        self._launch_backoff_until = 0.0
        self.num_launches = 0
        self.num_terminations = 0

    # -- cluster views ---------------------------------------------------

    def _ray_view(self):
        view = self.client.call(self.head_address, "cluster_view", {},
                                timeout=10)["nodes"]
        pgs = self.client.call(self.head_address, "pg_table", {},
                               timeout=10).get("groups", [])
        return view, pgs

    # -- one idempotent pass --------------------------------------------

    def reconcile(self):
        """One idempotent pass; serialized so a manual call can never
        race the background loop into an illegal double-transition."""
        with self._reconcile_lock:
            self._reconcile_locked()

    def _reconcile_locked(self):
        from ray_tpu.autoscaler import compute_demand, idle_node_ids

        try:
            view, pgs = self._ray_view()
        except Exception:  # noqa: BLE001
            return  # head unreachable: change nothing
        alive = [n for n in view if n["alive"]]
        by_node_id = {n["node_id"]: n for n in alive}
        try:
            provider_nodes = {self.provider.node_id(h): h
                              for h in self.provider.non_terminated_nodes()}
            provider_nodes.pop(b"", None)  # pending/booting placeholders
        except Exception:  # noqa: BLE001
            return  # provider unreachable: change nothing

        # 1. sync REQUESTED → ALLOCATED by matching UNCLAIMED provider
        # nodes (NOT the create handle: a GCP slice's create handle is a
        # placeholder and one create yields N hosts)
        claimed = {i.node_id for i in self.storage.list()
                   if i.node_id is not None}
        unclaimed = [nid for nid in provider_nodes if nid not in claimed]
        for inst in self.storage.list(REQUESTED):
            if not unclaimed:
                break
            nid = unclaimed.pop(0)
            self.storage.transition(inst.instance_id, ALLOCATED,
                                    node_id=nid,
                                    provider_handle=provider_nodes[nid])
        # 1b. ADOPT remaining unclaimed provider nodes (e.g. the extra
        # hosts of a pod slice — one create_node materialized N nodes;
        # reference: the reconciler adopts unknown cloud instances)
        for nid in unclaimed:
            inst = self.storage.add(self.scheduler.node_type)
            self.storage.transition(inst.instance_id, REQUESTED,
                                    provider_handle=provider_nodes[nid])
            self.storage.transition(inst.instance_id, ALLOCATED,
                                    node_id=nid)
        # 2. sync: ALLOCATED instances whose node registered with ray
        for inst in self.storage.list(ALLOCATED):
            if inst.node_id in by_node_id:
                self.storage.transition(inst.instance_id, RAY_RUNNING)
        # 2b. sync: RAY_RUNNING instances whose node DIED (crash,
        # preemption, or a whole-slice terminate taking sibling hosts):
        # without this they count as live forever and min_workers
        # replacement never fires
        for inst in self.storage.list(RAY_RUNNING):
            if inst.node_id not in by_node_id:
                self._terminate(inst)  # step 3 completes it next tick
        # 3. sync: TERMINATING instances gone from the provider
        for inst in self.storage.list(TERMINATING):
            if inst.node_id not in provider_nodes and \
                    inst.node_id not in by_node_id:
                self.storage.transition(inst.instance_id, TERMINATED)
                self.num_terminations += 1
        # 4. stuck-state handling (reference: reconciler timeouts)
        now = time.monotonic()
        for inst in self.storage.list(*self.stuck_timeouts):
            if now - inst.status_since <= self.stuck_timeouts[inst.status]:
                continue
            if inst.status == REQUESTED:
                if inst.provider_handle is not None:
                    # the provider call succeeded: the node may still
                    # materialize later — tear it down rather than leak
                    # a billing cloud resource behind a terminal record
                    self._terminate(inst)
                else:
                    self.storage.transition(inst.instance_id,
                                            ALLOCATION_FAILED)
            elif inst.status == ALLOCATED:
                # node exists but ray never came up: reclaim it
                self._terminate(inst)
            elif inst.status == TERMINATING:
                # retry the provider terminate; only force-complete the
                # record once the provider agrees the node is gone
                try:
                    if inst.provider_handle is not None:
                        self.provider.terminate_node(inst.provider_handle)
                except Exception:  # noqa: BLE001
                    pass
                if inst.node_id not in provider_nodes:
                    self.storage.transition(inst.instance_id, TERMINATED)
                    self.num_terminations += 1

        # 5. schedule against live demand (signals shared with v1)
        decision = self.scheduler.decide(
            compute_demand(alive, pgs), self.storage.list(),
            idle_node_ids(alive))
        # 6. apply: launches (QUEUED → REQUESTED with the provider call),
        # under a backoff after provider failures (a stockout must not
        # mint one failed record per tick forever)
        if decision.to_launch and now < self._launch_backoff_until:
            decision.to_launch = {}
        for node_type, count in decision.to_launch.items():
            for _ in range(count):
                inst = self.storage.add(node_type)
                try:
                    handle = self.provider.create_node(node_type)
                except Exception:  # noqa: BLE001
                    self.storage.transition(inst.instance_id, REQUESTED)
                    self.storage.transition(inst.instance_id,
                                            ALLOCATION_FAILED)
                    # stop the WHOLE tick's launches: hammering a
                    # stocked-out provider mints a failure per attempt
                    self._launch_backoff_until = now + 10.0
                    break
                self.storage.transition(inst.instance_id, REQUESTED,
                                        provider_handle=handle)
                self.num_launches += 1
            if now < self._launch_backoff_until:
                break
        # 7. apply: terminations
        for iid in decision.to_terminate:
            inst = self.storage.get(iid)
            if inst is not None and inst.status == RAY_RUNNING:
                self._terminate(inst)
        self.storage.prune_terminal()

    def _terminate(self, inst: Instance):
        self.storage.transition(inst.instance_id, TERMINATING)
        try:
            if inst.provider_handle is not None:
                self.provider.terminate_node(inst.provider_handle)
        except Exception:  # noqa: BLE001
            pass

    # -- lifecycle -------------------------------------------------------

    def start(self, interval_s: float = 1.0) -> "Reconciler":
        def loop():
            while not self._stopped.wait(interval_s):
                try:
                    self.reconcile()
                except Exception:  # noqa: BLE001
                    # one bad pass (transient provider/storage error)
                    # must not silently end autoscaling forever
                    pass

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="autoscaler-v2")
        self._thread.start()
        return self

    def stop(self):
        self._stopped.set()

    def summary(self) -> dict:
        """Operator view (reference: `ray status` v2 output)."""
        counts: dict[str, int] = {}
        for inst in self.storage.list():
            counts[inst.status] = counts.get(inst.status, 0) + 1
        return {"instances": counts, "launches": self.num_launches,
                "terminations": self.num_terminations}
