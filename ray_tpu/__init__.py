"""ray_tpu — a TPU-native distributed computing framework.

A ground-up rebuild of the capabilities of Ray (tasks, actors, objects,
placement groups, distributed scheduling, fault tolerance) plus its ML
libraries (Train, Tune, RLlib, Data, Serve), designed TPU-first:

- compute is expressed as SPMD programs over ``jax.sharding.Mesh`` device
  meshes; collectives lower to XLA ICI/DCN primitives (psum, all_gather,
  ppermute, all_to_all) instead of NCCL worlds,
- the scheduler understands TPU pod-slice topology as a first-class
  resource (slice bundles, host gang scheduling),
- hot ops (attention, collectives overlap) are pallas TPU kernels.

Public core API (reference parity: python/ray/_private/worker.py:1275,
python/ray/remote_function.py:41, python/ray/actor.py:602):

    import ray_tpu as ray
    ray.init()
    @ray.remote
    def f(x): return x + 1
    ref = f.remote(1)
    ray.get(ref)
"""

from ray_tpu._version import __version__
from ray_tpu.core.api import (
    ObjectRef,
    ObjectRefGenerator,
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    get_runtime_context,
    init,
    timeline,
    is_initialized,
    kill,
    method,
    nodes,
    put,
    remote,
    shutdown,
    wait,
)

__all__ = [
    "__version__",
    "ObjectRef",
    "ObjectRefGenerator",
    "available_resources",
    "cancel",
    "cluster_resources",
    "get",
    "get_actor",
    "get_runtime_context",
    "init",
    "timeline",
    "is_initialized",
    "kill",
    "method",
    "nodes",
    "put",
    "remote",
    "shutdown",
    "wait",
]
