"""Multi-node-on-one-box test cluster.

Reference parity: ray.cluster_utils.Cluster
(python/ray/cluster_utils.py:135) — THE mechanism for multi-node tests
without real machines: one head + N nodelets as local services with
*asserted* (fake) resources; workers are real OS processes.
"""

from __future__ import annotations

import os
import time

from ray_tpu.core.head import Head
from ray_tpu.core.nodelet import Nodelet


class Cluster:
    def __init__(self, initialize_head: bool = True, head_node_args: dict | None = None):
        self.head: Head | None = None
        self.nodelets: list[Nodelet] = []
        session = f"session_test_{int(time.time())}_{os.getpid()}"
        self.session_dir = os.path.join("/tmp/ray_tpu", session)
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)
        if initialize_head:
            self.head = Head(session_name=session).start()
            if head_node_args is not None:
                self.add_node(**head_node_args)

    @property
    def address(self) -> str:
        return self.head.address

    def add_node(self, num_cpus: float = 4, num_tpus: float = 0,
                 resources: dict | None = None, labels: dict | None = None,
                 store_capacity: int = 64 * 1024 * 1024) -> Nodelet:
        res = dict(resources or {})
        res.setdefault("CPU", float(num_cpus))
        if num_tpus:
            res["TPU"] = float(num_tpus)
        nl = Nodelet(self.head.address, res, labels=labels,
                     session_dir=self.session_dir,
                     store_capacity=store_capacity).start()
        self.nodelets.append(nl)
        return nl

    def remove_node(self, nodelet: Nodelet):
        nodelet.stop()
        self.nodelets.remove(nodelet)

    def wait_for_nodes(self, timeout: float = 30):
        from ray_tpu.core.rpc import RpcClient

        client = RpcClient.shared()
        deadline = time.monotonic() + timeout
        want = len(self.nodelets)
        while time.monotonic() < deadline:
            view = client.call(self.head.address, "cluster_view", {}, timeout=5)
            if sum(1 for n in view["nodes"] if n["alive"]) >= want:
                return
            time.sleep(0.1)
        raise TimeoutError("nodes did not register in time")

    def shutdown(self):
        for nl in self.nodelets:
            try:
                nl.stop()
            except Exception:
                pass
        self.nodelets.clear()
        if self.head is not None:
            self.head.stop()
            self.head = None
