"""Durable workflows — crash-resumable DAG execution over tasks.

Reference parity: python/ray/workflow/ (workflow_executor.py:1 — a DAG
of steps executed as tasks with every step result durably logged;
api.py run/resume/list_all; workflow_storage.py — filesystem-backed
step-result store). Redesign: steps are plain ray_tpu tasks; the
executor walks the DAG bottom-up, skipping any step whose result is
already persisted under its DETERMINISTIC step id (name + structural
hash of its inputs), so `resume()` after a crash re-executes only the
unfinished suffix. Storage is a directory tree:

    <storage>/<workflow_id>/
        dag.pkl            # the submitted DAG (enables resume)
        status.json        # RUNNING | SUCCESS | FAILED
        steps/<step_id>.pkl  # one durable result per finished step
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any

import cloudpickle

_DEFAULT_STORAGE = os.path.expanduser("~/.ray_tpu_workflows")

RUNNING = "RUNNING"
SUCCESS = "SUCCESS"
FAILED = "FAILED"
RESUMABLE = "RESUMABLE"


class WorkflowError(RuntimeError):
    pass


class StepNode:
    """One DAG node: a function + (possibly nested) inputs. Produced by
    `@workflow.step` functions' `.step(*args)` (reference:
    workflow step decorator / DAG node bind)."""

    def __init__(self, fn, name: str, args: tuple, kwargs: dict,
                 max_retries: int = 0, num_cpus: float = 1.0,
                 timeout_s: float | None = None):
        self.fn = fn
        self.name = name
        self.args = args
        self.kwargs = kwargs
        self.max_retries = max_retries
        self.num_cpus = num_cpus
        self.timeout_s = timeout_s  # None = wait as long as the step runs

    def step_id(self) -> str:
        """Deterministic content-addressed id: the step's name plus the
        structural hash of its inputs — stable across resumes."""
        cached = getattr(self, "_sid", None)
        if cached is not None:
            return cached

        def feed(h, v):
            # recurse through containers so NESTED StepNodes contribute
            # their deterministic ids (a raw pickle of the container
            # would vary across resumes and break completed-step skips)
            if isinstance(v, StepNode):
                h.update(v.step_id().encode())
            elif isinstance(v, (list, tuple)):
                h.update(b"[")
                for x in v:
                    feed(h, x)
                h.update(b"]")
            elif isinstance(v, dict):
                h.update(b"{")
                for k in sorted(v, key=repr):
                    h.update(repr(k).encode())
                    feed(h, v[k])
                h.update(b"}")
            else:
                try:
                    h.update(cloudpickle.dumps(v))
                except Exception:  # noqa: BLE001
                    h.update(repr(v).encode())

        h = hashlib.sha1(self.name.encode())
        # the FUNCTION is part of the identity: same-named steps with
        # different bodies (or a body edited between run and resume)
        # must not reuse each other's persisted results
        fn = self.fn
        h.update(getattr(fn, "__module__", "").encode())
        h.update(getattr(fn, "__qualname__", "").encode())
        code = getattr(fn, "__code__", None)
        if code is not None:
            h.update(code.co_code)
            h.update(repr(code.co_consts).encode())
        for cell in getattr(fn, "__closure__", None) or ():
            try:
                h.update(cloudpickle.dumps(cell.cell_contents))
            except Exception:  # noqa: BLE001
                h.update(repr(cell.cell_contents).encode())
        for a in self.args:
            feed(h, a)
        for k in sorted(self.kwargs):
            h.update(k.encode())
            feed(h, self.kwargs[k])
        self._sid = f"{self.name}-{h.hexdigest()[:16]}"
        return self._sid


class _StepFunction:
    def __init__(self, fn, name=None, max_retries=0, num_cpus=1.0,
                 timeout_s=None):
        self._fn = fn
        self._name = name or fn.__name__
        self._max_retries = max_retries
        self._num_cpus = num_cpus
        self._timeout_s = timeout_s

    def step(self, *args, **kwargs) -> StepNode:
        return StepNode(self._fn, self._name, args, kwargs,
                        self._max_retries, self._num_cpus,
                        self._timeout_s)

    def options(self, **kw) -> "_StepFunction":
        return _StepFunction(self._fn, kw.get("name", self._name),
                             kw.get("max_retries", self._max_retries),
                             kw.get("num_cpus", self._num_cpus),
                             kw.get("timeout_s", self._timeout_s))

    def __call__(self, *a, **kw):
        return self._fn(*a, **kw)


def step(_fn=None, *, name: str | None = None, max_retries: int = 0,
         num_cpus: float = 1.0, timeout_s: float | None = None):
    """Decorator: make a function a workflow step (reference:
    workflow step API). `timeout_s` bounds ONE execution of the step;
    the default (None) waits as long as the step runs — durable DAGs
    exist precisely for long jobs."""

    def wrap(fn):
        return _StepFunction(fn, name, max_retries, num_cpus, timeout_s)

    return wrap(_fn) if _fn is not None else wrap


# ------------------------------------------------------------ storage


class _Storage:
    def __init__(self, root: str, workflow_id: str):
        self.dir = os.path.join(root, workflow_id)
        self.steps_dir = os.path.join(self.dir, "steps")
        os.makedirs(self.steps_dir, exist_ok=True)

    def write_status(self, status: str, error: str | None = None):
        tmp = os.path.join(self.dir, ".status.tmp")
        with open(tmp, "w") as f:
            json.dump({"status": status, "error": error,
                       "time": time.time()}, f)
        os.replace(tmp, os.path.join(self.dir, "status.json"))

    def read_status(self) -> dict:
        try:
            with open(os.path.join(self.dir, "status.json")) as f:
                return json.load(f)
        except OSError:
            return {"status": "NOT_FOUND"}

    def save_dag(self, node: StepNode):
        with open(os.path.join(self.dir, "dag.pkl"), "wb") as f:
            cloudpickle.dump(node, f)

    def load_dag(self) -> StepNode:
        with open(os.path.join(self.dir, "dag.pkl"), "rb") as f:
            return cloudpickle.load(f)

    def has_step(self, step_id: str) -> bool:
        return os.path.exists(os.path.join(self.steps_dir,
                                           step_id + ".pkl"))

    def save_step(self, step_id: str, value: Any):
        tmp = os.path.join(self.steps_dir, step_id + ".tmp")
        with open(tmp, "wb") as f:
            cloudpickle.dump(value, f)  # durable BEFORE marked done
        os.replace(tmp, os.path.join(self.steps_dir, step_id + ".pkl"))

    def load_step(self, step_id: str) -> Any:
        with open(os.path.join(self.steps_dir, step_id + ".pkl"),
                  "rb") as f:
            return cloudpickle.load(f)


# ------------------------------------------------------------ executor


def _execute(node: StepNode, storage: _Storage, stats: dict) -> Any:
    """Post-order DAG walk: resolve inputs (recursively), skip steps
    whose results are persisted, run the rest as ray_tpu tasks
    (reference: workflow_executor.py — the executor resolves
    WorkflowRefs then submits the step as a task)."""
    sid = node.step_id()
    if storage.has_step(sid):
        stats["skipped"] += 1
        return storage.load_step(sid)

    def resolve(v):
        # containers may nest StepNodes (e.g. fan-in via a list of
        # steps) — resolve recursively, mirroring step_id's hashing
        if isinstance(v, StepNode):
            return _execute(v, storage, stats)
        if isinstance(v, list):
            return [resolve(x) for x in v]
        if isinstance(v, tuple):
            return tuple(resolve(x) for x in v)
        if isinstance(v, dict):
            return {k: resolve(x) for k, x in v.items()}
        return v

    args = tuple(resolve(a) for a in node.args)
    kwargs = {k: resolve(v) for k, v in node.kwargs.items()}

    import ray_tpu

    task = ray_tpu.remote(num_cpus=node.num_cpus,
                          max_retries=node.max_retries)(node.fn)
    value = ray_tpu.get(task.remote(*args, **kwargs),
                        timeout=node.timeout_s)
    storage.save_step(sid, value)
    stats["executed"] += 1
    return value


def run(node: StepNode, *, workflow_id: str | None = None,
        storage: str | None = None) -> Any:
    """Execute a workflow DAG durably; returns the final step's value.
    Reference: workflow.run (api.py)."""
    if not isinstance(node, StepNode):
        raise WorkflowError("workflow.run expects a StepNode "
                            "(build one with @workflow.step + .step(...))")
    workflow_id = workflow_id or f"wf-{int(time.time())}-{os.getpid()}"
    st = _Storage(storage or _DEFAULT_STORAGE, workflow_id)
    st.save_dag(node)
    st.write_status(RUNNING)
    stats = {"executed": 0, "skipped": 0}
    try:
        value = _execute(node, st, stats)
    except BaseException as e:
        st.write_status(FAILED, error=repr(e))
        raise
    st.save_step("__result__", value)
    st.write_status(SUCCESS)
    return value


def resume(workflow_id: str, *, storage: str | None = None) -> Any:
    """Re-run a workflow from its logged DAG; completed steps are
    skipped via their durable results (reference: workflow.resume)."""
    st = _Storage(storage or _DEFAULT_STORAGE, workflow_id)
    status = st.read_status()
    if status["status"] == "NOT_FOUND":
        raise WorkflowError(f"no workflow {workflow_id!r}")
    if status["status"] == SUCCESS:
        return st.load_step("__result__")
    node = st.load_dag()
    st.write_status(RUNNING)
    stats = {"executed": 0, "skipped": 0}
    try:
        value = _execute(node, st, stats)
    except BaseException as e:
        st.write_status(FAILED, error=repr(e))
        raise
    st.save_step("__result__", value)
    st.write_status(SUCCESS)
    return value


def get_status(workflow_id: str, *, storage: str | None = None) -> str:
    st = _Storage(storage or _DEFAULT_STORAGE, workflow_id)
    s = st.read_status()["status"]
    # a workflow last seen RUNNING whose driver is gone is resumable
    return RESUMABLE if s in (RUNNING, FAILED) else s


def list_all(*, storage: str | None = None) -> list[tuple[str, str]]:
    root = storage or _DEFAULT_STORAGE
    out = []
    try:
        ids = sorted(os.listdir(root))
    except OSError:
        return []
    for wid in ids:
        if os.path.isdir(os.path.join(root, wid)):
            out.append((wid, get_status(wid, storage=root)))
    return out


def get_output(workflow_id: str, *, storage: str | None = None) -> Any:
    st = _Storage(storage or _DEFAULT_STORAGE, workflow_id)
    if st.read_status()["status"] != SUCCESS:
        raise WorkflowError(f"workflow {workflow_id!r} has no output "
                            f"(status {st.read_status()['status']})")
    return st.load_step("__result__")
