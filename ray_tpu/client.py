"""Ray-Client-style remote drivers: drive a cluster from OUTSIDE it.

Reference parity: python/ray/util/client/ (worker.py:81 — the thin
client mirroring the ray API over a connection; server/proxier.py —
a proxy that spawns ONE dedicated server per client so clients are
isolated from each other) and src/ray/protobuf/ray_client.proto:325
(the put/get/task/actor RPC surface). Redesign on this runtime's own
transport: the proxy (`ClientProxy`) listens on a well-known port; on
connect it spawns a per-client HOST process on the cluster (a full
driver-mode ClusterRuntime with local shm-store access) and hands the
client its address; the thin client (`ClientContext`) then talks to
its host directly with cloudpickle frames. The thin client needs NO
nodelet, NO shm store, NO cluster-routable object plane — exactly the
reference's client-mode contract.

    # on the cluster (e.g. next to the head):
    ray_tpu.client.start_client_server(head_address, port=10001)
    # anywhere with a route to that port:
    ctx = ray_tpu.client.connect("host:10001")
    f = ctx.remote(num_cpus=1)(fn)
    ctx.get(f.remote(3))
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time

import cloudpickle

from ray_tpu.core import serialization as ser
from ray_tpu.core.rpc import RpcClient, RpcServer

_REF = "__client_ref__"
_ACTOR = "__client_actor__"


# ------------------------------------------------------------ host side


class _ClientHost:
    """Per-client server: a real driver runtime executing the thin
    client's commands (reference: one SpecificServer per client,
    util/client/server/proxier.py)."""

    def __init__(self, head_address: str):
        from ray_tpu.core import api as _api

        _api.init(address=head_address)
        self.rt = _api._runtime
        # client-visible state: refs pinned alive on behalf of the client
        self._objects: dict[bytes, object] = {}
        self._actors: dict[bytes, object] = {}
        self._fns: dict[bytes, object] = {}
        self._lock = threading.Lock()
        self._last_seen = time.monotonic()
        s = self.rt.server  # ride the runtime's own RpcServer

        def alive(fn):
            # EVERY client RPC is liveness — without this a client busy
            # with tasks for >idle_timeout would get its host reaped
            def wrapped(msg, frames):
                self._last_seen = time.monotonic()
                return fn(msg, frames)

            return wrapped

        s.register("c_ping", alive(self._h_ping))
        s.register("c_put", alive(self._h_put))
        s.register("c_get", alive(self._h_get), slow=True)
        s.register("c_wait", alive(self._h_wait), slow=True)
        s.register("c_task", alive(self._h_task))
        s.register("c_actor_new", alive(self._h_actor_new))
        s.register("c_actor_call", alive(self._h_actor_call))
        s.register("c_get_actor", alive(self._h_get_actor))
        s.register("c_kill", alive(self._h_kill))
        s.register("c_free", alive(self._h_free), oneway=True)
        s.register("c_disconnect", self._h_disconnect, oneway=True)

    # -- arg translation -------------------------------------------------

    def _decode(self, v):
        if isinstance(v, dict) and _REF in v:
            with self._lock:
                return self._objects[v[_REF]]
        if isinstance(v, dict) and _ACTOR in v:
            with self._lock:
                return self._actors[v[_ACTOR]]
        return v

    def _track(self, ref) -> dict:
        b = ref.id.binary()
        with self._lock:
            self._objects[b] = ref
        return {_REF: b}

    # -- handlers --------------------------------------------------------

    def _h_ping(self, msg, frames):
        self._last_seen = time.monotonic()
        return {"ok": True, "address": self.rt.address}

    def _h_put(self, msg, frames):
        import ray_tpu

        value = ser.deserialize(memoryview(frames[0]))
        return self._track(ray_tpu.put(value))

    def _h_get(self, msg, frames):
        import ray_tpu

        refs = [self._decode(r) for r in msg["refs"]]
        # always a list in, list out; the thin client unwraps singles.
        # Blocking here is the proxy's job: c_get rides the slow lane,
        # and task_done lands on the main pool. v2 index audit: the RPC
        # registry confirms this handler registered slow=True (the
        # reentry analysis therefore excludes its edges — the slow pool
        # can park without starving the control plane)
        # graftlint: disable=async-blocking
        values = ray_tpu.get(refs, timeout=msg.get("timeout", 300))
        head, views, total = ser.serialize(values)
        buf = bytearray(total)
        ser.write_into(memoryview(buf), head, views)
        return {"ok": True}, [bytes(buf)]

    def _h_wait(self, msg, frames):
        import ray_tpu

        refs = [self._decode(r) for r in msg["refs"]]
        by_id = {r.id.binary(): m for r, m in zip(refs, msg["refs"])}
        # synchronous proxy on the slow lane, same rationale as c_get
        # (v2 index audit: registered slow=True, excluded from reentry
        # edges)
        # graftlint: disable=async-blocking
        ready, pending = ray_tpu.wait(
            refs, num_returns=msg.get("num_returns", 1),
            timeout=msg.get("timeout"))
        return {"ready": [by_id[r.id.binary()] for r in ready],
                "pending": [by_id[r.id.binary()] for r in pending]}

    def _remote_fn(self, blob: bytes, opts: dict):
        import hashlib

        import ray_tpu

        key = hashlib.sha1(blob).digest() + ser.dumps_msg(
            sorted(opts.items()))
        with self._lock:
            fn = self._fns.get(key)
        if fn is None:
            fn = ray_tpu.remote(**opts)(cloudpickle.loads(blob))
            with self._lock:
                self._fns[key] = fn
        return fn

    def _h_task(self, msg, frames):
        fn = self._remote_fn(frames[0], msg.get("opts") or {})
        args = [self._decode(a) for a in msg.get("args", ())]
        kwargs = {k: self._decode(v)
                  for k, v in (msg.get("kwargs") or {}).items()}
        out = fn.remote(*args, **kwargs)
        refs = out if isinstance(out, list) else [out]
        return {"refs": [self._track(r) for r in refs],
                "single": not isinstance(out, list)}

    def _h_actor_new(self, msg, frames):
        import ray_tpu

        cls = cloudpickle.loads(frames[0])
        actor_cls = ray_tpu.remote(**(msg.get("opts") or {}))(cls)
        copts = msg.get("actor_opts") or {}
        if copts:
            actor_cls = actor_cls.options(**copts)
        args = [self._decode(a) for a in msg.get("args", ())]
        kwargs = {k: self._decode(v)
                  for k, v in (msg.get("kwargs") or {}).items()}
        handle = actor_cls.remote(*args, **kwargs)
        b = handle._actor_id.binary()
        with self._lock:
            self._actors[b] = handle
        return {_ACTOR: b}

    def _h_get_actor(self, msg, frames):
        import ray_tpu

        handle = ray_tpu.get_actor(msg["name"])
        b = handle._actor_id.binary()
        with self._lock:
            self._actors[b] = handle
        return {_ACTOR: b}

    def _h_actor_call(self, msg, frames):
        with self._lock:
            handle = self._actors[msg["actor"]]
        args = [self._decode(a) for a in msg.get("args", ())]
        kwargs = {k: self._decode(v)
                  for k, v in (msg.get("kwargs") or {}).items()}
        ref = getattr(handle, msg["method"]).remote(*args, **kwargs)
        return self._track(ref)

    def _h_kill(self, msg, frames):
        import ray_tpu

        with self._lock:
            handle = self._actors.pop(msg["actor"], None)
        if handle is not None:
            ray_tpu.kill(handle)
        return {"ok": handle is not None}

    def _h_free(self, msg, frames):
        with self._lock:
            for b in msg.get("refs", ()):
                self._objects.pop(b, None)

    def _h_disconnect(self, msg, frames):
        threading.Thread(target=self._shutdown, daemon=True).start()

    def _shutdown(self):
        time.sleep(0.2)  # let the oneway's socket settle
        try:
            # return leases / free owned objects so the cluster's
            # resources release NOW, not at lease-TTL expiry
            self.rt.shutdown()
        except Exception:  # noqa: BLE001
            pass
        # lease returns are SYNCHRONOUS inside shutdown() (the reply is
        # the delivery guarantee); this beat only gives zmq's io thread
        # a chance to push the remaining best-effort oneways (frees,
        # disconnect acks) before the process dies
        time.sleep(0.1)
        os._exit(0)

    def serve_forever(self, idle_timeout_s: float = 300.0):
        while True:
            time.sleep(5.0)
            if time.monotonic() - self._last_seen > idle_timeout_s:
                self._shutdown()  # orphaned client host


def _client_host_main():
    head = os.environ["RAY_TPU_HEAD_ADDR"]
    host = _ClientHost(head)
    # hand our address to the spawning proxy over stdout — PROTOCOL
    # output the parent parses line-by-line, not logging
    # graftlint: disable=bare-print
    print(f"CLIENT_HOST_ADDR {host.rt.address}", flush=True)
    # the proxy stops reading this pipe after the handshake line: any
    # later stdout (e.g. worker prints mirrored here under
    # RAY_TPU_LOG_TO_DRIVER) would fill the ~64KB pipe and BLOCK the
    # writing RPC thread forever — detach to devnull; the runtime's
    # bounded mirror ring still retains mirrored lines for the client
    sys.stdout = open(os.devnull, "w")
    host.serve_forever()


# ------------------------------------------------------------ proxy


class ClientProxy:
    """Well-known-port proxy: `client_connect` spawns a dedicated host
    process per client (reference: proxier.py)."""

    def __init__(self, head_address: str, port: int = 0):
        # port is advisory: the RpcServer binds a random port and
        # `.address` is authoritative (operators publish it the same way
        # they publish the head address). A fixed listen port would need
        # a bind option on RpcServer; deferred until something needs it.
        del port
        self.head_address = head_address
        self.server = RpcServer(name="client-proxy")
        self.server.register("client_connect", self._h_connect, slow=True)
        self.server.register("ping", lambda m, f: "pong")
        self.server.start()
        self.address = self.server.address
        self._procs: list[subprocess.Popen] = []

    def _h_connect(self, msg, frames):
        env = dict(os.environ, RAY_TPU_HEAD_ADDR=self.head_address)
        proc = subprocess.Popen(
            [sys.executable, "-c",
             "from ray_tpu.client import _client_host_main; "
             "_client_host_main()"],
            env=env, stdout=subprocess.PIPE, text=True)
        # reap exited client hosts so the list tracks live processes
        # only (it otherwise grows by one per connect, forever)
        self._procs = [p for p in self._procs if p.poll() is None]
        self._procs.append(proc)
        deadline = time.monotonic() + 60
        addr = None
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if line.startswith("CLIENT_HOST_ADDR "):
                addr = line.split(" ", 1)[1].strip()
                break
            if proc.poll() is not None:
                break
        if addr is None:
            raise RuntimeError("client host failed to start")
        return {"host": addr}

    def stop(self):
        self.server.stop()
        for p in self._procs:
            try:
                p.kill()
            except Exception:  # noqa: BLE001
                pass


def start_client_server(head_address: str, port: int = 0) -> ClientProxy:
    """Start the client proxy next to the cluster; returns the proxy
    (its .address is what remote clients connect to)."""
    return ClientProxy(head_address, port)


# ------------------------------------------------------------ thin client


class ClientObjectRef:
    __slots__ = ("ctx", "id")

    def __init__(self, ctx, ref_id: bytes):
        self.ctx = ctx
        self.id = ref_id

    def _wire(self):
        return {_REF: self.id}

    def __del__(self):
        try:
            self.ctx._free(self.id)
        except Exception:  # noqa: BLE001
            pass

    def __repr__(self):
        return f"ClientObjectRef({self.id.hex()[:12]})"


class _ClientMethod:
    def __init__(self, ctx, actor_id: bytes, name: str):
        self._ctx = ctx
        self._actor = actor_id
        self._name = name

    def remote(self, *args, **kwargs):
        ctx = self._ctx
        r = ctx._call("c_actor_call", {
            "actor": self._actor, "method": self._name,
            "args": [ctx._encode(a) for a in args],
            "kwargs": {k: ctx._encode(v) for k, v in kwargs.items()},
        })
        return ClientObjectRef(ctx, r[_REF])


class ClientActorHandle:
    def __init__(self, ctx, actor_id: bytes):
        self._ctx = ctx
        self._actor_id = actor_id

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClientMethod(self._ctx, self._actor_id, name)


class _ClientActorClass:
    def __init__(self, ctx, cls, opts: dict):
        self._ctx = ctx
        self._blob = cloudpickle.dumps(cls)
        self._opts = opts
        self._actor_opts: dict = {}

    def options(self, **kw) -> "_ClientActorClass":
        out = _ClientActorClass.__new__(_ClientActorClass)
        out._ctx, out._blob = self._ctx, self._blob
        out._opts = dict(self._opts)
        out._actor_opts = {**self._actor_opts, **kw}
        return out

    def remote(self, *args, **kwargs) -> ClientActorHandle:
        ctx = self._ctx
        r = ctx._call("c_actor_new", {
            "opts": self._opts, "actor_opts": self._actor_opts,
            "args": [ctx._encode(a) for a in args],
            "kwargs": {k: ctx._encode(v) for k, v in kwargs.items()},
        }, frames=[self._blob])
        return ClientActorHandle(ctx, r[_ACTOR])


class _ClientRemoteFunction:
    def __init__(self, ctx, fn, opts: dict):
        self._ctx = ctx
        self._blob = cloudpickle.dumps(fn)
        self._opts = opts

    def remote(self, *args, **kwargs):
        ctx = self._ctx
        r = ctx._call("c_task", {
            "opts": self._opts,
            "args": [ctx._encode(a) for a in args],
            "kwargs": {k: ctx._encode(v) for k, v in kwargs.items()},
        }, frames=[self._blob])
        refs = [ClientObjectRef(ctx, e[_REF]) for e in r["refs"]]
        return refs[0] if r["single"] else refs


class ClientContext:
    """The thin client (reference: util/client/worker.py Worker)."""

    def __init__(self, address: str, timeout: float = 30.0):
        self._rpc = RpcClient.shared()
        r = self._rpc.call(address, "client_connect", {}, timeout=timeout)
        self._host = r["host"]
        self._rpc.call(self._host, "c_ping", {}, timeout=timeout)
        self._connected = True

    # -- plumbing --------------------------------------------------------

    def _call(self, method, msg, frames=(), timeout: float = 300.0):
        return self._rpc.call(self._host, method, msg, frames=frames,
                              timeout=timeout)

    def _encode(self, v):
        if isinstance(v, ClientObjectRef):
            return v._wire()
        if isinstance(v, ClientActorHandle):
            return {_ACTOR: v._actor_id}
        return v

    def _free(self, ref_id: bytes):
        if self._connected:
            self._rpc.send_oneway(self._host, "c_free", {"refs": [ref_id]})

    # -- mirrored API ----------------------------------------------------

    def remote(self, _fn=None, **opts):
        def wrap(obj):
            if isinstance(obj, type):
                return _ClientActorClass(self, obj, opts)
            return _ClientRemoteFunction(self, obj, opts)

        return wrap(_fn) if _fn is not None else wrap

    def put(self, value) -> ClientObjectRef:
        head, views, total = ser.serialize(value)
        buf = bytearray(total)
        ser.write_into(memoryview(buf), head, views)
        r = self._call("c_put", {}, frames=[bytes(buf)])
        return ClientObjectRef(self, r[_REF])

    def get(self, refs, timeout: float = 300.0):
        single = isinstance(refs, ClientObjectRef)
        lst = [refs] if single else list(refs)
        value, frames = self._rpc.call_frames(
            self._host, "c_get",
            {"refs": [r._wire() for r in lst], "timeout": timeout,
             "as_list": not single},
            timeout=timeout + 10)
        values = ser.deserialize(memoryview(frames[0]))
        return values[0] if single else values

    def wait(self, refs, num_returns: int = 1, timeout=None):
        r = self._call("c_wait", {
            "refs": [x._wire() for x in refs],
            "num_returns": num_returns, "timeout": timeout,
        }, timeout=(timeout or 300) + 10)
        by_id = {x.id: x for x in refs}
        return ([by_id[e[_REF]] for e in r["ready"]],
                [by_id[e[_REF]] for e in r["pending"]])

    def get_actor(self, name: str) -> ClientActorHandle:
        r = self._call("c_get_actor", {"name": name})
        return ClientActorHandle(self, r[_ACTOR])

    def kill(self, handle: ClientActorHandle):
        self._call("c_kill", {"actor": handle._actor_id})

    def disconnect(self):
        if self._connected:
            self._connected = False
            try:
                self._rpc.send_oneway(self._host, "c_disconnect", {})
            except Exception:  # noqa: BLE001
                pass


def connect(address: str) -> ClientContext:
    """Connect to a cluster's client proxy ("host:port" — the ray://
    scheme prefix is accepted and stripped)."""
    if address.startswith("ray://"):
        address = address[len("ray://"):]
    return ClientContext(address)
