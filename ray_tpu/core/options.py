"""Validation of @remote(...) / .options(...) arguments.

Reference: python/ray/_private/ray_option_utils.py.
"""

from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass
class TaskOptions:
    num_cpus: float | None = None
    num_tpus: float | None = None
    resources: dict[str, float] = dataclasses.field(default_factory=dict)
    # int, or "streaming" for generator tasks (each yield becomes one
    # stream item delivered to the owner as produced — reference:
    # num_returns="streaming", python/ray/_raylet.pyx generator tasks)
    num_returns: int | str = 1
    # streaming only: cap on yielded-but-unconsumed items before the
    # producer blocks (reference: _generator_backpressure_num_objects)
    generator_backpressure_num_objects: int | None = None
    max_retries: int = 3
    retry_exceptions: bool | list = False
    name: str | None = None
    scheduling_strategy: Any = None
    placement_group: Any = None
    placement_group_bundle_index: int = -1
    label_selector: dict[str, str] | None = None
    # {"env_vars": {...}, "working_dir": path} (reference:
    # _private/runtime_env/ — env materialized before the worker starts)
    runtime_env: dict | None = None

    def resource_request(self) -> dict[str, float]:
        req = dict(self.resources)
        req["CPU"] = self.num_cpus if self.num_cpus is not None else 1.0
        if self.num_tpus:
            req["TPU"] = self.num_tpus
        return {k: v for k, v in req.items() if v}


@dataclasses.dataclass
class ActorOptions:
    num_cpus: float | None = None
    num_tpus: float | None = None
    resources: dict[str, float] = dataclasses.field(default_factory=dict)
    name: str | None = None
    namespace: str | None = None
    lifetime: str | None = None  # None | "detached"
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    max_pending_calls: int = -1
    # named concurrency groups: {group: max_concurrency}
    # (reference: concurrency_group_manager.h:34)
    concurrency_groups: dict[str, int] | None = None
    scheduling_strategy: Any = None
    placement_group: Any = None
    placement_group_bundle_index: int = -1
    get_if_exists: bool = False
    label_selector: dict[str, str] | None = None
    runtime_env: dict | None = None

    def resource_request(self) -> dict[str, float]:
        req = dict(self.resources)
        # Actors default to 1 CPU for placement but 0 for running
        # (reference semantics); we keep it simple: reserve what's asked,
        # default 1 CPU.
        req["CPU"] = self.num_cpus if self.num_cpus is not None else 1.0
        if self.num_tpus:
            req["TPU"] = self.num_tpus
        return {k: v for k, v in req.items() if v}


_TASK_KEYS = {f.name for f in dataclasses.fields(TaskOptions)}
_ACTOR_KEYS = {f.name for f in dataclasses.fields(ActorOptions)}
# accepted-but-ignored (compat shims, recorded for parity)
_SOFT_KEYS = {"memory", "accelerator_type", "num_gpus",
              "_metadata", "enable_task_events"}


def _normalize(d: dict) -> dict:
    d = dict(d)
    if d.get("num_gpus"):
        # GPU-shaped requests map onto the TPU resource on this framework.
        d["num_tpus"] = d.pop("num_gpus")
    strat = d.get("scheduling_strategy")
    if strat is not None and hasattr(strat, "placement_group"):
        d["placement_group"] = strat.placement_group
        d["placement_group_bundle_index"] = getattr(
            strat, "placement_group_bundle_index", -1)
    elif strat is not None and hasattr(strat, "to_label_selector"):
        # NodeAffinity / NodeLabel strategies lower to the label
        # scheduler (nodes auto-carry "ray.io/node-id"); explicit
        # selectors win on key conflicts
        sel = dict(strat.to_label_selector())
        sel.update(d.get("label_selector") or {})
        d["label_selector"] = sel
    return d


def task_options(d: dict) -> TaskOptions:
    _check(d, _TASK_KEYS, "task")
    d = _normalize(d)
    nr = d.get("num_returns", 1)
    if isinstance(nr, str) and nr not in ("streaming", "dynamic"):
        raise ValueError(
            f'num_returns must be an int or "streaming", got {nr!r}')
    return TaskOptions(**{k: v for k, v in d.items() if k in _TASK_KEYS})


def actor_options(d: dict) -> ActorOptions:
    _check(d, _ACTOR_KEYS, "actor")
    d = _normalize(d)
    return ActorOptions(**{k: v for k, v in d.items() if k in _ACTOR_KEYS})


def _check(d: dict, allowed: set, kind: str):
    bad = set(d) - allowed - _SOFT_KEYS
    if bad:
        raise ValueError(f"invalid {kind} option(s): {sorted(bad)}")
