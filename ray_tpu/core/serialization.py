"""Object serialization: cloudpickle envelope + out-of-band buffers.

Reference parity: python/ray/_private/serialization.py:122
(SerializationContext — msgpack + pickle5 with out-of-band buffers,
zero-copy numpy). Same idea here: pickle protocol 5 with a
buffer_callback so large array payloads (numpy, and jax arrays via
numpy view) are written separately from the pickle stream and can be
mapped zero-copy out of the shared-memory store on the read side.

Wire format (one contiguous blob):
  [u32 magic][u32 nbuf][u64 pickle_len][u64 buf_len]*nbuf
  [pickle bytes][pad to 64][buf0][pad to 64][buf1]...
"""

from __future__ import annotations

import io
import pickle
import struct
from typing import Any

import cloudpickle

_MAGIC = 0x52545053  # "RTPS"
_ALIGN = 64

# Per-process payload accounting (reference role: object-store metrics).
# pickle_bytes counts bytes that went THROUGH the pickle stream;
# buffer_bytes counts out-of-band payload that bypassed it. The data
# layer's zero-copy claim is auditable as: big numeric blocks move with
# buffer_bytes ≈ payload and pickle_bytes ≈ envelope-only.
STATS = {"pickle_bytes": 0, "buffer_bytes": 0,
         "serialize_calls": 0, "deserialize_calls": 0}


def reset_stats():
    for k in STATS:
        STATS[k] = 0


def _pad(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


_FAST_LEAF = (int, float, bool, bytes, str, type(None), complex)


def _fast_picklable(obj, depth: int = 3) -> bool:
    """True when plain (C-accelerated) pickle provably behaves like
    cloudpickle for this object: builtin scalars, numpy/contiguous
    buffers, and shallow builtin containers of those. Anything that could
    reference user-defined modules (instances, functions, classes) goes
    through cloudpickle so register_pickle_by_value semantics hold."""
    if isinstance(obj, _FAST_LEAF):
        return True
    t = type(obj)
    if t.__module__ == "numpy":
        # object-dtype arrays hold arbitrary python objects that need
        # cloudpickle's by-value semantics
        dt = getattr(obj, "dtype", None)
        return dt is None or dt.kind != "O"
    if depth <= 0:
        return False
    if t is dict:
        return all(isinstance(k, _FAST_LEAF) and _fast_picklable(v, depth - 1)
                   for k, v in obj.items())
    if t in (list, tuple, set, frozenset):
        return all(_fast_picklable(v, depth - 1) for v in obj)
    return False


def serialize(obj: Any) -> tuple[bytes, list[memoryview], int]:
    """Returns (header+pickle bytes, out-of-band buffers, total_size)."""
    buffers: list[pickle.PickleBuffer] = []
    if _fast_picklable(obj):
        payload = pickle.dumps(obj, protocol=5,
                               buffer_callback=buffers.append)
    else:
        payload = cloudpickle.dumps(obj, protocol=5,
                                    buffer_callback=buffers.append)
    views = [b.raw() for b in buffers]
    STATS["serialize_calls"] += 1
    STATS["pickle_bytes"] += len(payload)
    STATS["buffer_bytes"] += sum(v.nbytes for v in views)
    head = struct.pack("<II", _MAGIC, len(views))
    head += struct.pack("<Q", len(payload))
    for v in views:
        head += struct.pack("<Q", v.nbytes)
    total = _pad(len(head) + len(payload))
    for v in views:
        total = _pad(total + v.nbytes)
    return head + payload, views, total


def write_into(buf: memoryview, head_payload: bytes, views: list[memoryview]):
    off = len(head_payload)
    buf[:off] = head_payload
    off = _pad(off)
    for v in views:
        flat = v.cast("B") if v.ndim == 1 else memoryview(bytes(v))
        buf[off:off + flat.nbytes] = flat
        off = _pad(off + flat.nbytes)


def dumps(obj: Any) -> bytes:
    head_payload, views, total = serialize(obj)
    out = bytearray(total)
    write_into(memoryview(out), head_payload, views)
    return bytes(out)


def deserialize_info(buf: memoryview) -> tuple[Any, int]:
    """Like deserialize, also returning the number of out-of-band
    buffers the object graph references (0 ⇒ nothing aliases `buf`)."""
    return _deserialize(buf)


def deserialize(buf: memoryview) -> Any:
    return _deserialize(buf)[0]


def _deserialize(buf: memoryview) -> tuple[Any, int]:
    buf = buf.cast("B") if isinstance(buf, memoryview) else memoryview(buf)
    magic, nbuf = struct.unpack_from("<II", buf, 0)
    if magic != _MAGIC:
        raise ValueError("not a ray_tpu serialized object")
    off = 8
    (plen,) = struct.unpack_from("<Q", buf, off)
    off += 8
    blens = []
    for _ in range(nbuf):
        (bl,) = struct.unpack_from("<Q", buf, off)
        off += 8
        blens.append(bl)
    pickle_bytes = bytes(buf[off:off + plen])
    off = _pad(off + plen)
    oob = []
    for bl in blens:
        # READ-ONLY views: zero-copy arrays alias the shared-memory
        # store — a consumer mutating one in place would silently
        # corrupt the stored object for every other reader (reference:
        # Ray marks zero-copy numpy arrays immutable for this reason).
        # In-place writes now raise; mutate a copy instead.
        oob.append(buf[off:off + bl].toreadonly())
        off = _pad(off + bl)
    STATS["deserialize_calls"] += 1
    STATS["pickle_bytes"] += plen
    STATS["buffer_bytes"] += sum(blens)
    return pickle.loads(pickle_bytes, buffers=oob), len(oob)


def loads(data: bytes | memoryview) -> Any:
    return deserialize(memoryview(data))


def dumps_msg(obj: Any) -> bytes:
    """Serialize a small control-plane message (no out-of-band path).
    Plain (C-accelerated) pickle when the payload is provably made of
    builtin/numpy values — several times faster than cloudpickle on the
    hot path. Anything that might reference user modules (e.g. task args
    holding a driver-__main__ class, which plain pickle would serialize
    by an unresolvable reference) goes through cloudpickle. Sender-side
    try/except is NOT enough: pickling __main__ classes by reference
    succeeds here and fails only at the receiver."""
    if _fast_picklable(obj, depth=8):
        return pickle.dumps(obj, protocol=5)
    return cloudpickle.dumps(obj, protocol=5)


def loads_msg(data: bytes) -> Any:
    return pickle.loads(data)
