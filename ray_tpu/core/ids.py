"""Binary IDs (reference: src/ray/common/id.h)."""

from __future__ import annotations

import os


class BaseID:
    """16-byte random id with hex repr."""

    __slots__ = ("_bytes",)
    SIZE = 16

    def __init__(self, b: bytes):
        if len(b) != self.SIZE:
            raise ValueError(f"{type(self).__name__} needs {self.SIZE} bytes")
        self._bytes = b

    @classmethod
    def random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def from_hex(cls, h: str):
        return cls(bytes.fromhex(h))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return hash((type(self).__name__, self._bytes))

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()[:12]}…)"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class ObjectID(BaseID):
    pass


class TaskID(BaseID):
    pass


class ActorID(BaseID):
    pass


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class JobID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass
