"""Binary IDs (reference: src/ray/common/id.h)."""

from __future__ import annotations

import os
import random as _random
import threading


class _IdRng(threading.local):
    """Per-thread PRNG for id minting, seeded once from the OS pool.

    ``os.urandom`` is a syscall per call and costs ~100us on small
    Firecracker guests (measured: 40% of the task-submit hot path went
    to entropy reads). Ids need uniqueness, not unpredictability: a
    128-bit draw from a per-thread Mersenne generator seeded with
    urandom + pid + thread id keeps the collision math identical while
    staying in user space. Thread-local so concurrent submitters never
    contend (and never share generator state unlocked); fork safety
    comes from the pid in the lazy seed."""

    def __init__(self):
        self.rng = _random.Random(
            os.urandom(16) + os.getpid().to_bytes(8, "little")
            + threading.get_ident().to_bytes(8, "little"))


_id_rng = _IdRng()


def _reseed_after_fork():
    # a forked child inherits the parent thread's generator STATE; a
    # fresh thread-local forces re-seeding (pid differs) on first use
    global _id_rng
    _id_rng = _IdRng()


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reseed_after_fork)


class BaseID:
    """16-byte random id with hex repr."""

    __slots__ = ("_bytes",)
    SIZE = 16

    def __init__(self, b: bytes):
        if len(b) != self.SIZE:
            raise ValueError(f"{type(self).__name__} needs {self.SIZE} bytes")
        self._bytes = b

    @classmethod
    def random(cls):
        return cls(_id_rng.rng.randbytes(cls.SIZE))

    @classmethod
    def from_hex(cls, h: str):
        return cls(bytes.fromhex(h))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return hash((type(self).__name__, self._bytes))

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()[:12]}…)"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class ObjectID(BaseID):
    pass


class TaskID(BaseID):
    pass


class ActorID(BaseID):
    pass


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class JobID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass
