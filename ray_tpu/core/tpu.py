"""TPU accelerator manager — slice identity, topology env, slice-head marker.

Reference parity: ray._private.accelerators.tpu.TPUAcceleratorManager
(python/ray/_private/accelerators/tpu.py:19-44 — pod metadata → slice
identity; :157-170 — TPU_WORKER_ID / TPU_WORKER_HOSTNAMES wiring) and the
`TPU-{pod_type}-head` marker resource placed on worker 0 of each slice so
a single task can target "one per slice".

TPU-first design: slice identity is carried as node LABELS
(`ray.io/tpu-slice`, `ray.io/tpu-worker-id`, ...) that the placement layer
understands natively — STRICT_PACK gangs land on the hosts of ONE slice,
one bundle per host in worker-id order; SPREAD gangs prefer distinct
slices. On real TPU VMs the labels come from the libtpu/GKE environment;
in tests they are asserted via Cluster.add_node(labels=...).
"""

from __future__ import annotations

import os

SLICE_LABEL = "ray.io/tpu-slice"
WORKER_ID_LABEL = "ray.io/tpu-worker-id"
POD_TYPE_LABEL = "ray.io/tpu-pod-type"
TOPOLOGY_LABEL = "ray.io/tpu-topology"


def detect_slice_labels(environ=None) -> dict[str, str]:
    """Slice-identity labels from the TPU VM environment, or {} off-pod.

    Sources, in priority order (reference tpu.py:19-44 reads the GCE
    metadata server / GKE env; this image has zero egress so env vars are
    the seam — real deployments set them via the pod spec):
      TPU_NAME / HOSTNAME        -> slice id
      TPU_WORKER_ID              -> index of this host within the slice
      TPU_ACCELERATOR_TYPE       -> pod type (e.g. "v4-16")
      TPU_TOPOLOGY               -> chip topology (e.g. "2x2x2")
    """
    env = environ if environ is not None else os.environ
    labels: dict[str, str] = {}
    slice_name = env.get("TPU_NAME") or env.get("RAY_TPU_SLICE_NAME")
    if not slice_name:
        return labels
    labels[SLICE_LABEL] = slice_name
    if env.get("TPU_WORKER_ID") is not None:
        labels[WORKER_ID_LABEL] = str(env["TPU_WORKER_ID"])
    if env.get("TPU_ACCELERATOR_TYPE"):
        labels[POD_TYPE_LABEL] = env["TPU_ACCELERATOR_TYPE"]
    if env.get("TPU_TOPOLOGY"):
        labels[TOPOLOGY_LABEL] = env["TPU_TOPOLOGY"]
    return labels


def slice_head_resource(pod_type: str) -> str:
    """Marker resource asserted on worker 0 of a slice (reference
    tpu.py: `TPU-{accelerator_type}-head`) so `resources={"TPU-v4-16-head": 1}`
    schedules exactly one task per slice."""
    return f"TPU-{pod_type}-head"


def head_marker_resources(labels: dict[str, str]) -> dict[str, float]:
    """Extra resources a node should assert given its slice labels."""
    if (labels.get(WORKER_ID_LABEL) == "0"
            and labels.get(POD_TYPE_LABEL)):
        return {slice_head_resource(labels[POD_TYPE_LABEL]): 1.0}
    return {}


def slice_members(nodes) -> dict[str, list]:
    """Group node records (anything with .labels) by slice, each group
    sorted by worker-id so index i == TPU_WORKER_ID i."""
    groups: dict[str, list] = {}
    for n in nodes:
        sl = n.labels.get(SLICE_LABEL)
        if sl is not None:
            groups.setdefault(sl, []).append(n)
    for members in groups.values():
        members.sort(key=_worker_id)
    return groups


def _worker_id(node) -> int:
    try:
        return int(node.labels.get(WORKER_ID_LABEL, 1 << 30))
    except (TypeError, ValueError):
        return 1 << 30


def topology_env(labels: dict[str, str], slice_ips: list[str],
                 worker_id: int | None = None) -> dict[str, str]:
    """The libtpu multi-host env for a worker on a node with these labels
    (reference: backend_executor.py:306-322 shares the slice view across
    colocated workers; tpu.py:157-170 derives id/hostnames)."""
    env: dict[str, str] = {}
    wid = worker_id
    if wid is None and labels.get(WORKER_ID_LABEL) is not None:
        wid = int(labels[WORKER_ID_LABEL])
    if wid is not None:
        env["TPU_WORKER_ID"] = str(wid)
    if slice_ips:
        env["TPU_WORKER_HOSTNAMES"] = ",".join(slice_ips)
    if labels.get(POD_TYPE_LABEL):
        env["TPU_ACCELERATOR_TYPE"] = labels[POD_TYPE_LABEL]
    if labels.get(TOPOLOGY_LABEL):
        env["TPU_TOPOLOGY"] = labels[TOPOLOGY_LABEL]
    if labels.get(SLICE_LABEL):
        env["TPU_NAME"] = labels[SLICE_LABEL]
    return env
