"""Node-local shared-memory object store (Python side).

Reference parity: the plasma store + client
(src/ray/object_manager/plasma/store.h:55, client.h) and the two-tier
store providers (src/ray/core_worker/store_provider/). Design departure:
no store server process — every worker maps the same named shm segment
and calls into the native allocator library (ray_tpu/_native/object_store.cc)
directly under a process-shared lock, so create/get are library calls,
not RPCs.

Two implementations with one interface:
- `SharedMemoryStore`: one big segment + native C++ allocator (preferred).
- `SegmentPerObjectStore`: pure-Python fallback, one shm segment per
  object (slower create, still zero-copy cross-process).
"""

from __future__ import annotations

import ctypes
import mmap
import os
import secrets
import threading

from ray_tpu.core import config as _cfg


def default_capacity() -> int:
    return _cfg.get("OBJECT_STORE_BYTES")


_creation_metrics = None


def _note_create(nbytes: int) -> None:
    """Per-process creation accounting (objects written into the shm
    store by THIS process — workers' numbers reach the cluster /metrics
    page via the nodelet's per-worker scrape). Lazy so importing the
    store never drags in the metrics module."""
    global _creation_metrics
    m = _creation_metrics
    if m is None:
        from ray_tpu.util.metrics import Counter

        m = _creation_metrics = (
            Counter("object_store_created_objects_total",
                    "Objects created in the local shm store"),
            Counter("object_store_created_bytes_total",
                    "Bytes of objects created in the local shm store"))
    m[0].inc()
    m[1].inc(nbytes)
_TABLE_CAPACITY = 65536

_SHM_DIR = "/dev/shm" if os.path.isdir("/dev/shm") else (
    os.environ.get("TMPDIR", "/tmp"))


class ShmSegment:
    """Named shared-memory segment via raw shm file + mmap.

    Deliberately NOT multiprocessing.shared_memory: its resource_tracker
    unlinks 'leaked' segments when any attaching process dies without
    cleanup — a crashing worker would destroy the node's object store
    for everyone (exactly the crash-isolation plasma exists to provide).
    """

    def __init__(self, name: str | None = None, create: bool = False,
                 size: int = 0):
        if create:
            name = name or f"rts_{secrets.token_hex(6)}"
            path = os.path.join(_SHM_DIR, name)
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
            try:
                os.ftruncate(fd, size)
                self._mmap = mmap.mmap(fd, size)
            finally:
                os.close(fd)
        else:
            path = os.path.join(_SHM_DIR, name)
            fd = os.open(path, os.O_RDWR)
            try:
                size = os.fstat(fd).st_size
                self._mmap = mmap.mmap(fd, size)
            finally:
                os.close(fd)
        self.name = name
        self.size = size
        self.buf = memoryview(self._mmap)

    def close(self):
        try:
            self.buf.release()
        except (BufferError, AttributeError):
            pass
        try:
            self._mmap.close()
        except (BufferError, ValueError):
            pass  # exported pointers still alive; mapping dies with process

    def unlink(self):
        try:
            os.unlink(os.path.join(_SHM_DIR, self.name))
        except FileNotFoundError:
            pass


class ObjectStoreFullError(MemoryError):
    pass


def _load_native():
    from ray_tpu import _native

    path = _native.build_library("object_store")
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.rts_init.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint32]
    lib.rts_attached_ok.argtypes = [ctypes.c_void_p]
    lib.rts_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, u64p]
    lib.rts_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rts_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, u64p, u64p]
    lib.rts_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rts_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rts_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rts_stats.argtypes = [ctypes.c_void_p, u64p, u64p, u64p, u64p]
    for f in ("rts_init", "rts_attached_ok", "rts_create", "rts_seal", "rts_get",
              "rts_contains", "rts_release", "rts_delete"):
        getattr(lib, f).restype = ctypes.c_int
    return lib


_native_lib = None
_native_lock = threading.Lock()


def native_lib():
    global _native_lib
    if _native_lib is None:
        with _native_lock:
            if _native_lib is None:
                _native_lib = _load_native() or False
    return _native_lib or None


class SharedMemoryStore:
    """One shm segment, native allocator. All sizes in bytes."""

    def __init__(self, name: str | None = None, capacity: int | None = None,
                 create: bool = True):
        capacity = capacity if capacity is not None else default_capacity()
        self._lib = native_lib()
        if self._lib is None:
            raise RuntimeError("native object store library unavailable")
        if create:
            self._shm = ShmSegment(name=name, create=True, size=capacity)
            self._base = ctypes.addressof(ctypes.c_char.from_buffer(self._shm._mmap))
            if self._lib.rts_init(self._base, self._shm.size, _TABLE_CAPACITY) != 0:
                raise RuntimeError("object store segment too small")
        else:
            self._shm = ShmSegment(name=name, create=False)
            self._base = ctypes.addressof(ctypes.c_char.from_buffer(self._shm._mmap))
            if self._lib.rts_attached_ok(self._base) != 0:
                raise RuntimeError(f"shm segment {name} is not an object store")
        self.name = self._shm.name
        self._owner = create

    # -- raw buffer protocol --------------------------------------------------

    def create(self, oid: bytes, size: int) -> memoryview:
        off = ctypes.c_uint64()
        rc = self._lib.rts_create(self._base, oid, size, ctypes.byref(off))
        if rc == -1:
            raise KeyError(f"object {oid.hex()} already exists")
        if rc == -2:
            raise ObjectStoreFullError(
                f"object of {size} bytes does not fit in store {self.name}")
        if rc != 0:
            raise RuntimeError(f"object table full (rc={rc})")
        _note_create(size)
        o = off.value
        return self._shm.buf[o:o + size]

    def seal(self, oid: bytes):
        if self._lib.rts_seal(self._base, oid) != 0:
            raise KeyError(f"seal: no unsealed object {oid.hex()}")

    def put(self, oid: bytes, data) -> None:
        data = memoryview(data).cast("B")
        buf = self.create(oid, data.nbytes)
        buf[:] = data
        self.seal(oid)
        self._lib.rts_release(self._base, oid)

    def get(self, oid: bytes) -> memoryview | None:
        """Returns a zero-copy view (holds a refcount; call release(oid))."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        if self._lib.rts_get(self._base, oid, ctypes.byref(off), ctypes.byref(size)) != 0:
            return None
        return self._shm.buf[off.value:off.value + size.value]

    def contains(self, oid: bytes) -> bool:
        return bool(self._lib.rts_contains(self._base, oid))

    def release(self, oid: bytes):
        self._lib.rts_release(self._base, oid)

    def delete(self, oid: bytes):
        self._lib.rts_delete(self._base, oid)

    def stats(self) -> dict:
        a = ctypes.c_uint64(); n = ctypes.c_uint64()
        e = ctypes.c_uint64(); c = ctypes.c_uint64()
        self._lib.rts_stats(self._base, ctypes.byref(a), ctypes.byref(n),
                            ctypes.byref(e), ctypes.byref(c))
        return {"bytes_allocated": a.value, "num_objects": n.value,
                "evictions": e.value, "capacity": c.value}

    def close(self):
        self._base = None
        self._shm.close()

    def unlink(self):
        if self._owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass


class SegmentPerObjectStore:
    """Fallback: one shm segment per object, discovered by name. No
    eviction, no allocator — used only when g++ is unavailable."""

    def __init__(self, name: str | None = None, capacity: int = 0, create: bool = True):
        self.name = name or f"rts_{secrets.token_hex(6)}"
        # RPC handler threads (fetch/pull/free) hit one store instance
        # concurrently; the native path is locked in C, this fallback
        # must lock its segment tables itself
        self._lock = threading.Lock()
        self._held: dict[bytes, ShmSegment] = {}  # guarded_by(_lock)
        self._unsealed: dict[bytes, ShmSegment] = {}  # guarded_by(_lock)
        self._owner = create

    def _seg_name(self, oid: bytes) -> str:
        return f"{self.name}_{oid.hex()[:24]}"

    # segment layout: [u8 sealed][7 pad][u64 size][payload]
    _HDR = 16

    def create(self, oid: bytes, size: int) -> memoryview:
        seg = ShmSegment(self._seg_name(oid), create=True,
                         size=max(1, size) + self._HDR)
        seg.buf[0] = 0  # unsealed
        seg.buf[8:16] = size.to_bytes(8, "little")
        with self._lock:
            self._unsealed[oid] = seg
        _note_create(size)
        return seg.buf[self._HDR:self._HDR + size]

    def seal(self, oid: bytes):
        # one critical section: a pop/insert gap would let a racing
        # delete() miss the object (leaking its shm file) and a racing
        # get() attach a duplicate segment this assignment clobbers
        with self._lock:
            seg = self._unsealed.pop(oid, None)
            if seg is None:
                raise KeyError(f"seal: no unsealed object {oid.hex()}")
            seg.buf[0] = 1
            self._held[oid] = seg

    def put(self, oid: bytes, data) -> None:
        data = memoryview(data).cast("B")
        buf = self.create(oid, data.nbytes)
        buf[:] = data
        self.seal(oid)

    def get(self, oid: bytes) -> memoryview | None:
        with self._lock:
            if oid in self._unsealed:
                return None
            seg = self._held.get(oid)
        if seg is None:
            try:
                seg = ShmSegment(self._seg_name(oid), create=False)
            except FileNotFoundError:
                return None
            with self._lock:
                # a racing get may have attached too; keep the winner so
                # the loser's mapping dies with its local reference
                seg = self._held.setdefault(oid, seg)
        if seg.buf[0] != 1:  # not sealed yet
            return None
        size = int.from_bytes(bytes(seg.buf[8:16]), "little")
        return seg.buf[self._HDR:self._HDR + size]

    def contains(self, oid: bytes) -> bool:
        return self.get(oid) is not None

    def release(self, oid: bytes):
        pass

    def delete(self, oid: bytes):
        with self._lock:
            seg = self._held.pop(oid, None)
        if seg is not None:
            try:
                seg.close()
                seg.unlink()
            except FileNotFoundError:
                pass

    def stats(self) -> dict:
        return {"bytes_allocated": 0, "num_objects": len(self._held),
                "evictions": 0, "capacity": 0}

    def close(self):
        for seg in list(self._held.values()) + list(self._unsealed.values()):
            try:
                seg.close()
            except Exception:
                pass

    def unlink(self):
        if self._owner:
            for oid in list(self._held):
                self.delete(oid)


def open_store(name: str | None = None, capacity: int | None = None,
               create: bool = True):
    capacity = capacity if capacity is not None else default_capacity()
    if native_lib() is not None:
        return SharedMemoryStore(name=name, capacity=capacity, create=create)
    return SegmentPerObjectStore(name=name, capacity=capacity, create=create)
