"""Reference: python/ray/runtime_context.py."""

from __future__ import annotations

import dataclasses

from ray_tpu.core.ids import ActorID, JobID, NodeID, TaskID, WorkerID


@dataclasses.dataclass(frozen=True)
class RuntimeContext:
    job_id: JobID
    node_id: NodeID
    worker_id: WorkerID
    actor_id: ActorID | None = None
    task_id: TaskID | None = None
    namespace: str = "default"
    placement_group_id: str | None = None

    def get_job_id(self) -> str:
        return self.job_id.hex()

    def get_node_id(self) -> str:
        return self.node_id.hex()

    def get_actor_id(self) -> str | None:
        return self.actor_id.hex() if self.actor_id else None

    def get_task_id(self) -> str | None:
        return self.task_id.hex() if self.task_id else None

    def get_worker_id(self) -> str:
        return self.worker_id.hex()
