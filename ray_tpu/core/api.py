"""Public task/actor/object API.

Reference parity: ray.init (python/ray/_private/worker.py:1275),
@ray.remote (python/ray/remote_function.py:41, python/ray/actor.py:602),
ray.get/put/wait (worker.py:2636,2804,2869).
"""

from __future__ import annotations

import functools
import inspect
import threading
from typing import Any, Sequence

from ray_tpu.core import options as _opt
from ray_tpu.core.ids import ActorID, ObjectID

_runtime = None
_runtime_lock = threading.RLock()


# ---------------------------------------------------------------- ObjectRef


class ObjectRef:
    """A future for a task result or `put` value. Owned by the worker that
    created it (reference: ownership model, core_worker/reference_count.h).

    Each live ObjectRef instance holds one local reference on the
    object's store slot; when the last instance is garbage-collected the
    runtime may free the value (reference: ReferenceCounter local refs,
    core_worker/reference_count.h:66)."""

    __slots__ = ("id", "owner", "__weakref__")

    def __init__(self, id: ObjectID, owner: str | None = None):
        self.id = id
        self.owner = owner
        rt = _runtime
        if rt is not None:
            rt._incref(id, owner)

    def __del__(self):
        rt = _runtime
        if rt is not None:
            try:
                rt._decref(self.id, self.owner)
            except Exception:
                pass

    def hex(self) -> str:
        return self.id.hex()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()[:12]}…)"

    def __reduce__(self):
        return (ObjectRef, (self.id, self.owner))

    def future(self):
        """concurrent.futures.Future view of this ref."""
        return _global_runtime().as_future(self)

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()


class ObjectRefGenerator:
    """Handle for a streaming-generator task (`num_returns="streaming"`).

    Iterating yields one ObjectRef per value the remote generator yields,
    AS the producer yields them — the consumer does not wait for the task
    to finish (reference: ObjectRefStream,
    src/ray/core_worker/task_manager.h:104 and the ObjectRefGenerator in
    python/ray/_raylet.pyx). Picklable: a borrower process iterates by
    asking the stream's owner for each index."""

    __slots__ = ("_task_id", "_owner", "_index", "_done", "_handed_off",
                 "__weakref__")

    def __init__(self, task_id: bytes, owner: str):
        self._task_id = task_id
        self._owner = owner
        self._index = 0
        self._done = False
        self._handed_off = False

    def __iter__(self):
        return self

    def __next__(self) -> "ObjectRef":
        return self._next_sync(None)

    def _next_sync(self, timeout: float | None = None) -> "ObjectRef":
        """Like __next__ but with a timeout (reference:
        ObjectRefGenerator._next_sync)."""
        if self._done:
            raise StopIteration
        try:
            ref = _global_runtime().stream_next(
                self._task_id, self._owner, self._index, timeout=timeout)
        except StopIteration:
            self._done = True
            raise
        self._index += 1
        return ref

    def __aiter__(self):
        return self

    async def __anext__(self):
        import asyncio

        try:
            return await asyncio.get_running_loop().run_in_executor(
                None, self.__next__)
        except StopIteration:
            raise StopAsyncIteration from None

    def close(self):
        """Early termination: tells the owner to drop unconsumed items
        and cancel the producer (reference: stream deletion GC,
        task_manager.h:212)."""
        if self._done:
            return
        self._done = True
        rt = _runtime
        if rt is not None:
            try:
                rt.stream_close(self._task_id, self._owner)
            except Exception:
                pass

    def __del__(self):
        # a handle that was pickled away handed consumption to the
        # borrower copy: closing here would silently truncate its
        # iteration (the borrower's close/exhaustion does the GC instead)
        if not self._handed_off:
            self.close()

    def __reduce__(self):
        self._handed_off = True
        g = (_rebuild_generator, (self._task_id, self._owner, self._index))
        return g

    def __repr__(self):
        return (f"ObjectRefGenerator({self._task_id.hex()[:12]}…, "
                f"index={self._index})")


def _rebuild_generator(task_id: bytes, owner: str, index: int):
    g = ObjectRefGenerator(task_id, owner)
    g._index = index
    return g


# ---------------------------------------------------------------- init


def init(
    address: str | None = None,
    *,
    num_cpus: float | None = None,
    num_tpus: float | None = None,
    resources: dict[str, float] | None = None,
    local_mode: bool = False,
    namespace: str | None = None,
    labels: dict[str, str] | None = None,
    ignore_reinit_error: bool = False,
    **kwargs,
):
    """Connect to (or boot) a cluster. With no address, starts a head node
    in-process-tree; `local_mode=True` runs everything in this process
    (threads) for debugging — same semantics, no isolation."""
    global _runtime
    with _runtime_lock:
        if _runtime is not None:
            if ignore_reinit_error:
                return _runtime.context_info()
            raise RuntimeError("ray_tpu.init() called twice; use shutdown() first")
        from ray_tpu.core.runtime import make_runtime

        _runtime = make_runtime(
            address=address,
            num_cpus=num_cpus,
            num_tpus=num_tpus,
            resources=resources or {},
            local_mode=local_mode,
            namespace=namespace,
            labels=labels or {},
            **kwargs,
        )
        return _runtime.context_info()


def shutdown():
    global _runtime
    with _runtime_lock:
        if _runtime is not None:
            _runtime.shutdown()
            _runtime = None


def is_initialized() -> bool:
    return _runtime is not None


def _global_runtime():
    global _runtime
    if _runtime is None:
        with _runtime_lock:
            if _runtime is None:
                init()
    return _runtime


def _set_runtime(rt):
    """Internal: workers install their runtime at startup."""
    global _runtime
    _runtime = rt


# ---------------------------------------------------------------- core verbs


def put(value: Any) -> ObjectRef:
    return _global_runtime().put(value)


def get(refs, timeout: float | None = None):
    single = isinstance(refs, ObjectRef)
    if single:
        refs = [refs]
    elif not isinstance(refs, (list, tuple)):
        raise TypeError(f"get() expects ObjectRef or list, got {type(refs)}")
    vals = _global_runtime().get(list(refs), timeout=timeout)
    return vals[0] if single else vals


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: float | None = None,
    fetch_local: bool = True,
):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    refs = list(refs)
    if len(set(refs)) != len(refs):
        raise ValueError("wait() got duplicate ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns > number of refs")
    return _global_runtime().wait(
        refs, num_returns=num_returns, timeout=timeout, fetch_local=fetch_local
    )


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    return _global_runtime().cancel(ref, force=force, recursive=recursive)


def kill(actor: "ActorHandle", *, no_restart: bool = True):
    if not isinstance(actor, ActorHandle):
        raise TypeError("kill() expects an ActorHandle")
    return _global_runtime().kill_actor(actor._actor_id, no_restart=no_restart)


def get_actor(name: str, namespace: str | None = None) -> "ActorHandle":
    return _global_runtime().get_named_actor(name, namespace)


def nodes() -> list[dict]:
    return _global_runtime().nodes()


def cluster_resources() -> dict[str, float]:
    return _global_runtime().cluster_resources()


def available_resources() -> dict[str, float]:
    return _global_runtime().available_resources()


def get_runtime_context():
    return _global_runtime().runtime_context()


def timeline(filename: str | None = None):
    """Export task events as a Chrome trace (reference: `ray timeline`)."""
    return _global_runtime().timeline(filename)


# ---------------------------------------------------------------- @remote


def remote(*args, **kwargs):
    """Decorator turning a function into a RemoteFunction or a class into
    an ActorClass. Usable bare (@remote) or with options
    (@remote(num_cpus=2, resources={"TPU": 1}))."""
    if len(args) == 1 and not kwargs and (inspect.isfunction(args[0]) or inspect.isclass(args[0])):
        return _make_remote(args[0], {})
    if args:
        raise TypeError("@remote takes keyword options only")

    def wrap(obj):
        return _make_remote(obj, kwargs)

    return wrap


def method(**kwargs):
    """Per-method options on an actor class (reference: ray.method,
    python/ray/actor.py:116)."""

    def wrap(fn):
        fn.__ray_tpu_method_options__ = kwargs
        return fn

    return wrap


def _make_remote(obj, opts: dict):
    if inspect.isclass(obj):
        return ActorClass(obj, _opt.actor_options(opts))
    return RemoteFunction(obj, _opt.task_options(opts))


class RemoteFunction:
    """Reference: python/ray/remote_function.py:41."""

    def __init__(self, fn, opts: _opt.TaskOptions):
        self._fn = fn
        self._opts = opts
        functools.update_wrapper(self, fn)

    def remote(self, *args, **kwargs):
        return _global_runtime().submit_task(self._fn, args, kwargs, self._opts)

    def options(self, **opts):
        merged = {**_asdict_nondefault(self._opts), **opts}
        return RemoteFunction(self._fn, _opt.task_options(merged))

    def __call__(self, *a, **kw):
        raise TypeError(
            f"remote function {self._fn.__name__} cannot be called directly; "
            f"use .remote()"
        )


class ActorClass:
    """Reference: python/ray/actor.py:602."""

    def __init__(self, cls, opts: _opt.ActorOptions):
        self._cls = cls
        self._opts = opts
        functools.update_wrapper(self, cls, updated=[])

    def remote(self, *args, **kwargs) -> "ActorHandle":
        return _global_runtime().create_actor(self._cls, args, kwargs, self._opts)

    def options(self, **opts):
        merged = {**_asdict_nondefault(self._opts), **opts}
        return ActorClass(self._cls, _opt.actor_options(merged))

    def __call__(self, *a, **kw):
        raise TypeError(
            f"actor class {self._cls.__name__} cannot be instantiated directly; "
            f"use .remote()"
        )


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, opts: dict):
        self._handle = handle
        self._name = name
        self._opts = opts

    def remote(self, *args, **kwargs):
        return _global_runtime().submit_actor_task(
            self._handle._actor_id, self._name, args, kwargs, self._opts
        )

    def bind(self, *args):
        """Bind into a compiled DAG (reference: ray.dag —
        actor.method.bind(node), dag/class_node.py)."""
        from ray_tpu.dag import ClassMethodNode

        return ClassMethodNode(self._handle, self._name, args)

    def options(self, **opts):
        return ActorMethod(self._handle, self._name, {**self._opts, **opts})


class ActorHandle:
    """Reference: python/ray/actor.py:1265."""

    def __init__(self, actor_id: ActorID, method_meta: dict[str, dict] | None = None):
        self._actor_id = actor_id
        self._method_meta = method_meta or {}

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name, self._method_meta.get(name, {}))

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()[:12]}…)"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._method_meta))

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return isinstance(other, ActorHandle) and other._actor_id == self._actor_id


def _asdict_nondefault(opts) -> dict:
    import dataclasses

    out = {}
    for f in dataclasses.fields(opts):
        v = getattr(opts, f.name)
        default = f.default if f.default is not dataclasses.MISSING else (
            f.default_factory() if f.default_factory is not dataclasses.MISSING else None
        )
        if v != default:
            out[f.name] = v
    return out
